"""Parameter groups — per-group hyperparameters over pytree paths.

The reference's optimizers operate over ``param_groups``: the user
partitions parameters into lists, each with its own lr/weight_decay/eps
(``apex/optimizers/fused_adam.py:50-146`` loops groups; amp keeps the
partition working through its surgery and supports adding a group
mid-training, ``apex/amp/_process_optimizer.py:333-407``).

In a pytree world the partition is declared, not hand-built: a group is a
*path predicate* plus hyperparameter overrides, and every optimizer
resolves leaves to groups by matching the leaf's key path.  A group spec
is a plain dict::

    {"match": r"(bias|LayerNorm)", "weight_decay": 0.0, "lr": 1e-4}

``match`` is a regex (searched against ``jax.tree_util.keystr`` of the
leaf path) or a callable ``f(path_str) -> bool``.  Groups are checked in
order; the first match wins; unmatched leaves fall into the implicit
default group 0, whose hyperparameters are the optimizer's constructor
arguments.  This is the BERT no-decay recipe in one line, and it survives
checkpoint/restore because the partition is a function of paths, not of
object identity.

``labels``/``masks`` adapt the same declaration to plain optax optimizers
via ``optax.multi_transform`` for the amp wrapped-optimizer path.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

Pytree = Any
GroupSpec = Dict[str, Any]


def validate_specs(group_specs: Sequence[GroupSpec],
                   allowed: Sequence[str], owner: str) -> None:
    """Reject group specs with unknown override keys: a typo'd
    ``weight_deacy`` or a key the target optimizer never reads would
    otherwise be silently ignored (the no-decay recipe quietly not
    applying is the worst kind of bug)."""
    allowed_set = set(allowed) | {"match"}
    for spec in group_specs:
        if "match" not in spec:
            raise ValueError(f"{owner} param group {spec!r} has no 'match'")
        unknown = set(spec) - allowed_set
        if unknown:
            raise ValueError(
                f"{owner} param group {spec!r} has unsupported keys "
                f"{sorted(unknown)}; supported overrides: "
                f"{sorted(allowed_set - {'match'})}")


def match_fn(match) -> Callable[[str], bool]:
    """Compile a group spec's ``match`` field into a path predicate."""
    if callable(match):
        return match
    rx = re.compile(match)
    return lambda path: rx.search(path) is not None


def leaf_paths(tree: Pytree) -> Tuple[str, ...]:
    """keystr path for every leaf, in tree-flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return tuple(jax.tree_util.keystr(kp) for kp, _ in flat)


def resolve_group_ids(tree: Pytree,
                      group_specs: Sequence[GroupSpec]) -> Tuple[int, ...]:
    """Group id per leaf (tree order): 0 = default, i+1 = group_specs[i].
    First matching spec wins."""
    fns = [match_fn(s["match"]) for s in group_specs]
    ids = []
    for path in leaf_paths(tree):
        gid = 0
        for i, f in enumerate(fns):
            if f(path):
                gid = i + 1
                break
        ids.append(gid)
    return tuple(ids)


def group_hparams(defaults: Dict[str, Any],
                  group_specs: Sequence[GroupSpec]) -> List[Dict[str, Any]]:
    """Resolved hyperparameter dict per group: [default, *overridden]."""
    out = [dict(defaults)]
    for spec in group_specs:
        hp = dict(defaults)
        hp.update({k: v for k, v in spec.items() if k != "match"})
        out.append(hp)
    return out


def hparam_for_path(path: str, defaults: Dict[str, Any],
                    group_specs: Sequence[GroupSpec]) -> Dict[str, Any]:
    """Resolved hyperparameters for one leaf path (per-leaf optimizers)."""
    for spec in group_specs:
        if match_fn(spec["match"])(path):
            hp = dict(defaults)
            hp.update({k: v for k, v in spec.items() if k != "match"})
            return hp
    return dict(defaults)


def labels(tree: Pytree, group_specs: Sequence[GroupSpec]) -> Pytree:
    """Pytree of string labels ("group0".."groupN") shaped like ``tree`` —
    the ``param_labels`` argument of ``optax.multi_transform``."""
    ids = resolve_group_ids(tree, group_specs)
    it = iter(ids)
    return jax.tree_util.tree_map(lambda _: f"group{next(it)}", tree)


def masks(tree: Pytree,
          group_specs: Sequence[GroupSpec]) -> List[Pytree]:
    """Boolean mask pytree per group (incl. default group 0) — for
    ``optax.masked`` style composition."""
    ids = resolve_group_ids(tree, group_specs)
    n_groups = len(group_specs) + 1
    out = []
    for g in range(n_groups):
        it = iter(ids)
        out.append(jax.tree_util.tree_map(lambda _: next(it) == g, tree))
    return out


def multi_transform(make_opt: Callable[..., Any], defaults: Dict[str, Any],
                    group_specs: Sequence[GroupSpec], tree: Pytree):
    """Build ``optax.multi_transform`` applying ``make_opt(**hparams)``
    per group — param groups for ANY optax optimizer (the amp
    wrapped-optimizer path, reference ``_process_optimizer.py:333-407``).

    Example::

        opt = multi_transform(optax.adamw, {"learning_rate": 1e-3,
                                            "weight_decay": 0.01},
                              [{"match": r"bias", "weight_decay": 0.0}],
                              params)
    """
    import optax
    hps = group_hparams(defaults, group_specs)
    transforms = {f"group{i}": make_opt(**hp) for i, hp in enumerate(hps)}
    return optax.multi_transform(transforms, labels(tree, group_specs))
