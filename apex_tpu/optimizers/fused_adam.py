"""FusedAdam — Adam over flat parameter buffers with a Pallas TPU kernel.

Re-design of the reference ``apex/optimizers/fused_adam.py`` (``FusedAdam``
at :5) and its CUDA kernel ``csrc/fused_adam_cuda_kernel.cu:48-84``. The
update math is identical:

    g     = grad / combined_scale
    m     = beta1*m + (1-beta1)*g
    v     = beta2*v + (1-beta2)*g*g
    denom = sqrt(v) + eps              (eps outside sqrt, mode 1)
          | sqrt(v + eps)              (eps inside sqrt,  mode 0)
    p    -= step_size * (m/denom + weight_decay*p)

with ``step_size = lr * sqrt(1-beta2^t)/(1-beta1^t)`` when bias correction
is on (host-side fold in the reference, ``fused_adam_cuda.cpp:112-119``;
traced arithmetic here). Grad-norm clipping folds into ``combined_scale``
exactly as ``fused_adam.py:98-104``.

TPU design: instead of one CUDA launch per parameter tensor (reference
loops params at ``fused_adam.py:133-146``), one Pallas kernel updates every
parameter. The moments m/v live as contiguous flat fp32 buffers in the
optimizer state for the life of training; params and grads are concatenated
into matching flat buffers at each step (a fused copy under jit) and the
result is sliced back to the pytree layout. A pure-jnp path
(``use_pallas=False``) provides the CPU fallback and the parity oracle.

The optax ``GradientTransformation`` protocol (init/update) is also
provided so FusedAdam slots into ``amp.initialize`` as the inner optimizer.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops.flatten import (FlatSpec, flatten, flatten_grouped,
                                  flatten_like, unflatten)
from apex_tpu.ops.pallas_utils import (LANES, on_tpu, pad_to_tiles,
                                       pallas_auto_gate, untile)
from apex_tpu.optimizers.param_groups import (group_hparams,
                                              resolve_group_ids)

Pytree = Any


class FusedAdamState(NamedTuple):
    step: jax.Array      # i32
    m: jax.Array         # f32 flat
    v: jax.Array         # f32 flat
    spec: FlatSpec       # static pytree metadata (hashable aux data)


# ``spec`` is static layout metadata, not an array: register the state so it
# jits cleanly with spec carried as aux data.
jax.tree_util.register_pytree_node(
    FusedAdamState,
    lambda s: ((s.step, s.m, s.v), s.spec),
    lambda spec, kids: FusedAdamState(kids[0], kids[1], kids[2], spec),
)


def _adam_math(p, m, v, g, step_size, beta1, beta2, eps, combined_scale,
               weight_decay, eps_inside_sqrt: bool, keep=None):
    """Shared update math (jnp ops — usable inside and outside Pallas).

    ``keep`` (f32 scalar 1.0/0.0, or None = unconditional): amp's
    overflow->skip-step protocol fused into the update itself. The
    wrapper-level alternative — ``jnp.where`` selects over params AND
    m/v AFTER the step (amp/optimizer.py) — re-reads and re-writes every
    flat buffer (~0.9 GB/step at ResNet-50 scale, measured on v5e,
    BENCH_NOTES.md); in-kernel the select fuses into the aliased write
    and costs nothing. ``jnp.where`` rather than an arithmetic blend: an
    overflowed g carries inf/nan and ``0 * nan`` would still be nan."""
    g = g / combined_scale
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    if eps_inside_sqrt:
        denom = jnp.sqrt(v_new + eps)
    else:
        denom = jnp.sqrt(v_new) + eps
    update = m_new / denom + weight_decay * p
    p_new = p - step_size * update
    if keep is not None:
        tag = keep > 0.5
        p_new = jnp.where(tag, p_new, p)
        m_new = jnp.where(tag, m_new, m)
        v_new = jnp.where(tag, v_new, v)
    return p_new, m_new, v_new


def _adam_kernel(scalars_ref, p_ref, m_ref, v_ref, g_ref,
                 p_out, m_out, v_out, *, eps_inside_sqrt: bool):
    step_size = scalars_ref[0]
    beta1 = scalars_ref[1]
    beta2 = scalars_ref[2]
    eps = scalars_ref[3]
    combined_scale = scalars_ref[4]
    weight_decay = scalars_ref[5]
    keep = scalars_ref[6]
    p_new, m_new, v_new = _adam_math(
        p_ref[:], m_ref[:], v_ref[:], g_ref[:], step_size, beta1, beta2,
        eps, combined_scale, weight_decay, eps_inside_sqrt, keep=keep)
    p_out[:] = p_new
    m_out[:] = m_new
    v_out[:] = v_new


@functools.partial(jax.jit, static_argnames=("eps_inside_sqrt", "rows",
                                             "interpret"))
def _adam_flat_pallas(p, m, v, g, scalars, *, eps_inside_sqrt: bool,
                      rows: int = 512, interpret: bool = False):
    """Run the fused kernel over tiled flat fp32 buffers."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = p.shape[0]
    pt, _ = pad_to_tiles(p, rows)
    mt, _ = pad_to_tiles(m, rows)
    vt, _ = pad_to_tiles(v, rows)
    gt, _ = pad_to_tiles(g, rows)
    total_rows = pt.shape[0]
    grid = (total_rows // rows,)
    tile_spec = pl.BlockSpec((rows, LANES), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct(pt.shape, jnp.float32)
    kernel = functools.partial(_adam_kernel, eps_inside_sqrt=eps_inside_sqrt)
    p2, m2, v2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            tile_spec, tile_spec, tile_spec, tile_spec,
        ],
        out_specs=[tile_spec, tile_spec, tile_spec],
        out_shape=[out_shape, out_shape, out_shape],
        # update p/m/v in place (reference kernel mutates in place too,
        # fused_adam_cuda_kernel.cu): halves the HBM footprint of the step
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(scalars, pt, mt, vt, gt)
    return untile(p2, n), untile(m2, n), untile(v2, n)


class FusedAdam:
    """Apex-compatible FusedAdam (reference ``fused_adam.py:5-49``).

    Arguments match the reference: ``lr``, ``bias_correction``, ``betas``,
    ``eps``, ``eps_inside_sqrt``, ``weight_decay``, ``max_grad_norm``
    (folded into the combined scale at step time), ``amsgrad`` rejected
    exactly like the reference (:46).

    ``param_groups``: optional list of path-predicate group specs
    (``optimizers.param_groups``) with per-group ``lr`` / ``weight_decay``
    / ``eps`` / ``betas`` / ``max_grad_norm`` overrides — the pytree
    analog of the reference's per-group loop (``fused_adam.py:50-146``).
    At ``init`` each group's leaves are laid out as one contiguous slice
    of the flat buffer, so the grouped step is still one Pallas launch per
    group over flat memory (no per-leaf launches, no extra HBM traffic).

    ``use_pallas``: None = auto (Pallas on TPU, jnp elsewhere).

    ``pad_to``: zero-pad the flat state buffers to a length multiple, so
    they shard evenly across mesh axes whose size divides it (ZeRO-1
    layout via ``parallel.shard_optimizer_state``; no reference analog —
    its flat masters are replicated per rank,
    ``apex/optimizers/fp16_optimizer.py:61-67``). Default 128 covers
    every power-of-two axis up to 128 at the cost of <=127 extra
    elements; the padding tail is zeros and stays zeros.

    ``layout``: where the moments live and how the update runs.

    - ``"flat"`` (default): contiguous flat fp32 m/v + the Pallas kernel
      — the reference's flat-buffer architecture, ZeRO-shardable as two
      arrays, one kernel for the whole model.
    - ``"tree"``: m/v as pytrees mirroring the params, updated per leaf
      by the SAME math under jit. On TPU, XLA fuses each leaf's
      unscale+update+skip-select into one HBM pass and kernel-launch
      count is irrelevant (no CUDA-style per-launch cost, the thing the
      reference's multi_tensor_apply exists to amortize) — while the
      flat layout pays a params+grads concat, a pad, and an unflatten
      slice-back EVERY step (~1.5-2 ms at ResNet-50 scale on v5e,
      xprof-measured, BENCH_NOTES.md). Same update semantics, group
      support, and skip protocol; state is per-leaf (like optax), so
      checkpoints are layout-specific.

    Tensor-parallel params need ``layout="tree"``: the flat layout's
    whole-model concat cannot preserve per-param Megatron placements
    (``parallel.gpt_tp_rules`` / ``bert_tp_rules``), so a flat-layout
    step gathers the TP shards and emits replicated params — numerics
    are right but the placement is silently gone after one step (found
    by driving a dp x tp x pp train loop). The tree layout updates each
    leaf in place, so shardings propagate through. Flat + ZeRO over the
    DATA axis (``with_zero``) is unaffected — that sharding is applied
    to the flat buffers themselves.
    """

    # AmpOptimizer.apply_gradients: the overflow->skip select runs inside
    # the fused kernel (step(..., skip=...)) instead of as wrapper-level
    # tree-selects over params + state
    supports_fused_skip = True

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 eps_inside_sqrt: bool = False, weight_decay: float = 0.0,
                 max_grad_norm: float = 0.0, amsgrad: bool = False,
                 use_pallas: Optional[bool] = None, param_groups=None,
                 pad_to: int = 128, layout: str = "flat"):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")
        if layout not in ("flat", "tree"):
            raise ValueError(f"layout must be 'flat' or 'tree', "
                             f"got {layout!r}")
        self.layout = layout
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.eps_inside_sqrt = eps_inside_sqrt
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.use_pallas = use_pallas
        self.pad_to = pad_to
        self._zero = None  # (mesh, axis) once with_zero() configures it
        self.param_groups = list(param_groups) if param_groups else []
        if self.param_groups:
            from apex_tpu.optimizers.param_groups import validate_specs
            validate_specs(self.param_groups, self._defaults().keys(),
                           "FusedAdam")

    def _defaults(self):
        return {"lr": self.lr, "betas": self.betas, "eps": self.eps,
                "weight_decay": self.weight_decay,
                "max_grad_norm": self.max_grad_norm}

    def _clone(self, **overrides) -> "FusedAdam":
        kw = dict(lr=self.lr, bias_correction=self.bias_correction,
                  betas=self.betas, eps=self.eps,
                  eps_inside_sqrt=self.eps_inside_sqrt,
                  weight_decay=self.weight_decay,
                  max_grad_norm=self.max_grad_norm,
                  use_pallas=self.use_pallas,
                  param_groups=self.param_groups, pad_to=self.pad_to,
                  layout=self.layout)
        kw.update(overrides)
        new = FusedAdam(**kw)
        new._zero = self._zero
        return new

    def with_zero(self, mesh, axis: str = "data",
                  min_shard_elems: Optional[int] = None) -> "FusedAdam":
        """Return a copy whose Pallas update runs shard-local over ``axis``.

        ZeRO-1 composition (``parallel.shard_optimizer_state``): the raw
        ``pallas_call`` lowers to a ``tpu_custom_call`` that carries no
        GSPMD partitioning rule, so under a sharded m/v state XLA would
        re-gather the flat buffers — defeating the memory win.  Configured
        with the mesh, the kernel is wrapped in ``jax.shard_map`` over the
        ZeRO axis instead: each device updates only its 1/n slice of the
        flat buffers (the update is elementwise, so no collectives), and
        the sharded placement survives the step.  The buffers are padded
        to ``pad_to`` (default 128) at ``init`` precisely so they divide
        evenly.

        ``axis`` and ``min_shard_elems`` must match what was given to
        ``parallel.shard_optimizer_state`` — the kernel's out_specs SET
        the output placement, so a mismatch would reshard the buffers
        every step.  Buffers below the threshold (default
        ``axis_size * 128`` elements, same as that helper) take the jnp
        update and stay replicated, matching its placement decision.

        ``layout="tree"`` needs no configuration at all (the per-leaf
        jnp update is GSPMD-partitionable and simply follows each
        leaf's placement); this method is then a no-op clone kept for
        call-site symmetry.
        """
        if min_shard_elems is None:
            min_shard_elems = mesh.shape[axis] * 128
        new = self._clone()
        new._zero = (mesh, axis, min_shard_elems)
        return new

    # -- optax GradientTransformation protocol ---------------------------
    def init(self, params: Pytree) -> FusedAdamState:
        if self.layout == "tree":
            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            return FusedAdamState(step=jnp.asarray(0, jnp.int32),
                                  m=zeros,
                                  v=jax.tree_util.tree_map(jnp.copy, zeros),
                                  spec=None)
        if self.param_groups:
            ids = resolve_group_ids(params, self.param_groups)
            # number groups densely 0..n_specs even if some are empty so
            # group_bounds aligns with group_hparams
            ids = tuple(ids)
            flat, spec = flatten_grouped(
                params, ids, dtype=jnp.float32, pad_to=self.pad_to)
            n_groups = len(self.param_groups) + 1
            if len(spec.group_bounds) < n_groups:  # trailing empty groups
                bounds = list(spec.group_bounds)
                while len(bounds) < n_groups:
                    bounds.append((spec.total, 0))
                spec = spec._replace(group_bounds=tuple(bounds))
        else:
            flat, spec = flatten(params, dtype=jnp.float32,
                                 pad_to=self.pad_to)
        return FusedAdamState(step=jnp.asarray(0, jnp.int32),
                              m=jnp.zeros_like(flat),
                              v=jnp.zeros_like(flat), spec=spec)

    # -- runtime group surgery -------------------------------------------
    def add_param_group(self, state: FusedAdamState, params: Pytree,
                        match, **overrides):
        """Mid-training group addition (reference
        ``_process_optimizer.py:333-407`` / ``test_add_param_group``):
        returns ``(new_optimizer, new_state)`` where leaves matching
        ``match`` now use ``overrides`` and every leaf keeps its Adam
        moments.  ``params`` may also contain NEW leaves (the reference's
        actual use: unfreezing fresh params) — their moments start at
        zero."""
        from apex_tpu.optimizers.param_groups import leaf_paths

        # PREPEND: group resolution is first-match-wins, so the newest
        # declaration must come first to actually override leaves an
        # earlier group already matched
        new_opt = self._clone(
            param_groups=[dict(match=match, **overrides)]
            + self.param_groups)
        if self.layout == "tree":
            # per-leaf state: carry moments over by path, zeros for new
            # leaves — no flat-layout surgery needed
            old = {}
            for path, m_leaf, v_leaf in zip(
                    leaf_paths(state.m),
                    jax.tree_util.tree_leaves(state.m),
                    jax.tree_util.tree_leaves(state.v)):
                old[path] = (m_leaf, v_leaf)
            fresh = new_opt.init(params)
            paths = leaf_paths(params)

            def carry(which, tree):
                leaves = jax.tree_util.tree_leaves(tree)
                out = []
                for path, leaf in zip(paths, leaves):
                    prev = old.get(path)
                    out.append(prev[which] if prev is not None and
                               prev[which].shape == leaf.shape else leaf)
                return jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(tree), out)
            return new_opt, FusedAdamState(
                step=state.step, m=carry(0, fresh.m), v=carry(1, fresh.v),
                spec=None)
        new_state = new_opt.init(params)
        # carry over moments by leaf path (old layout -> new layout)
        old_m = unflatten(state.m, state.spec, cast_back=False)
        old_v = unflatten(state.v, state.spec, cast_back=False)
        old = {}
        for path, m_leaf, v_leaf in zip(
                leaf_paths(old_m), jax.tree_util.tree_leaves(old_m),
                jax.tree_util.tree_leaves(old_v)):
            old[path] = (m_leaf, v_leaf)

        new_paths = leaf_paths(params)
        m_leaves = list(jax.tree_util.tree_leaves(
            unflatten(new_state.m, new_state.spec, cast_back=False)))
        v_leaves = list(jax.tree_util.tree_leaves(
            unflatten(new_state.v, new_state.spec, cast_back=False)))
        for i, path in enumerate(new_paths):
            if path in old and old[path][0].shape == m_leaves[i].shape:
                m_leaves[i], v_leaves[i] = old[path]
        treedef = new_state.spec.treedef
        m_tree = jax.tree_util.tree_unflatten(treedef, m_leaves)
        v_tree = jax.tree_util.tree_unflatten(treedef, v_leaves)
        return new_opt, FusedAdamState(
            step=state.step,
            m=flatten_like(m_tree, new_state.spec, dtype=jnp.float32,
                           pad_to=self.pad_to),
            v=flatten_like(v_tree, new_state.spec, dtype=jnp.float32,
                           pad_to=self.pad_to),
            spec=new_state.spec)

    def update(self, grads: Pytree, state: FusedAdamState,
               params: Optional[Pytree] = None, *, scale=1.0,
               grad_norm=None, skip=None):
        """optax-style: returns (updates, new_state) where
        ``new_params = params + updates``.  With ``skip`` (bool scalar)
        true, updates are zero and the state is unchanged — the
        skip-step select runs inside the fused kernel (zero extra HBM
        traffic) instead of over materialized trees."""
        if params is None:
            raise ValueError("FusedAdam.update requires params")
        if self.layout == "tree":
            p2, new_state = self._step_tree(params, grads, state, scale,
                                            grad_norm, skip=skip)
            updates = jax.tree_util.tree_map(
                lambda n, p: (n - p.astype(n.dtype)).astype(p.dtype),
                p2, params)
            return updates, new_state
        new_flat, new_state, old_flat = self._step_flat(
            params, grads, state, scale, grad_norm, skip=skip)
        updates = unflatten(new_flat - old_flat, state.spec, cast_back=False)
        # match param leaf dtypes (masters are fp32; O3 runs half params)
        updates = jax.tree_util.tree_map(
            lambda u, p: u.astype(p.dtype), updates, params)
        return updates, new_state

    # -- apex-style step --------------------------------------------------
    def step(self, params: Pytree, grads: Pytree, state: FusedAdamState,
             scale=1.0, grad_norm=None, output_params_dtype=None,
             skip=None):
        """Apply the update directly (reference ``step`` semantics with
        ``grads``/``scale``/``grad_norms`` args, ``fused_adam.py:50``).

        Returns ``(new_params, new_state)`` — with ``output_params_dtype``
        the returned params are also cast (the reference's fp16
        ``output_params`` copy-out, ``fused_adam_cuda_kernel.cu:82``).

        ``skip`` (bool scalar or None): amp's overflow->skip-step,
        selected INSIDE the fused kernel — see :func:`_adam_math`.
        """
        if self.layout == "tree":
            new_params, new_state = self._step_tree(
                params, grads, state, scale, grad_norm, skip=skip)
            if output_params_dtype is not None:
                new_params = jax.tree_util.tree_map(
                    lambda x: x.astype(output_params_dtype), new_params)
            return new_params, new_state
        new_flat, new_state, _ = self._step_flat(params, grads, state, scale,
                                                 grad_norm, skip=skip)
        if output_params_dtype is not None:
            new_params = jax.tree_util.tree_map(
                lambda x: x.astype(output_params_dtype),
                unflatten(new_flat, state.spec, cast_back=False))
        else:
            new_params = unflatten(new_flat, state.spec)
        return new_params, new_state

    # -- core -------------------------------------------------------------
    def _step_group(self, p, m, v, g, hp, step, scale, grad_norm,
                    use_pallas, keep=None):
        """One (contiguous) group's fused update. ``keep`` (f32 1.0/0.0
        or None): in-kernel skip-step select, see :func:`_adam_math`."""
        beta1, beta2 = hp["betas"]

        combined_scale = jnp.asarray(scale, jnp.float32)
        if hp["max_grad_norm"] > 0:
            if grad_norm is None:
                grad_norm = jnp.sqrt(
                    jnp.sum(jnp.square(g)))  # this group's grads only
            # reference fused_adam.py:98-104
            clip = (grad_norm / jnp.asarray(scale, jnp.float32)) / \
                hp["max_grad_norm"]
            combined_scale = jnp.where(clip > 1,
                                       clip * scale, combined_scale)

        if self.bias_correction:
            # a skipped step does not advance ``step``, so the first
            # (skipped) step sees t=0 where 1-beta^0 = 0: clamp to 1 —
            # the produced step_size only feeds a result the keep-select
            # discards
            t = jnp.maximum(step, 1).astype(jnp.float32)
            bc1 = 1.0 - beta1 ** t
            bc2 = 1.0 - beta2 ** t
            step_size = hp["lr"] * jnp.sqrt(bc2) / bc1
        else:
            step_size = jnp.asarray(hp["lr"], jnp.float32)

        if use_pallas:
            scalars = jnp.stack([
                jnp.asarray(step_size, jnp.float32),
                jnp.asarray(beta1, jnp.float32),
                jnp.asarray(beta2, jnp.float32),
                jnp.asarray(hp["eps"], jnp.float32),
                combined_scale,
                jnp.asarray(hp["weight_decay"], jnp.float32),
                (jnp.asarray(1.0, jnp.float32) if keep is None
                 else jnp.asarray(keep, jnp.float32)),
            ])
            call = functools.partial(
                _adam_flat_pallas, eps_inside_sqrt=self.eps_inside_sqrt,
                interpret=not on_tpu())
            if self._zero is not None:
                mesh, ax, min_elems = self._zero
                nshard = mesh.shape[ax]
                # mirror shard_optimizer_state's min-size threshold: a
                # buffer it left replicated must not be force-sharded by
                # the kernel's out_specs (placement flip + recompile
                # under donation)
                if p.shape[0] % nshard == 0 and p.shape[0] >= min_elems:
                    # ZeRO composition: run the kernel shard-local over
                    # the axis the flat state is sharded on (with_zero);
                    # elementwise update, so no collectives inside
                    from jax.sharding import PartitionSpec as P
                    sharded = P(ax)
                    # check_vma=False: pallas_call outputs carry no vma
                    # annotation; the update is shard-local elementwise,
                    # so there is no replication invariant to check
                    return jax.shard_map(
                        call, mesh=mesh,
                        in_specs=(sharded, sharded, sharded, sharded, P()),
                        out_specs=(sharded, sharded, sharded),
                        check_vma=False)(p, m, v, g, scalars)
                # a group slice that doesn't divide the axis (grouped
                # layouts pad only the total buffer), or a buffer small
                # enough that shard_optimizer_state left it replicated:
                # the jnp update follows the state's placement for free
                return _adam_math(
                    p, m, v, g, step_size, beta1, beta2, hp["eps"],
                    combined_scale, hp["weight_decay"],
                    self.eps_inside_sqrt, keep=keep)
            return call(p, m, v, g, scalars)
        return _adam_math(
            p, m, v, g, step_size, beta1, beta2, hp["eps"],
            combined_scale, hp["weight_decay"], self.eps_inside_sqrt,
            keep=keep)

    def _step_tree(self, params, grads, state: FusedAdamState, scale,
                   grad_norm, skip=None):
        """Per-leaf update (``layout="tree"``): same math as the flat
        kernel, one fused HBM pass per leaf, no concat/pad/slice-back.
        Returns ``(new_params_tree, new_state)``."""
        hps = group_hparams(self._defaults(), self.param_groups)
        ids = (resolve_group_ids(params, self.param_groups)
               if self.param_groups else None)
        if skip is None:
            keep = None
            step = state.step + 1
        else:
            keep = 1.0 - jnp.asarray(skip, jnp.float32)
            step = state.step + keep.astype(jnp.int32)

        g_leaves = jax.tree_util.tree_leaves(grads)

        def group_scalars(gid, hp):
            beta1, beta2 = hp["betas"]
            combined_scale = jnp.asarray(scale, jnp.float32)
            if hp["max_grad_norm"] > 0:
                gn = grad_norm
                if gn is None:  # this group's grads only (flat parity)
                    sq = jnp.asarray(0.0, jnp.float32)
                    for i, g in enumerate(g_leaves):
                        if ids is None or ids[i] == gid:
                            sq = sq + jnp.sum(
                                jnp.square(g.astype(jnp.float32)))
                    gn = jnp.sqrt(sq)
                clip = (gn / jnp.asarray(scale, jnp.float32)) / \
                    hp["max_grad_norm"]
                combined_scale = jnp.where(clip > 1, clip * scale,
                                           combined_scale)
            if self.bias_correction:
                t = jnp.maximum(step, 1).astype(jnp.float32)
                step_size = hp["lr"] * jnp.sqrt(1.0 - beta2 ** t) / \
                    (1.0 - beta1 ** t)
            else:
                step_size = jnp.asarray(hp["lr"], jnp.float32)
            return step_size, combined_scale

        scalars = [group_scalars(gid, hp) for gid, hp in enumerate(hps)]

        i = -1

        def leaf(p, m, v, g):
            nonlocal i
            i += 1
            gid = ids[i] if ids is not None else 0
            hp = hps[gid]
            step_size, combined_scale = scalars[gid]
            p_new, m_new, v_new = _adam_math(
                p.astype(jnp.float32), m, v, g.astype(jnp.float32),
                step_size, hp["betas"][0], hp["betas"][1], hp["eps"],
                combined_scale, hp["weight_decay"], self.eps_inside_sqrt,
                keep=keep)
            return p_new.astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(leaf, params, state.m, state.v, grads)
        # unzip the (p, m, v) leaf triples back into three trees
        treedef = jax.tree_util.tree_structure(params)
        triples = jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: isinstance(x, tuple))
        p2 = jax.tree_util.tree_unflatten(treedef,
                                          [t[0] for t in triples])
        m2 = jax.tree_util.tree_unflatten(treedef,
                                          [t[1] for t in triples])
        v2 = jax.tree_util.tree_unflatten(treedef,
                                          [t[2] for t in triples])
        return p2, FusedAdamState(step=step, m=m2, v=v2, spec=None)

    def _step_flat(self, params, grads, state: FusedAdamState, scale,
                   grad_norm, skip=None):
        # pad p/g (independently — a pre-padded params tree arrives at
        # full length while grads may not) to the state buffers' length,
        # not self.pad_to: a state restored from a checkpoint must keep
        # ITS layout
        buf_len = state.m.shape[0]

        def to_buf_len(x):
            if x.shape[0] < buf_len:
                x = jnp.concatenate(
                    [x, jnp.zeros((buf_len - x.shape[0],), jnp.float32)])
            return x

        p = to_buf_len(flatten_like(params, state.spec, dtype=jnp.float32))
        g = to_buf_len(flatten_like(grads, state.spec, dtype=jnp.float32))
        if skip is None:
            keep = None
            step = state.step + 1
        else:
            keep = 1.0 - jnp.asarray(skip, jnp.float32)
            # a skipped step leaves the bias-correction clock alone too
            # (the reference's patched step is a full no-op on overflow,
            # handle.py:130-150)
            step = state.step + keep.astype(jnp.int32)
        # with_zero's kernel call sits inside its own fully-manual
        # shard_map (legal for Mosaic even when the enclosing trace has
        # GSPMD-automatic axes — nested binding under partial-manual
        # fails loudly on its own); only the bare kernel needs the
        # auto-axes gate
        use_pallas = self.use_pallas if self.use_pallas is not None \
            else (on_tpu() if self._zero is not None
                  else pallas_auto_gate())
        if use_pallas and self._zero is None:
            # eager-path guard: a sharded state meeting the un-configured
            # Pallas kernel would be silently re-gathered by GSPMD (no
            # partitioning rule on the custom call), defeating ZeRO's
            # memory win — fall back to the partitionable jnp update and
            # tell the user about with_zero.  (Inside jit the committed
            # input sharding is not visible on tracers; the same pairing
            # is then the caller's contract, parallel/zero.py.)
            try:
                sharding = (getattr(state.m, "sharding", None)
                            if jax.core.is_concrete(state.m) else None)
            except Exception:
                sharding = None
            if sharding is not None and not sharding.is_fully_replicated:
                warnings.warn(
                    "FusedAdam: optimizer state is sharded but the Pallas "
                    "kernel has no GSPMD partitioning rule; using the jnp "
                    "update instead. Configure the fused path with "
                    "optimizer.with_zero(mesh, axis) to run it "
                    "shard-local.", stacklevel=3)
                use_pallas = False

        bounds = state.spec.group_bounds or ((0, state.spec.total),)
        hps = group_hparams(self._defaults(), self.param_groups)
        if len(hps) == 1 and len(bounds) > 1:
            # state carries a grouped layout but this optimizer declares no
            # groups (e.g. layout-only restore): every group uses defaults
            hps = hps * len(bounds)
        elif len(hps) != len(bounds):
            raise ValueError(
                f"optimizer declares {len(hps)} groups but the state's "
                f"flat layout has {len(bounds)} — param_groups must match "
                "the specs the state was init'd (or add_param_group'd) "
                "with")
        if len(bounds) == 1:
            p2, m2, v2 = self._step_group(
                p, state.m, state.v, g, hps[0], step, scale, grad_norm,
                use_pallas, keep=keep)
        else:
            # write each group's slice back into the full buffers with
            # dynamic_update_slice (alias-friendly under donation) rather
            # than concatenating fresh full-size arrays
            p2, m2, v2 = p, state.m, state.v
            for (start, size), hp in zip(bounds, hps):
                if size == 0:
                    continue
                sl = slice(start, start + size)
                pp, mm, vv = self._step_group(
                    p[sl], state.m[sl], state.v[sl], g[sl], hp, step,
                    scale, grad_norm, use_pallas, keep=keep)
                p2 = jax.lax.dynamic_update_slice(p2, pp, (start,))
                m2 = jax.lax.dynamic_update_slice(m2, mm, (start,))
                v2 = jax.lax.dynamic_update_slice(v2, vv, (start,))
        return p2, FusedAdamState(step=step, m=m2, v=v2, spec=state.spec), p
