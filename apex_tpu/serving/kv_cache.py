"""Block-table-indexed KV cache — the serving memory manager.

vLLM's PagedAttention insight, re-derived for jit-stability on TPU:
the cache is ONE preallocated fixed-shape pool of ``num_blocks``
physical blocks of ``block_size`` token slots each, per layer —

    k, v: (num_layers, num_blocks * block_size, num_heads, head_dim)

— and every request owns an ordered *block table* mapping its logical
token positions to physical blocks.  Fixed shapes mean the jitted
prefill/decode steps never recompile as requests come and go; block
granularity means a request's memory grows in ``block_size`` quanta
with zero copying, and a finished request's blocks return to the free
list immediately (no compaction, no fragmentation beyond the last
partial block).

Split of responsibilities:

- device side (this module's pure functions): fixed-shape gather of a
  request batch's context (``gather_context``), scatter of freshly
  projected K/V into flat slots (``write_tokens`` / ``write_prefill``)
  — all jit-traceable, cache pytree in/out;
- host side (:class:`BlockAllocator`): the free list.  Allocation is
  control flow, not math — it stays in Python where it is O(blocks)
  trivial, exactly like the schedulers it serves.

Physical block 0 is RESERVED as the garbage sink: unallocated
block-table entries and padded prefill positions all point at it, so
every scatter/gather stays in-bounds with no data-dependent branching
— reads from it are masked by the context bias (built from lengths),
writes to it land on data nothing will ever read.

Dtype policy: the cache is typically the HBM hog (2 * L * T * H * D
per token), so it defaults to the amp "half" dtype — the active
``amp.initialize`` policy's ``cast_model_type`` when one is installed,
else bfloat16 (``amp.properties.HALF``).  ``KVCacheConfig(dtype=...)``
overrides explicitly (tests pin fp32 for bit-parity runs).

Quantized mode (``docs/serving.md``, "Quantized KV cache"):
``KVCacheConfig(quantize="int8")`` stores the pool as int8 with a
per-token-slot, per-head fp32 absmax scale SIDECAR — two extra cache
leaves ``k_scale`` / ``v_scale`` of shape (L, num_slots, H), allocated
block-granular alongside the pool so every block-lifecycle path (COW
duplication, prefix-cache holds, speculation rollback, preemption
re-prefill) carries scales with their blocks by construction, and
head-sharded with their heads under tensor parallelism.  ``dtype``
keeps meaning the COMPUTE dtype the dequantized values widen to; the
STORAGE dtype becomes int8 (:meth:`KVCacheConfig.storage_dtype`).
Scales are per token slot — not one scalar per block — because a
block fills incrementally (decode writes one token at a time) and a
shared per-block scalar would have to requantize earlier tokens from
their already-lossy int8, destroying the bit-stability the serving
stack pins across preemption / chunked prefill / COW (BENCH_NOTES,
kv-quant decision table).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

# the quantization numeric contract lives with the kernels that widen
# it back (ops); re-exported here because the cache is what stores it
from apex_tpu.ops.kv_quant import (  # noqa: F401  (re-export)
    INT8_QMAX,
    dequantize_kv,
    quantize_kv,
)

NEG_INF = -1e9

# env twin of the ``kv_quant=`` knob (InferenceServer reads it)
KV_QUANT_ENV = "APEX_TPU_KV_QUANT"

_QUANT_MODES = (None, "int8")


def resolve_kv_quant(value):
    """Normalize a ``kv_quant`` knob / ``APEX_TPU_KV_QUANT`` env value
    to ``None`` or ``"int8"``; anything else is a loud error."""
    if value is None:
        return None
    if isinstance(value, str):
        v = value.strip().lower()
        if v in ("", "0", "none", "off"):
            return None
        if v in ("1", "int8"):
            return "int8"
    raise ValueError(
        f"unknown KV quantization mode {value!r} "
        f"(expected one of: None/'', 'int8')")


def resolve_cache_dtype(dtype=None):
    """The ONE resolution of ``KVCacheConfig.dtype=None``: an explicit
    dtype wins; else the installed amp policy's half type (``O1``-``O3``
    set ``cast_model_type``); else bfloat16 (TPU-native half).

    Integer dtypes are rejected: ``dtype`` is the COMPUTE dtype the
    pool's values carry through attention, and an int pool here would
    silently store garbage K/V — int8 storage is a quantization mode
    (``KVCacheConfig(quantize="int8")``), not a cache dtype."""
    if dtype is not None:
        dt = jnp.dtype(dtype)
        if not jnp.issubdtype(dt, jnp.floating):
            raise TypeError(
                f"cache dtype must be a floating-point compute dtype, "
                f"got {dt}; for an int8-quantized KV pool pass "
                f"KVCacheConfig(quantize='int8') (per-block-scaled "
                f"storage), not dtype={dt}")
        return dt
    try:
        from apex_tpu.amp._amp_state import _amp_state
        props = _amp_state.opt_properties
        cast = getattr(props, "cast_model_type", None) if props else None
        if cast is not None:
            return jnp.dtype(cast)
    except Exception:
        pass
    from apex_tpu.amp.properties import HALF
    return jnp.dtype(HALF)


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Geometry of the block pool.

    ``num_blocks`` INCLUDES the reserved garbage block 0, so the
    usable capacity is ``(num_blocks - 1) * block_size`` tokens.
    ``dtype=None`` defers to :func:`resolve_cache_dtype`.

    ``quantize="int8"`` turns on quantized storage: the pool leaves
    become int8 and a per-slot, per-head fp32 scale sidecar
    (``k_scale`` / ``v_scale``, shape (L, num_slots, H)) rides along;
    ``dtype`` then names the COMPUTE dtype dequantized values widen
    to.  All byte accounting (:meth:`bytes`, :attr:`bytes_per_block`)
    includes the sidecar — occupancy and headroom math must price a
    block at what it actually costs in HBM."""

    num_layers: int
    num_heads: int
    head_dim: int
    num_blocks: int
    block_size: int = 16
    dtype: Optional[object] = None
    quantize: Optional[str] = None

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError(
                "num_blocks must be >= 2 (block 0 is the reserved "
                f"garbage sink); got {self.num_blocks}")
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1; got "
                             f"{self.block_size}")
        if self.quantize not in _QUANT_MODES:
            raise ValueError(
                f"quantize must be one of {_QUANT_MODES}; got "
                f"{self.quantize!r}")
        self.resolved_dtype()   # reject int compute dtypes loudly

    @property
    def num_slots(self) -> int:
        return self.num_blocks * self.block_size

    @property
    def usable_tokens(self) -> int:
        return (self.num_blocks - 1) * self.block_size

    @property
    def quantized(self) -> bool:
        return self.quantize is not None

    def resolved_dtype(self):
        return resolve_cache_dtype(self.dtype)

    def storage_dtype(self):
        """The dtype the pool's K/V payload is actually stored in:
        int8 under quantization, the compute dtype otherwise."""
        if self.quantized:
            return jnp.dtype(jnp.int8)
        return self.resolved_dtype()

    @property
    def scale_bytes_per_block(self) -> int:
        """HBM cost of one block's share of the scale sidecar (both
        K and V legs); 0 when quantization is off."""
        if not self.quantized:
            return 0
        return 2 * self.num_layers * self.block_size * self.num_heads \
            * jnp.dtype(jnp.float32).itemsize

    @property
    def bytes_per_block(self) -> int:
        """TRUE HBM cost of one physical block — K + V payload plus
        the scale sidecar under quantization.  The allocator's
        occupancy/fragmentation math and the fixed-pool-bytes bench
        arms price blocks with this, so quantized headroom claims are
        net of the sidecar."""
        payload = (2 * self.num_layers * self.block_size
                   * self.num_heads * self.head_dim
                   * self.storage_dtype().itemsize)
        return payload + self.scale_bytes_per_block

    def bytes(self) -> int:
        """HBM footprint of the pool (both K and V, scale sidecar
        included when quantized)."""
        return self.num_blocks * self.bytes_per_block


def init_kv_cache(cfg: KVCacheConfig, sharding=None,
                  scale_sharding=None):
    """Allocate the zeroed pool: ``{"k","v"}`` each
    (L, num_slots, H, D) in the storage dtype, plus — under
    ``quantize="int8"`` — the fp32 scale sidecar ``{"k_scale",
    "v_scale"}`` each (L, num_slots, H).

    ``sharding``: optional ``jax.sharding.Sharding`` for the pool
    leaves — tensor-parallel serving passes the head-sharded pool
    placement (``P(None, None, model, None)``) so every device
    materializes ONLY its ``H/tp`` heads of every block; the zeros are
    created sharded (jit ``out_shardings``), never allocated whole and
    scattered.  ``scale_sharding`` is the sidecar's placement
    (``P(None, None, model)`` — heads are its LAST dim), so scales
    live on the same shard as the heads they dequantize."""
    shape = (cfg.num_layers, cfg.num_slots, cfg.num_heads, cfg.head_dim)
    dt = cfg.storage_dtype()

    def build():
        cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        if cfg.quantized:
            sshape = shape[:-1]
            cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
            cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
        return cache

    if sharding is None:
        return build()
    outs = {"k": sharding, "v": sharding}
    if cfg.quantized:
        outs["k_scale"] = scale_sharding
        outs["v_scale"] = scale_sharding
    return jax.jit(build, out_shardings=outs)()




# ---------------------------------------------------------------------------
# device-side pure functions (jit-traceable, cache pytree in -> out)
# ---------------------------------------------------------------------------

def slot_index(block_tables, positions, block_size: int):
    """Flat pool slot of logical ``positions`` — (B,) one per
    sequence, or (B, S) many per sequence — under ``block_tables``
    (B, max_blocks): ``table[pos // bs] * bs + pos % bs``.
    Unallocated table entries are 0, so out-of-range logical positions
    land in the garbage block."""
    blk = positions // block_size
    off = positions % block_size
    squeeze = blk.ndim == block_tables.ndim - 1
    if squeeze:
        blk = blk[..., None]
    phys = jnp.take_along_axis(block_tables, blk, axis=-1)
    if squeeze:
        phys = phys[..., 0]
    return phys * block_size + off


def write_tokens(cache, kvs, slots):
    """Scatter one new token per sequence into the pool.

    kvs: (L, B, 1, H, D) stacked per-layer (k, v) pairs — i.e. a tuple
    ``(k_new, v_new)`` of that shape; slots: (B,) flat slot indices.
    Under quantization kvs is ``((k_q, k_scale), (v_q, v_scale))``
    with the payloads (L, B, 1, H, D) int8 and the scales
    (L, B, 1, H) fp32 — ALREADY quantized by the model's projection
    path, so the pool receives byte-for-byte the values attention just
    used."""
    k_new, v_new = kvs
    if "k_scale" in cache:
        (kq, ks), (vq, vs) = k_new, v_new
        return {"k": cache["k"].at[:, slots].set(kq[:, :, 0]),
                "v": cache["v"].at[:, slots].set(vq[:, :, 0]),
                "k_scale": cache["k_scale"].at[:, slots].set(ks[:, :, 0]),
                "v_scale": cache["v_scale"].at[:, slots].set(vs[:, :, 0])}
    k_new = k_new[:, :, 0].astype(cache["k"].dtype)   # (L, B, H, D)
    v_new = v_new[:, :, 0].astype(cache["v"].dtype)
    return {"k": cache["k"].at[:, slots].set(k_new),
            "v": cache["v"].at[:, slots].set(v_new)}


def write_prefill(cache, kvs, slots):
    """Scatter a whole prompt's K/V into the pool.

    kvs: tuple of (L, B, S, H, D); slots: (B, S) flat slot indices with
    padded positions pointed at the garbage block by the caller.
    Under quantization kvs is ``((k_q, k_scale), (v_q, v_scale))``
    exactly as in :func:`write_tokens` (payloads (L, B, S, H, D),
    scales (L, B, S, H))."""
    k_new, v_new = kvs
    if "k_scale" in cache:
        (kq, ks), (vq, vs) = k_new, v_new
        L = kq.shape[0]
        flat = slots.reshape(-1)                      # (B*S,)
        out = {"k": cache["k"].at[:, flat].set(
                   kq.reshape(L, -1, *kq.shape[3:])),
               "v": cache["v"].at[:, flat].set(
                   vq.reshape(L, -1, *vq.shape[3:]))}
        out["k_scale"] = cache["k_scale"].at[:, flat].set(
            ks.reshape(L, -1, *ks.shape[3:]))
        out["v_scale"] = cache["v_scale"].at[:, flat].set(
            vs.reshape(L, -1, *vs.shape[3:]))
        return out
    L = k_new.shape[0]
    flat = slots.reshape(-1)                          # (B*S,)
    k2 = k_new.reshape(L, -1, *k_new.shape[3:]).astype(cache["k"].dtype)
    v2 = v_new.reshape(L, -1, *v_new.shape[3:]).astype(cache["v"].dtype)
    return {"k": cache["k"].at[:, flat].set(k2),
            "v": cache["v"].at[:, flat].set(v2)}


def gather_context(cache, block_tables, block_size: int, out_dtype=None):
    """Gather each sequence's logical context from the pool.

    block_tables: (B, max_blocks) int32 (0 = unallocated -> garbage
    block; masked by the caller's ctx bias).  Returns ``(k_ctx,
    v_ctx)`` of shape (L, B, max_blocks * block_size, H, D): gathered
    position j IS logical token j because tables are ordered."""
    b, mb = block_tables.shape
    bs = block_size
    slots = (block_tables[:, :, None] * bs
             + jnp.arange(bs, dtype=block_tables.dtype)[None, None, :]
             ).reshape(b, mb * bs)                    # (B, T)
    k = cache["k"][:, slots]                          # (L, B, T, H, D)
    v = cache["v"][:, slots]
    if out_dtype is not None:
        k = k.astype(out_dtype)
        v = v.astype(out_dtype)
    return k, v


def gather_scales(cache, block_tables, block_size: int):
    """The scale-sidecar leg of :func:`gather_context`: gather each
    sequence's per-slot dequantization scales with the SAME slot map
    the payload gather uses.  Returns ``(k_scale, v_scale)`` of shape
    (L, B, max_blocks * block_size, H) fp32 — position j is logical
    token j's scales, garbage slots carry garbage scales that the
    context bias masks exactly like the payload they scale."""
    b, mb = block_tables.shape
    bs = block_size
    slots = (block_tables[:, :, None] * bs
             + jnp.arange(bs, dtype=block_tables.dtype)[None, None, :]
             ).reshape(b, mb * bs)                    # (B, T)
    return cache["k_scale"][:, slots], cache["v_scale"][:, slots]


def context_bias(lengths, max_context: int):
    """(B,) valid-token counts -> (B, T) additive bias: 0 for logical
    slots < length, NEG_INF beyond (covers unwritten slots, freed
    garbage, and the tail of the last partial block)."""
    t = jnp.arange(max_context, dtype=jnp.int32)[None, :]
    return jnp.where(t < lengths[:, None].astype(jnp.int32),
                     0.0, NEG_INF).astype(jnp.float32)


def copy_blocks_across(dst_cache, src_cache, src, dst, block_size: int):
    """Whole-block copy ``src[i] (in src_cache) -> dst[i] (in
    dst_cache)`` BETWEEN two pools of identical geometry — the device
    half of the disaggregated prefill/decode hand-off
    (``docs/serving.md``, "Disaggregated prefill/decode"): a finished
    prefill's blocks move from the prefill pool into the decode pool
    as one fixed-shape gather+scatter, so the two pools' programs
    share no array and their compute never serializes through a common
    pool version.

    src, dst: (M,) int32 physical block ids, (0, 0)-padded exactly
    like :func:`copy_blocks` (garbage block -> garbage block is a
    no-op by construction).  Copies EVERY leaf the two caches share —
    under quantization the scale sidecar rows move with their int8
    payload, so a handed-off block dequantizes bit-identically on the
    decode side."""
    off = jnp.arange(block_size, dtype=src.dtype)[None, :]
    s = (src[:, None] * block_size + off).reshape(-1)
    d = (dst[:, None] * block_size + off).reshape(-1)
    return {name: arr.at[:, d].set(src_cache[name][:, s])
            for name, arr in dst_cache.items()}


def copy_blocks(cache, src, dst, block_size: int):
    """Whole-block copy ``src[i] -> dst[i]`` inside the pool — the
    device half of copy-on-write duplication (a request that must
    write into a block shared through the prefix cache first clones it
    into a private block).

    src, dst: (M,) int32 physical block ids.  Unused pairs pad with
    (0, 0): copying the garbage block onto itself is a no-op by
    construction, so the call stays fixed-shape.

    Copies EVERY cache leaf — under quantization the scale sidecar
    legs duplicate with their payload in the same program, so a COW
    clone dequantizes bit-identically to its source block."""
    off = jnp.arange(block_size, dtype=src.dtype)[None, :]
    s = (src[:, None] * block_size + off).reshape(-1)
    d = (dst[:, None] * block_size + off).reshape(-1)
    return {name: arr.at[:, d].set(arr[:, s])
            for name, arr in cache.items()}


# ---------------------------------------------------------------------------
# host-side allocator
# ---------------------------------------------------------------------------

class BlockAllocator:
    """Refcounted free-list over physical blocks 1..num_blocks-1 (0 is
    the garbage sink and is never handed out).

    LIFO reuse (a stack) keeps hot blocks hot — a freed request's
    blocks are the most recently touched HBM and the next allocation
    gets them first.  A parallel ``_free_set`` mirrors the list so
    double-free detection and :meth:`free` are O(1) per block instead
    of an O(n) list scan.

    Refcounts are what make prefix caching possible: a block shared by
    several requests' tables carries one ref per table
    (:meth:`incref`), and :meth:`free` only returns it to the free
    list when the last ref drops.  A block whose refcount reaches zero
    is first offered to ``release_hook`` (the prefix cache): the hook
    returning True keeps the block out of the free list — still
    resident, evictable later via :meth:`release_to_free` — so cached
    prefixes survive their original request.  Every block is therefore
    in exactly one of three states: free (in the list+set), live
    (refcount >= 1), or cache-held (refcount 0, hook-retained)."""

    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        self.release_hook = None      # blk -> bool; True = hook keeps it
        self.reset_hooks: List = []   # called on reset() (cache clears)
        self.reset()

    def reset(self):
        """Return every block to the free list (between workloads;
        in-place so schedulers holding this allocator stay wired).
        Reset hooks fire so a prefix cache indexing the old blocks
        drops its now-dangling entries."""
        self._free: List[int] = list(range(self.cfg.num_blocks - 1, 0,
                                           -1))
        self._free_set = set(self._free)
        self._refs: Dict[int, int] = {}
        self.live_peak = 0          # high-watermark of live blocks
        for hook in self.reset_hooks:
            hook()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_live(self) -> int:
        """Blocks currently referenced by at least one table (memory
        observability: ``usable - num_free - num_live`` is the
        cache-held remainder)."""
        return len(self._refs)

    def _note_live(self) -> None:
        if len(self._refs) > self.live_peak:
            self.live_peak = len(self._refs)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Pop n blocks at refcount 1; raises :class:`MemoryError` when
        the pool is exhausted (the scheduler checks :meth:`can_alloc` /
        evicts / preempts first, so reaching this is a caller bug)."""
        if n <= 0:
            return []
        if n > len(self._free):
            raise MemoryError(
                f"KV cache pool exhausted: requested {n} blocks, "
                f"{len(self._free)} free "
                f"(pool={self.cfg.num_blocks - 1})")
        out = self._free[-n:][::-1]
        del self._free[len(self._free) - n:]
        for blk in out:
            self._free_set.discard(blk)
            self._refs[blk] = 1
        self._note_live()
        return out

    def refs(self, blk: int) -> int:
        return self._refs.get(blk, 0)

    def incref(self, blocks: List[int]):
        """Add one ref per block (a second table now references it)."""
        for blk in blocks:
            if blk not in self._refs:
                raise ValueError(
                    f"incref of unallocated block {blk}")
            self._refs[blk] += 1

    def adopt(self, blk: int):
        """Re-own a cache-held block (refcount 0, hook-retained) at
        refcount 1 — the prefix cache reactivating an evictable block a
        new request just matched."""
        if blk in self._free_set or blk in self._refs:
            raise ValueError(
                f"adopt of block {blk} that is not cache-held "
                f"(free={blk in self._free_set}, "
                f"refs={self._refs.get(blk)})")
        self._refs[blk] = 1
        self._note_live()

    def free(self, blocks: List[int]):
        """Drop one ref per block; blocks reaching zero return to the
        free list unless ``release_hook`` claims them (prefix cache
        hold).  All blocks validate before any state changes."""
        for blk in blocks:
            if not 1 <= blk < self.cfg.num_blocks:
                raise ValueError(f"freeing invalid block id {blk}")
            if blk in self._free_set:
                raise ValueError(f"double free of block {blk}")
            if blk not in self._refs:
                raise ValueError(f"freeing unallocated block {blk}")
        for blk in blocks:
            if self._refs[blk] > 1:
                self._refs[blk] -= 1
                continue
            del self._refs[blk]
            if self.release_hook is not None and self.release_hook(blk):
                continue
            self._free.append(blk)
            self._free_set.add(blk)

    def release_to_free(self, blk: int):
        """Return a cache-held block (refcount 0) to the free list —
        the prefix cache's eviction path."""
        if blk in self._free_set or blk in self._refs:
            raise ValueError(
                f"release_to_free of block {blk} that is not "
                f"cache-held")
        self._free.append(blk)
        self._free_set.add(blk)

    @staticmethod
    def blocks_for(num_tokens: int, block_size: int) -> int:
        return -(-max(num_tokens, 1) // block_size)
