"""`InferenceServer` — the synchronous front door of `apex_tpu.serving`.

Composes the device half (:class:`serving.engine.DecodeEngine`: jitted
prefill/decode over the block-pool KV cache) with the host half
(:class:`serving.scheduler.Scheduler`: iteration-level continuous
batching) into a step loop, and meters it (queue depth, running-batch
occupancy, tokens/s — ``utils.RateMeter``/``GaugeMeter``).

``generate()`` is batch-synchronous (submit N prompts, run the loop to
completion, return N completions) — the shape every test and bench
needs.  A live service would run :meth:`step` on its event loop and
stream ``Request.generated`` as it grows; both drive the identical
scheduler/engine machinery, so the offline numbers transfer.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from apex_tpu.serving.engine import DecodeEngine
from apex_tpu.serving.scheduler import Request, Scheduler
from apex_tpu.utils import GaugeMeter, RateMeter


def greedy_sample(logits: np.ndarray) -> np.ndarray:
    """(…, V) logits -> (…,) argmax token ids — deterministic, which
    is what makes cached decode testable token-for-token against the
    full-recompute forward."""
    return np.argmax(logits, axis=-1)


class InferenceServer:
    """Batched GPT inference with KV-cache + continuous batching.

    Args (beyond :class:`DecodeEngine`'s, which pass through):
      sample_fn: (…, V) numpy logits -> (…,) token ids; default
        greedy.  Runs on host — per-step logits are (B, V).

    Example::

        server = InferenceServer(cfg, params, max_batch_size=8)
        outs = server.generate(prompts, max_new_tokens=64, eos_id=50256)
    """

    def __init__(self, cfg, params, *,
                 max_batch_size: int = 8,
                 max_context: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 block_size: int = 16,
                 cache_dtype=None,
                 attention_fn=None,
                 prefill_buckets=None,
                 sample_fn: Optional[Callable] = None):
        self.engine = DecodeEngine(
            cfg, params, max_batch_size=max_batch_size,
            max_context=max_context, num_blocks=num_blocks,
            block_size=block_size, cache_dtype=cache_dtype,
            attention_fn=attention_fn, prefill_buckets=prefill_buckets)
        self.scheduler = Scheduler(
            self.engine.allocator,
            max_batch_size=self.engine.max_batch_size,
            block_size=self.engine.block_size,
            max_context=self.engine.max_context)
        self.sample_fn = sample_fn or greedy_sample
        self.queue_depth = GaugeMeter()
        self.occupancy = GaugeMeter()
        self.tokens = RateMeter()

    # -- request lifecycle ------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None) -> Request:
        """Enqueue one request.  ``max_new_tokens`` is silently capped
        so prompt + completion fits ``max_context``."""
        prompt = [int(t) for t in prompt]
        cap = self.engine.max_context - len(prompt)
        if cap <= 0:
            raise ValueError(
                f"prompt length {len(prompt)} leaves no room to "
                f"generate within max_context={self.engine.max_context}")
        req = Request(prompt=prompt,
                      max_new_tokens=min(int(max_new_tokens), cap),
                      eos_id=eos_id)
        return self.scheduler.submit(req)

    def step(self) -> int:
        """One continuous-batching iteration: admit + prefill newly
        schedulable requests, then one decode step across the running
        batch.  Returns the number of tokens sampled (0 = idle)."""
        sched, engine = self.scheduler, self.engine
        produced = 0

        for req in sched.admit():
            ctx, discard_logits = sched.prefill_plan(req)
            logits = engine.prefill(ctx, req.block_table)
            req.num_cached = len(ctx)
            if discard_logits:
                # resumed after preemption: the pending token continues
                continue
            tok = int(self.sample_fn(np.asarray(logits)))
            req.record_token(tok)
            produced += 1
            if req.finished:
                sched.retire(req)

        if sched.running:
            for req in list(sched.running.values()):
                if req.running:        # an earlier pass may have
                    sched.ensure_decode_capacity(req)  # preempted it
            running = list(sched.running.values())
            if running:
                b, mb = engine.max_batch_size, engine.blocks_per_seq
                tokens = np.zeros((b,), np.int32)
                positions = np.zeros((b,), np.int32)
                tables = np.zeros((b, mb), np.int32)
                for req in running:
                    tokens[req.slot] = req.next_input
                    positions[req.slot] = req.num_cached
                    tables[req.slot, :len(req.block_table)] = \
                        req.block_table
                logits = np.asarray(
                    engine.decode(tokens, positions, tables))
                toks = self.sample_fn(logits)
                for req in running:
                    req.num_cached += 1
                    req.record_token(int(toks[req.slot]))
                    produced += 1
                    if req.finished:
                        sched.retire(req)

        self.tokens.update(produced)
        self.queue_depth.update(sched.num_waiting)
        self.occupancy.update(sched.num_running
                              / self.engine.max_batch_size)
        return produced

    # -- front door -------------------------------------------------------

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int,
                 eos_id: Optional[int] = None) -> List[List[int]]:
        """Generate completions for ``prompts`` (token-id lists) and
        return the generated ids per prompt, in input order."""
        reqs = [self.submit(p, max_new_tokens, eos_id) for p in prompts]
        while self.scheduler.has_work:
            self.step()
        return [list(r.generated) for r in reqs]

    def reset_meters(self) -> None:
        """Zero the counters (after compile warmup, before a timed
        window) — a completed :meth:`generate` already returns every
        slot and block, so the server itself needs no reset."""
        self.tokens.reset()
        self.queue_depth.reset()
        self.occupancy.reset()
        self.scheduler.finished.clear()

    def stats(self) -> dict:
        """Serving counters for logs and the bench harness."""
        pre, dec = self.engine.compile_counts()
        return {
            "tokens_generated": self.tokens.total,
            "tokens_per_s": round(self.tokens.rate, 1),
            "queue_depth_peak": self.queue_depth.peak,
            "batch_occupancy_avg": round(self.occupancy.avg, 3),
            "prefill_compiles": pre,
            "decode_compiles": dec,
            "kv_blocks_free": self.engine.allocator.num_free,
            "requests_finished": len(self.scheduler.finished),
            "preemptions": sum(r.preemptions
                               for r in self.scheduler.finished),
        }
