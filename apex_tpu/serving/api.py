"""`InferenceServer` — the synchronous front door of `apex_tpu.serving`.

Composes the device half (:class:`serving.engine.DecodeEngine`: jitted
prefill/decode over the block-pool KV cache) with the host half
(:class:`serving.scheduler.Scheduler`: iteration-level continuous
batching) into a step loop, and meters it (queue depth, running-batch
occupancy, tokens/s — ``utils.RateMeter``/``GaugeMeter``).

Telemetry (``docs/observability.md``): every meter lives in a shared
:class:`apex_tpu.observability.MetricsRegistry` (one snapshot /
Prometheus scrape covers the server), each request carries an
enqueue → admit → first-token → finish timeline feeding TTFT,
queue-wait, and per-token decode-latency histograms surfaced in
:meth:`InferenceServer.stats`, and — when tracing is on
(``APEX_TPU_TRACE``) — the step loop emits admit / prefix-match /
chunk-prefill / decode / evict / preempt spans plus request-lifecycle
and engine-compile instants into a Perfetto-loadable Chrome trace.

Deep observability (``docs/observability.md``): an opt-in step-level
flight recorder (``flight_recorder=`` / ``postmortem_dir=`` /
``APEX_TPU_POSTMORTEM``; zero-allocation null when off) captures one
structured record per iteration — batch composition,
admit/shed/preempt/evict decisions, memory occupancy, speculation
outcomes, pressure, breaker state — and postmortem bundles (flight
JSONL + metrics snapshot + Chrome trace) dump on demand
(:meth:`InferenceServer.dump_postmortem`), on breaker-open
transitions, and on :meth:`InferenceServer.audit` failure;
``stats()["slo"]`` tracks per-priority-class SLO attainment and
goodput vs throughput, and ``stats()["memory"]`` the KV pool's
free/live/evictable occupancy, high-watermarks, and fragmentation.

Ops plane (``docs/observability.md``, "Ops plane & watchdog"): an
opt-in loopback HTTP endpoint (``ops_port=`` / ``APEX_TPU_OPS_PORT``)
serves ``/healthz`` (status-code health a router can key on),
``/metrics`` (Prometheus text under the proper content type),
``/statusz`` (full ``stats()``), ``/debug/flight`` and
``/debug/requests/<uid>`` live slices, and loopback-authenticated
``POST /drain`` / ``POST /postmortem`` triggers; an opt-in
:class:`observability.HangWatchdog` turns step-loop silence into a
detection — thread stacks + postmortem bundle + a 503 ``/healthz`` —
exactly once per stall; and per-compiled-program accounting
(``stats()["programs"]``, on by default) tallies every engine launch
per program/shape key so "where does the step go" is answerable per
program, not just per phase.

Pipelined serve loop (``docs/serving.md``, "Pipelined serve loop"; ON
by default, ``enable_pipeline=False`` opts out, a custom ``sample_fn``
auto-disables): each :meth:`step` first RETIRES the previous
iteration's launched decode/verify results (token ids + finite flags,
sampled on device by the engine's fused programs), then plans and
LAUNCHES this iteration's programs without materializing them — so
host scheduling for step N+1 overlaps device compute for step N, and
the per-step device→host transfer is a ``(B,)`` int32 vector instead
of a ``(B, V)`` logits block.  Output is bit-identical to the
synchronous loop: greedy argmax is computed by the same rule on
device, every host-side decision (deadlines, admission, shedding,
preemption, drafts) happens AFTER the prior step's results are
applied — exactly the state the synchronous loop would have seen —
and ``submit()`` flushes the window first so front-door decisions
(breaker, displacement) never race the in-flight step.

``generate()`` is batch-synchronous (submit N prompts, run the loop to
completion, return N completions) — the shape every test and bench
needs.  A live service would run :meth:`step` on its event loop and
stream ``Request.generated`` as it grows; both drive the identical
scheduler/engine machinery, so the offline numbers transfer.

Serving-perf layers (all ON by default; ``enable_prefix_cache=False``
/ ``enable_chunked_prefill=False`` / ``enable_speculation=False`` opt
out): block-level prefix caching shares cached full blocks at
admission so only the uncached tail prefills, chunked prefill
advances ONE chunk per prefilling request per iteration so a long
prompt stalls the decode batch by at most one chunk, and speculative
decoding drafts up to ``spec_tokens`` guesses per request per
iteration (zero-weight prompt-lookup by default), scores them in one
fixed-width verify launch, and accepts exactly the prefix matching
the model's own argmax — several tokens per engine step on
repetitive traffic, output bit-identical to one-token decode by
construction.  Hit/miss/eviction/COW counters, the per-iteration
chunk gauge, and the speculation acceptance counters/histograms
surface in :meth:`InferenceServer.stats` (``docs/serving.md``).

Failure isolation (``docs/resilience.md``): the step loop never lets
one pathological request take the batch down.  Per iteration it (1)
expires per-request deadlines (iteration or wall budget →
``finish_reason="timeout"``), (2) routes impossible-capacity requests
— never-fits prompts at admission, pool-outgrowers mid-flight — to
``finish_reason="capacity"``, and (3) evicts any request whose logits
went non-finite (``finish_reason="nonfinite"``) before sampling can
poison the rest of the batch.  A bounded waiting queue rejects at
submission (``finish_reason="rejected"``).  A transient engine
``MemoryError`` (an HBM allocation burst) skips the affected engine
call for one iteration and retries — same inputs, same logits, so
generation stays bit-stable — instead of killing the batch.  Every
failure is counted by reason in a
:class:`apex_tpu.utils.CounterMeter` surfaced through
:meth:`InferenceServer.stats`.

Overload control & lifecycle (``docs/resilience.md``, "Overload
policy & lifecycle"; both ON by default): requests carry a
``priority`` class and a block-cost estimate; under queue/pool
pressure the scheduler sheds the lowest-priority, newest waiting work
(``finish_reason="shed"``) and preempts worst-priority-first
(:mod:`serving.overload`).  A :class:`resilience.CircuitBreaker`
guards ``submit`` — after a streak of non-finite/OOM failures it
fast-rejects with ``finish_reason="breaker_open"`` until a half-open
probe succeeds.  :meth:`InferenceServer.drain` stops admissions
(``finish_reason="draining"``) and runs every in-flight request to a
terminal state — in-flight generation is bit-identical whether or not
a drain begins mid-stream — and :meth:`InferenceServer.close` drains
exactly once and makes further submission an error.
"""

from __future__ import annotations

import contextlib
import faulthandler
import os
import time
import warnings
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from apex_tpu.observability import (
    JOURNEYS_ENV,
    NULL_FLIGHT_RECORDER,
    NULL_JOURNEY_LOG,
    NULL_PROGRAM_ACCOUNTING,
    NULL_WATCHDOG,
    OPS_PORT_ENV,
    POSTMORTEM_ENV,
    FlightRecorder,
    HangWatchdog,
    JourneyLog,
    MetricsRegistry,
    OpsServer,
    ProgramAccounting,
    SLOPolicy,
    SLOTracker,
    dump_journeys,
    get_tracer,
    merge_journeys,
    resolve_journeys,
    write_postmortem,
)
from apex_tpu.ops.sampling import SamplingParams, sample_tokens_host
from apex_tpu.resilience.breaker import CircuitBreaker
from apex_tpu.serving.engine import DecodeEngine
from apex_tpu.serving.kv_cache import KV_QUANT_ENV, resolve_kv_quant
from apex_tpu.serving.offload import (
    KV_OFFLOAD_ENV,
    OffloadStore,
    resolve_kv_offload,
)
from apex_tpu.serving.overload import AdmissionEstimator, OverloadPolicy
from apex_tpu.serving.prefix_cache import PrefixCache
from apex_tpu.serving import reasons
from apex_tpu.serving.scheduler import QueueFullError, Request, Scheduler
from apex_tpu.serving.speculation import DraftSource, NgramDraft
from apex_tpu.serving.streaming import StreamBroker, TokenStream
from apex_tpu.serving.transport import (
    InProcessTransport,
    KVTransport,
    TransportPolicy,
)
from apex_tpu.utils import CounterMeter, GaugeMeter, RateMeter

# the stats() window for "tokens/s right now" (RateMeter.rate_over) —
# long enough to smooth step-to-step jitter, short enough that a
# traffic change shows up within seconds
RECENT_RATE_WINDOW_S = 10.0

# default chunked-prefill width (tokens) when the caller doesn't pick
# one: small enough that a chunk costs roughly a decode step at typical
# model sizes, large enough to amortize the per-chunk context gather
DEFAULT_PREFILL_CHUNK = 256

# the no-ops-plane lock stand-in: reusable, reentrant, allocation-free
# on entry — servers without an ops endpoint never take a real lock
_NO_LOCK = contextlib.nullcontext()

# default speculation depth (max drafted tokens per verify step).  The
# verify program is spec_tokens + 1 columns wide; deeper speculation
# multiplies the best-case tokens/step but also the wasted columns when
# drafts miss, and acceptance decays geometrically with depth — 4 is
# the classic knee (docs/serving.md, "K tuning")
DEFAULT_SPEC_TOKENS = 4


def _hist_ms(hist) -> dict:
    """Milliseconds view of a seconds histogram for ``stats()`` /
    bench JSON: count + p50/p90/p99 + max."""
    if hist.count == 0:
        return {"count": 0}
    return {"count": hist.count,
            "p50": round(hist.p50 * 1e3, 3),
            "p90": round(hist.p90 * 1e3, 3),
            "p99": round(hist.p99 * 1e3, 3),
            "max": round(hist.max * 1e3, 3)}


def _hist_counts(hist) -> dict:
    """Unscaled view of a count-valued histogram (speculation
    drafted/accepted depths): count + p50/p90 + mean + max."""
    if hist.count == 0:
        return {"count": 0}
    return {"count": hist.count,
            "p50": round(hist.p50, 2),
            "p90": round(hist.p90, 2),
            "mean": round(hist.sum / hist.count, 3),
            "max": round(hist.max, 2)}


def greedy_sample(logits: np.ndarray) -> np.ndarray:
    """(…, V) logits -> (…,) argmax token ids — deterministic, which
    is what makes cached decode testable token-for-token against the
    full-recompute forward.

    Ties break toward the LOWEST token id (``np.argmax`` returns the
    first maximum).  That tie rule is part of the bit-exactness
    contract speculative decoding relies on: greedy acceptance
    compares drafted tokens against the verify rows' argmax, so every
    argmax over equal logits must resolve the same way it would in a
    plain one-token decode step — including exact ties.

    Non-floating logits are rejected: an integer array here is almost
    always token ids passed where logits belong, and argmaxing ids
    "works" while silently decoding garbage."""
    logits = np.asarray(logits)
    if not np.issubdtype(logits.dtype, np.floating):
        raise TypeError(
            f"greedy_sample expects floating-point logits, got dtype "
            f"{logits.dtype} (token ids passed where logits belong?)")
    return np.argmax(logits, axis=-1)


class _InflightStep:
    """One launched-but-not-retired device step (the depth-1
    dispatch-ahead window): the requests it covers, the draft map and
    per-slot lengths (verify only), the un-materialized device arrays
    (token ids + finite flags), and the launch-time clock — the
    timestamp device-side failures are anchored to when they are
    observed a step later."""

    __slots__ = ("kind", "running", "drafts", "lengths", "ids",
                 "finite", "launched_at")

    def __init__(self, kind, running, ids, finite, launched_at,
                 drafts=None, lengths=None):
        self.kind = kind                  # "decode" | "verify"
        self.running = running
        self.ids = ids
        self.finite = finite
        self.launched_at = launched_at
        self.drafts = drafts
        self.lengths = lengths


class _Handoff:
    """One finished prefill waiting to move pools (``enable_disagg``):
    the request, plus — under pipelining — the un-materialized
    (token ids, finite flags) handles of its final chunk's fused
    sampling, consumed when the hand-off processes next step."""

    __slots__ = ("req", "handles")

    def __init__(self, req, handles=None):
        self.req = req
        self.handles = handles


class InferenceServer:
    """Batched GPT inference with KV-cache + continuous batching.

    Args (beyond :class:`DecodeEngine`'s, which pass through —
    including ``kv_quant="int8"``, the quantized KV pool with its
    per-slot per-head scale sidecar; ``APEX_TPU_KV_QUANT=int8`` is
    its env twin, the kwarg wins — ``docs/serving.md``, "Quantized
    KV cache"):
      sample_fn: LEGACY escape hatch — (…, V) numpy logits -> (…,)
        token ids, run on host with per-step (B, V) logits.  Passing
        one warns loudly: it forces the synchronous logits path
        (speculation + pipeline OFF) and ignores per-request
        ``SamplingParams``.  For temperature/top-k/top-p use
        ``submit(..., sampling=SamplingParams(...))`` instead — the
        on-device sampling suite keeps both fast paths ON with
        deterministic counter-keyed streams (``docs/serving.md``,
        "Stochastic sampling").
      max_waiting: bound on the waiting queue; a submit past it comes
        back already finished with ``finish_reason="rejected"``
        (explicit backpressure at the front door).
      clock: wall-deadline time source (monotonic seconds) —
        injectable so deadline tests never sleep.
      enable_prefix_cache: block-level prefix sharing at admission
        (:mod:`serving.prefix_cache`) — shared-prefix traffic skips
        re-prefilling cached full blocks.  Opt out for strictly
        private workloads or A/B baselines.
      enable_chunked_prefill: split long prefill tails into
        ``prefill_chunk``-token chunks, one per iteration, so a long
        prompt stalls running decodes by at most one chunk.  Opt out
        to restore monolithic bucketed prefills.
      prefill_chunk: chunk width in tokens (default
        ``min(256, max_context)``); ignored when chunked prefill is
        off.
      enable_speculation: speculative decoding with bit-exact greedy
        acceptance (``docs/serving.md``): each decode iteration,
        requests with a draft feed the pending token plus up to
        ``spec_tokens`` guesses through the fixed-width verify program
        and keep the longest prefix matching the model's own argmax,
        plus the model's next token — up to ``spec_tokens + 1`` tokens
        per engine step, bit-identical output by construction.
        Stochastic requests (``SamplingParams``) keep speculation ON
        via rejection sampling — acceptance compares drafts against
        each column's counter-keyed sample, so the output
        distribution (and, by the Gumbel-max coupling, the exact
        stream) is unchanged.  A legacy custom ``sample_fn`` still
        disables speculation, loudly.  Opt out for strictly
        non-repetitive traffic where drafting is pure overhead.
      spec_tokens: max drafted tokens per verify step (default 4); the
        verify program is ``spec_tokens + 1`` columns wide and
        compiles once.
      enable_pipeline: the dispatch-ahead step loop
        (``docs/serving.md``, "Pipelined serve loop"): decode/verify
        steps launch the engine's fused on-device-sampling programs
        and their results are retired at the START of the next
        iteration, so host scheduling overlaps device compute and the
        per-step transfer is token ids, not logits.  Output is
        bit-identical to the synchronous loop (sampling — argmax or
        counter-keyed stochastic — is computed by the same rule on
        device; every host decision sees post-retire state).
        Stochastic requests keep the pipeline ON; a legacy custom
        ``sample_fn`` needs the logits on host and falls back to the
        synchronous path, loudly.  Opt out to restore the strictly
        serial loop.
      draft_source: the :class:`serving.speculation.DraftSource`
        proposing drafts (default: zero-weight
        :class:`~serving.speculation.NgramDraft` prompt-lookup over
        each request's own history; pass a small-model drafter to run
        classic two-model speculation — acceptance, and therefore
        output, is identical either way).
      overload_policy: the :class:`serving.overload.OverloadPolicy`
        driving priority-aware load shedding (queue-full
        displacement, pressure shedding of best-effort waiting work,
        worst-priority preemption).  Default: a policy with stock
        thresholds; ``enable_overload=False`` opts out (queue-full
        strictly rejects, preemption is youngest-first).
      breaker: the :class:`apex_tpu.resilience.CircuitBreaker`
        guarding ``submit`` (default: stock thresholds on the
        server's ``clock``); after a streak of non-finite/OOM
        failures submissions fast-reject with
        ``finish_reason="breaker_open"`` until a half-open probe
        completes.  ``enable_breaker=False`` opts out.
      registry: the :class:`apex_tpu.observability.MetricsRegistry`
        holding every counter/gauge/histogram this server feeds
        (default: a fresh private one).  Pass a shared registry to
        co-scrape serving and training metrics from one snapshot.
      tracer: span tracer for the step-loop phases
        (admit / prefix-match / chunk-prefill / decode / evict /
        preempt) and per-request lifecycle instants; default is the
        process tracer (``APEX_TPU_TRACE`` turns it on, else a
        zero-overhead no-op — ``docs/observability.md``).
      slo_policy: per-priority-class SLO targets
        (:class:`observability.SLOPolicy`) behind the
        ``stats()["slo"]`` attainment/goodput block; the stock policy
        has no latency bounds (attainment = healthy completion +
        deadline holds) — pin real TTFT/decode budgets per class to
        make goodput mean something (``docs/observability.md``,
        "SLO & goodput").
      flight_recorder: a
        :class:`observability.FlightRecorder` enabling step-level
        postmortem capture — one structured record per :meth:`step`
        (batch composition, admit/shed/preempt/evict decisions,
        memory occupancy, speculation outcomes, pressure, breaker
        state) in a bounded ring.  Default: a fresh recorder when
        ``postmortem_dir`` (or ``APEX_TPU_POSTMORTEM``) is set, else
        the zero-allocation ``NULL_FLIGHT_RECORDER``.
      postmortem_dir: where auto-dumped postmortem bundles land
        (breaker-open transitions, :meth:`audit` failures, watchdog
        stalls; chaos-soak invariant violations via
        :func:`resilience.chaos.run_soak`).
        ``APEX_TPU_POSTMORTEM=/dir`` is the env twin.  On-demand
        bundles go wherever :meth:`dump_postmortem` is pointed.
      enable_program_accounting: per-compiled-program launch tallies
        (``docs/observability.md``, "Ops plane & watchdog"; ON by
        default): every engine program launch — prefill / chunk /
        decode / verify, logits and sampled twins, per bucket/width
        key — feeds the pinned ``stats()["programs"]`` table and the
        ``serving_program_*`` registry counters with call count, host
        wall time, and compile count/time, so "where does the step
        go" is answerable per program.  Accounting never feeds back
        into scheduling; opt out to shave the per-launch clock reads.
      watchdog: a :class:`observability.HangWatchdog` arming hang
        detection on this server's step loop: :meth:`step` feeds it
        heartbeats, and a step (or a step *gap* with work pending)
        exceeding the watchdog's deadline dumps every thread's stack
        plus a postmortem bundle (under ``postmortem_dir``, when
        set), flips the ops plane's ``/healthz`` to 503, and
        increments ``serving_watchdog_stalls`` — exactly once per
        stall.  Default: disabled at zero per-step cost
        (``NULL_WATCHDOG``).  The server installs its stall handler
        and starts the watchdog thread; :meth:`close` stops it.
      ops_port: turn on the embedded HTTP ops plane
        (:class:`observability.OpsServer`) on this loopback port
        (0 = ephemeral; the bound port is ``server.ops.port``):
        ``/healthz``, ``/metrics``, ``/statusz``,
        ``/debug/flight``, ``/debug/requests/<uid>``,
        ``POST /drain`` / ``/postmortem``.  Default: off
        (``APEX_TPU_OPS_PORT`` is the env twin).  While attached,
        :meth:`step` serializes against ops reads through the ops
        lock; without it the loop takes no lock at all.

      enable_disagg: disaggregated prefill/decode pools
        (``docs/serving.md``, "Disaggregated prefill/decode"; OFF by
        default): a second engine with its OWN KV pool runs every
        prefill (and hosts the prefix cache), and the main engine
        becomes a pure-decode pool — finished prefills hand their
        blocks over through the fixed-shape cross-pool block copy one
        step after their final chunk, so long-prompt bursts queue
        against prefill capacity instead of inflating the decode
        inter-token tail.  Output is bit-exact vs the monolithic
        loop; speculation, the pipelined loop, and stochastic
        sampling stay ON in the decode pool.
      disagg_prefill_blocks: the prefill pool's size in blocks
        (incl. its own garbage block 0); default
        ``prefill_max_concurrent`` full-context prefills + 1.  This
        is RESERVED capacity the decode batch cannot borrow — budget
        it from the same HBM the monolithic pool would have used.
      prefill_max_concurrent: prefill-pool scheduler slots — the
        bound on chunk launches per step, i.e. the prefill duty
        cycle protecting the decode cadence (default 2).
      handoff_sink: cross-replica hand-off hook
        (``(request, payload) -> bool``): when set, finished prefills
        export their blocks as a checksummed host payload
        (:meth:`DecodeEngine.export_blocks`) and the sink — normally
        ``ReplicaRouter.handoff_sink_for`` — places the decode half
        on another replica (:meth:`ingest_handoff`); True moves
        ownership (this server finishes its half
        ``finish_reason="handoff"``), False falls back to the LOCAL
        decode pool.
      enable_streaming: per-token delivery (docs/serving.md,
        "Streaming & cancellation"): a :class:`StreamBroker` fans
        every retired token out to :meth:`stream` consumers at the
        point it is applied, and :meth:`cancel` frees a request's
        blocks/holds mid-decode with ``finish_reason="cancelled"``
        (cancel works even with streaming disabled).  Default on —
        the broker is O(1) no-op work per token when nobody streams.
      stream_queue_tokens: per-stream bounded queue depth; a slower
        consumer drops the oldest queued notification (backfilled on
        the next read) instead of ever stalling ``step()``.
      enable_kv_offload: hierarchical KV offload (docs/serving.md,
        "Hierarchical KV offload"; OFF by default, env twin
        ``APEX_TPU_KV_OFFLOAD``): cold evictable prefix-cache blocks
        demote into a bounded host-RAM store — optionally spilling
        to ``kv_offload_dir`` with checksummed atomic writes —
        instead of dying at eviction, and promote back into fresh
        device blocks (checksummed ``import_blocks``) when a later
        admission's radix walk wants them, so a cache hit spans
        device -> host -> disk at fixed HBM.  Every integrity or
        capacity failure on the offload path falls back to cold
        prefill bit-identically.
      kv_offload_host_bytes: the host-RAM tier's byte bound
        (default 64 MiB); coldest entries past it spill or drop.
      kv_offload_dir: optional disk spill tier directory; surviving
        entries are re-adopted on construction (content-addressed).
      kv_transport: the KV transport backend (``docs/serving.md``,
        "KV transport") the offload promote path rides — a
        :class:`~apex_tpu.serving.transport.KVTransport`; default a
        fresh :class:`~apex_tpu.serving.transport.InProcessTransport`
        on this server's clock (behavior-identical to the direct
        import call it wraps).  The server registers its ``"offload"``
        ingest peer on it; ``stats()["transport"]`` reports the
        envelope counters either way.

    Example::

        server = InferenceServer(cfg, params, max_batch_size=8)
        outs = server.generate(prompts, max_new_tokens=64, eos_id=50256)
    """

    def __init__(self, cfg, params, *,
                 max_batch_size: int = 8,
                 max_context: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 block_size: int = 16,
                 cache_dtype=None,
                 kv_quant: Optional[str] = None,
                 attention_fn=None,
                 prefill_buckets=None,
                 mesh=None,
                 tp_rules=None,
                 tp_axis: str = "model",
                 sample_fn: Optional[Callable] = None,
                 max_waiting: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 enable_prefix_cache: bool = True,
                 enable_chunked_prefill: bool = True,
                 prefill_chunk: Optional[int] = None,
                 enable_speculation: bool = True,
                 spec_tokens: Optional[int] = None,
                 draft_source: Optional[DraftSource] = None,
                 enable_pipeline: bool = True,
                 enable_overload: bool = True,
                 overload_policy: Optional[OverloadPolicy] = None,
                 enable_breaker: bool = True,
                 breaker: Optional[CircuitBreaker] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer=None,
                 slo_policy: Optional[SLOPolicy] = None,
                 flight_recorder: Optional[FlightRecorder] = None,
                 postmortem_dir: Optional[str] = None,
                 enable_program_accounting: bool = True,
                 watchdog: Optional[HangWatchdog] = None,
                 ops_port: Optional[int] = None,
                 enable_disagg: bool = False,
                 disagg_prefill_blocks: Optional[int] = None,
                 prefill_max_concurrent: int = 2,
                 handoff_sink: Optional[Callable] = None,
                 enable_streaming: bool = True,
                 stream_queue_tokens: int = 256,
                 enable_kv_offload: Optional[bool] = None,
                 kv_offload_host_bytes: int = 64 << 20,
                 kv_offload_dir: Optional[str] = None,
                 kv_transport: Optional[KVTransport] = None,
                 enable_journeys: Optional[bool] = None,
                 journey_replica: str = "server"):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.tracer = tracer if tracer is not None else get_tracer()
        # flight recorder (docs/observability.md, "Flight recorder &
        # postmortems"): explicitly passed, or resolved on by a
        # postmortem destination, else the zero-allocation null
        self._postmortem_dir = (postmortem_dir
                                or os.environ.get(POSTMORTEM_ENV))
        if flight_recorder is not None:
            self.recorder = flight_recorder
        else:
            self.recorder = (FlightRecorder() if self._postmortem_dir
                             else NULL_FLIGHT_RECORDER)
        self.slo = SLOTracker(slo_policy, registry=self.registry)
        # per-compiled-program accounting (docs/observability.md,
        # "Ops plane & watchdog"): observation only, so on by default
        self.programs = (ProgramAccounting(registry=self.registry)
                         if enable_program_accounting
                         else NULL_PROGRAM_ACCOUNTING)
        # quantized KV pool (docs/serving.md, "Quantized KV cache"):
        # the APEX_TPU_KV_QUANT env twin turns it on fleet-wide
        # without touching call sites; a PROVIDED kwarg wins — None
        # means "not provided" (defer to the env), so a caller that
        # must stay full-width under any environment pins
        # kv_quant="off" (the bench's legacy arms do)
        if kv_quant is None:
            kv_quant = os.environ.get(KV_QUANT_ENV)
        self.kv_quant = resolve_kv_quant(kv_quant)
        self.engine = DecodeEngine(
            cfg, params, max_batch_size=max_batch_size,
            max_context=max_context, num_blocks=num_blocks,
            block_size=block_size, cache_dtype=cache_dtype,
            kv_quant=self.kv_quant,
            attention_fn=attention_fn, prefill_buckets=prefill_buckets,
            tracer=self.tracer, programs=self.programs,
            mesh=mesh, tp_rules=tp_rules, tp_axis=tp_axis)
        self.failures = CounterMeter(registry=self.registry,
                                     name="serving_failures",
                                     label="reason")
        self.prefix = CounterMeter(registry=self.registry,
                                   name="serving_prefix", label="event")
        self.prefill_chunk = None
        if enable_chunked_prefill:
            self.prefill_chunk = int(
                prefill_chunk if prefill_chunk is not None
                else min(DEFAULT_PREFILL_CHUNK, self.engine.max_context))
        self.overload_policy = (
            overload_policy if overload_policy is not None
            else OverloadPolicy()) if enable_overload else None
        # predictive admission (docs/resilience.md): learns service
        # rates from finished timelines and sheds provably
        # deadline-doomed arrivals at the front door.  Gated on the
        # policy flag so the default server carries no estimator at
        # all — cold-start behavior is byte-identical either way.
        self.admission = (
            AdmissionEstimator(
                min_history=self.overload_policy.admission_min_history,
                margin=self.overload_policy.admission_margin)
            if self.overload_policy is not None
            and self.overload_policy.predictive_admission else None)
        # disaggregated prefill/decode pools (docs/serving.md,
        # "Disaggregated prefill/decode"; OFF by default): a second
        # engine with its OWN KV pool runs every prefill, and the main
        # engine becomes a pure-decode pool — the two pools' programs
        # share no array, so their device compute never serializes
        # through a common pool version.  Finished prefills hand their
        # blocks to the decode pool via the fixed-shape cross-pool
        # block copy, one step after their final chunk launches.
        self.disagg = bool(enable_disagg)
        self.handoff_sink = handoff_sink
        self.prefill_engine = None
        self.prefill_scheduler = None
        self._handoff: "deque" = None
        if self.disagg:
            if prefill_max_concurrent < 1:
                raise ValueError(
                    f"prefill_max_concurrent must be >= 1, got "
                    f"{prefill_max_concurrent}")
            if disagg_prefill_blocks is None:
                # room for prefill_max_concurrent full-context
                # prefills plus the garbage block — the prefill pool's
                # slack doubles as the shared-prefix cache's home
                disagg_prefill_blocks = (
                    prefill_max_concurrent * self.engine.blocks_per_seq
                    + 1)
            if disagg_prefill_blocks < self.engine.blocks_per_seq + 1:
                raise ValueError(
                    f"disagg_prefill_blocks={disagg_prefill_blocks} "
                    f"cannot hold one full-context prefill "
                    f"({self.engine.blocks_per_seq} blocks + garbage)")
            self.prefill_engine = DecodeEngine(
                cfg, params, max_batch_size=1,
                max_context=self.engine.max_context,
                num_blocks=int(disagg_prefill_blocks),
                block_size=block_size, cache_dtype=cache_dtype,
                kv_quant=self.kv_quant,
                attention_fn=attention_fn,
                prefill_buckets=prefill_buckets,
                tracer=self.tracer, programs=self.programs,
                mesh=mesh, tp_rules=tp_rules, tp_axis=tp_axis)
        # the prefix cache lives with whichever pool runs prefills:
        # the prefill pool under disaggregation (its released blocks
        # become the warm shared-prefix cache), the single pool
        # otherwise
        cache_alloc = (self.prefill_engine.allocator if self.disagg
                       else self.engine.allocator)
        self.prefix_cache = (
            PrefixCache(cache_alloc, self.engine.block_size,
                        counters=self.prefix)
            if enable_prefix_cache else None)
        # hierarchical KV offload (docs/serving.md, "Hierarchical KV
        # offload"; OFF by default): cold evictable prefix blocks
        # demote into a bounded host-RAM store (optionally spilling
        # to disk) instead of dying, and promote back through the
        # checksummed import_blocks path at admission-time cache
        # hits.  The APEX_TPU_KV_OFFLOAD env twin turns it on
        # fleet-wide; a PROVIDED kwarg wins (None = defer to env), so
        # legacy bench/chaos arms pin enable_kv_offload=False.  The
        # meters exist unconditionally (stats()/flight records are
        # shape-stable offload-on or -off); the store and the cache
        # attachment only when enabled.
        if enable_kv_offload is None:
            enable_kv_offload = os.environ.get(KV_OFFLOAD_ENV)
        self.kv_offload = resolve_kv_offload(enable_kv_offload)
        # KV transport (docs/serving.md, "KV transport"): the offload
        # promote path — the one cross-pool block movement a bare
        # server owns — rides the policy envelope (deadline / retry /
        # breaker / exactly-once dedup).  The default in-process
        # backend on the server's clock is behavior-identical to the
        # direct import call it wraps: zero extra RNG draws, zero
        # extra branches on the healthy path.  The ingest handler
        # resolves the cache-home engine at CALL time so chaos
        # wrappers installed post-construction intercept.
        self.kv_transport = kv_transport if kv_transport is not None \
            else InProcessTransport(policy=TransportPolicy(clock=clock))
        self.kv_transport.register_peer("offload", self._offload_ingest)
        self.offload = CounterMeter(registry=self.registry,
                                    name="serving_offload",
                                    label="event")
        self.offload_promote = self.registry.histogram(
            "serving_offload_promote_s")
        self.offload_store: Optional[OffloadStore] = None
        if self.kv_offload:
            if self.prefix_cache is None:
                raise ValueError(
                    "enable_kv_offload requires the prefix cache "
                    "(enable_prefix_cache=True) — the offload tiers "
                    "extend its radix index")
            self.offload_store = OffloadStore(
                host_bytes=kv_offload_host_bytes,
                spill_dir=kv_offload_dir,
                counters=self.offload)
            # export/import closures resolve the cache-home engine at
            # CALL time: under disagg the prefill pool is the cache
            # home, and chaos wrappers installed post-construction
            # (server.engine = ChaosEngine(...)) must intercept
            self.prefix_cache.attach_offload(
                self.offload_store,
                lambda ids: (self.prefill_engine if self.disagg
                             else self.engine).export_blocks(
                                 ids, per_block_crc=True),
                lambda ids, payload: self.kv_transport.send(
                    "offload",
                    {"op": "promote",
                     "blocks": [int(b) for b in ids]},
                    payload),
                counters=self.offload,
                promote_hist=self.offload_promote,
                clock=clock)
        # journey correlation plane (docs/observability.md, "Request
        # journeys & exemplars"; OFF by default): one JourneyLog per
        # server, labeled with this replica's name and wired to the
        # injected iteration counter + clock — hop ordering rides the
        # traveling JourneyContext, never wall clocks.  The
        # APEX_TPU_JOURNEYS env twin arms it fleet-wide; a PROVIDED
        # kwarg wins (None = defer to env).  Disabled keeps the
        # zero-allocation NULL log (tests/L0/test_journey.py pins it
        # with tracemalloc).
        if enable_journeys is None:
            enable_journeys = os.environ.get(JOURNEYS_ENV)
        self.journeys = (
            JourneyLog(replica=journey_replica,
                       iter_source=lambda: self._iter, clock=clock)
            if resolve_journeys(enable_journeys)
            else NULL_JOURNEY_LOG)
        self.scheduler = Scheduler(
            self.engine.allocator,
            max_batch_size=self.engine.max_batch_size,
            block_size=self.engine.block_size,
            max_context=self.engine.max_context,
            max_waiting=None if self.disagg else max_waiting,
            counters=self.failures,
            prefix_cache=None if self.disagg else self.prefix_cache,
            chunk_size=self.prefill_chunk,
            overload=self.overload_policy,
            tracer=self.tracer, journeys=self.journeys)
        if self.disagg:
            self.prefill_scheduler = Scheduler(
                self.prefill_engine.allocator,
                max_batch_size=int(prefill_max_concurrent),
                block_size=self.engine.block_size,
                max_context=self.engine.max_context,
                max_waiting=max_waiting,
                counters=self.failures,
                prefix_cache=self.prefix_cache,
                chunk_size=self.prefill_chunk,
                overload=self.overload_policy,
                tracer=self.tracer, journeys=self.journeys)
            # ONE terminal ledger across both pools: a request finishes
            # exactly once, wherever it is, and every consumer of
            # scheduler.finished (finalize, soaks, benches) sees it
            self.prefill_scheduler.finished = self.scheduler.finished
            self._handoff = deque()
        self.handoffs = CounterMeter(registry=self.registry,
                                     name="serving_handoff",
                                     label="event")
        self.handoff_pending = GaugeMeter(registry=self.registry,
                                          name="serving_handoff_pending")
        self.sample_fn = sample_fn or greedy_sample
        if self.sample_fn is not greedy_sample:
            # the historical escape hatch, now a LOUD downgrade: a
            # custom sample_fn needs materialized host logits, which
            # turns OFF speculative decoding AND the pipelined loop
            # and ignores per-request SamplingParams.  The supported
            # stochastic path (docs/serving.md, "Stochastic
            # sampling") keeps both fast paths on.
            warnings.warn(
                "custom sample_fn disables the serving fast paths: "
                "speculative decoding and the pipelined "
                "(dispatch-ahead) serve loop fall back to the "
                "synchronous logits path, and per-request "
                "SamplingParams are ignored.  Pass "
                "SamplingParams(temperature=..., top_k=..., "
                "top_p=..., seed=...) per request instead — the "
                "on-device sampling suite keeps speculation and the "
                "pipeline ON (docs/serving.md, 'Stochastic "
                "sampling').", UserWarning, stacklevel=2)
        # per-class request accounting for stats()["sampling"]
        # (greedy / temperature / top_k / top_p / top_k_top_p)
        self.sampling_classes = CounterMeter(
            registry=self.registry, name="serving_sampling_requests",
            label="class")
        self.clock = clock
        # speculation (docs/serving.md): greedy-only by contract — the
        # acceptance rule compares drafts against argmax rows, which
        # only reproduces plain decode when sampling IS argmax
        self.spec_tokens = int(spec_tokens if spec_tokens is not None
                               else DEFAULT_SPEC_TOKENS)
        if self.spec_tokens < 1:
            raise ValueError(
                f"spec_tokens must be >= 1, got {self.spec_tokens}")
        self.draft_source = (draft_source if draft_source is not None
                             else NgramDraft())
        self.speculating = bool(enable_speculation
                                and self.sample_fn is greedy_sample)
        # pipelined serve loop (docs/serving.md, "Pipelined serve
        # loop"): greedy-only by contract — sampling must happen on
        # device for the host to skip materializing logits, and the
        # fused programs sample by argmax
        self.pipelining = bool(enable_pipeline
                               and self.sample_fn is greedy_sample)
        self._inflight: Optional[_InflightStep] = None
        self._pending_produced = 0   # retired outside step() (submit)
        self.pipe = CounterMeter(registry=self.registry,
                                 name="serving_pipeline", label="event")
        self.spec = CounterMeter(registry=self.registry,
                                 name="serving_speculation",
                                 label="event")
        # per-verify-step draft/accept depth distributions — token
        # counts, not seconds, so they get a count-shaped ladder
        # (1..64 at 2x: buckets 0/1, 2, 4, 8, ... — exact at small K)
        self.spec_drafted_hist = self.registry.histogram(
            "serving_spec_drafted_tokens", low=1.0, high=64.0)
        self.spec_accepted_hist = self.registry.histogram(
            "serving_spec_accepted_tokens", low=1.0, high=64.0)
        self.breaker_events = CounterMeter(registry=self.registry,
                                           name="serving_breaker",
                                           label="event")
        self.breaker = (
            breaker if breaker is not None
            else CircuitBreaker(clock=clock,
                                counters=self.breaker_events)
        ) if enable_breaker else None
        if self.breaker is not None and self.breaker.counters is None:
            # a caller-built breaker without its own counters reports
            # through the server's registry, so stats() reconciles
            self.breaker.counters = self.breaker_events
        self.oom = CounterMeter(registry=self.registry,
                                name="serving_oom", label="site")
        self._draining = False
        self._closed = False
        self._final_stats: Optional[dict] = None
        self.queue_depth = GaugeMeter(registry=self.registry,
                                      name="serving_queue_depth")
        self.pressure_gauge = GaugeMeter(registry=self.registry,
                                         name="serving_pressure")
        self.occupancy = GaugeMeter(registry=self.registry,
                                    name="serving_batch_occupancy")
        self.chunk_iters = GaugeMeter(   # chunk prefills per iteration
            registry=self.registry, name="serving_chunk_iters")
        self.tokens = RateMeter()
        # latency distributions fed by the per-request timelines
        # (enqueue -> admit -> first token -> finish) and the step loop
        hist = self.registry.histogram
        self.ttft = hist("serving_ttft_s")
        self.queue_wait = hist("serving_queue_wait_s")
        self.decode_latency = hist("serving_decode_token_s")
        # per-token inter-token-latency gaps (the wall gap before each
        # token after a request's first) — the per-TOKEN tail the
        # disaggregation bench floors, vs decode_latency's per-request
        # average (docs/observability.md, "SLO & goodput")
        self.itl = hist("serving_itl_s")
        self.step_time = hist("serving_step_s")
        # per-step phase-composition counts for the flight record
        # (prefill tokens vs decode tokens vs verify columns) — bound
        # to a dict only while a recorder is on, so the disabled path
        # stays allocation-free
        self._phase: Optional[dict] = None
        # pipeline overlap split (stats()["pipeline"]): retire-wait is
        # the host blocked on device results (device-bound time); plan
        # is the host's scheduling+launch work, which the device
        # overlaps when pipelining is on (host-bound time).  A
        # well-overlapped step costs ~max of the two, a serial step
        # their sum.
        self.retire_wait = hist("serving_retire_wait_s")
        self.plan_time = hist("serving_plan_s")
        # per-priority-class queue-wait distributions, materialized as
        # classes are first seen (labeled series of the same metric)
        self._queue_wait_prio: Dict[int, object] = {}
        # memory observability (docs/observability.md, "Memory
        # accounting"): per-step occupancy/fragmentation gauges — the
        # current/peak/avg view behind stats()["memory"]; the flight
        # recorder carries the per-step time series
        self.mem_live = GaugeMeter(registry=self.registry,
                                   name="serving_kv_live_blocks")
        self.mem_free = GaugeMeter(registry=self.registry,
                                   name="serving_kv_free_blocks")
        self.mem_evictable = GaugeMeter(
            registry=self.registry, name="serving_kv_evictable_blocks")
        self.mem_frag = GaugeMeter(registry=self.registry,
                                   name="serving_kv_frag_slots")
        self._iter = 0              # scheduler iterations served
        self._finalized = 0         # scheduler.finished timeline cursor
        self._rec_cursor = 0        # flight-recorder finished cursor
        self._last_breaker_state = (self.breaker.state
                                    if self.breaker is not None
                                    else "disabled")
        # hang watchdog (docs/observability.md, "Ops plane &
        # watchdog"): the server owns the stall handler — thread
        # stacks + postmortem bundle + counter — and the thread's
        # lifecycle; step() feeds heartbeats behind an
        # `enabled` guard, so the disabled default costs nothing
        self.watchdog = watchdog if watchdog is not None \
            else NULL_WATCHDOG
        self._watchdog_stalls = self.registry.counter(
            "serving_watchdog_stalls")
        if self.watchdog.enabled:
            self.watchdog.on_stall = self._on_watchdog_stall
            self.watchdog.start()
        # streaming delivery (docs/serving.md, "Streaming &
        # cancellation"): the broker fans retired tokens out to
        # per-request bounded queues on ITS OWN lock — never the ops
        # lock — so a slow consumer can't stall step()
        self.stream_broker: Optional[StreamBroker] = (
            StreamBroker(queue_tokens=stream_queue_tokens)
            if enable_streaming else None)
        # embedded HTTP ops plane: resolved off unless a port is
        # given (kwarg wins over APEX_TPU_OPS_PORT; 0 = ephemeral).
        # While attached, step()/stats() serialize through its lock.
        if ops_port is None:
            env_port = os.environ.get(OPS_PORT_ENV)
            if env_port not in (None, ""):
                ops_port = int(env_port)
        self.ops_requests = CounterMeter(registry=self.registry,
                                         name="serving_ops_requests",
                                         label="endpoint")
        self.ops: Optional[OpsServer] = None
        self._ops_lock = None
        if ops_port is not None:
            self.ops = OpsServer(self, port=ops_port,
                                 counters=self.ops_requests)
            self._ops_lock = self.ops.lock
            self.ops.start()

    # -- request lifecycle ------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None, *,
               priority: int = 0,
               deadline_iters: Optional[int] = None,
               deadline_s: Optional[float] = None,
               sampling: Optional[SamplingParams] = None,
               journey=None) -> Request:
        """Enqueue one request.

        ``max_new_tokens`` must be >= 1 and a prompt that leaves no
        room to generate within ``max_context`` is rejected with
        :class:`ValueError` (never silently capped to a <= 0 budget);
        a budget that merely overshoots the remaining context is capped
        down to fit.  ``priority`` is nice-style (0 = default
        foreground class; larger = lower priority, sheddable under
        overload — :mod:`serving.overload`).  Optional
        ``deadline_iters`` / ``deadline_s`` expire the request to
        ``finish_reason="timeout"``.

        ``sampling``: per-request :class:`SamplingParams`
        (temperature / top-k / top-p / seed; default greedy,
        bit-identical to the historical argmax path).  Stochastic
        requests keep BOTH fast paths — speculation and the pipelined
        loop — and are deterministic per (prompt, params, seed)
        thanks to counter-based keys (``docs/serving.md``,
        "Stochastic sampling").  Ignored (with a construction-time
        warning) when the server runs a legacy custom ``sample_fn``.

        A request can come back already finished instead of enqueued
        — always with ``finished_at`` stamped at submission and never
        entering the admission-latency histograms:
        ``finish_reason="rejected"`` (bounded queue full, no
        lower-priority work to displace), ``"breaker_open"`` (circuit
        breaker tripped), or ``"draining"`` (after :meth:`drain` /
        :meth:`close` began).  Submitting to a closed server raises
        :class:`RuntimeError`.  A queue-full submission may instead
        displace a lower-priority queued request, which then finishes
        ``"shed"`` during this call.

        ``journey``: an existing :class:`JourneyContext` to continue —
        the router passes the fleet-level context here on placement,
        failover re-enqueue, and torn-hand-off fallback so the
        request's hops keep one rid across replicas.  None (the
        default) starts a fresh journey keyed by the request ``uid``
        when journeys are enabled, and carries nothing when they are
        off."""
        with (self._ops_lock or _NO_LOCK):
            return self._submit(prompt, max_new_tokens, eos_id,
                                priority=priority,
                                deadline_iters=deadline_iters,
                                deadline_s=deadline_s,
                                sampling=sampling, journey=journey)

    def _submit(self, prompt, max_new_tokens, eos_id, *, priority,
                deadline_iters, deadline_s, sampling=None,
                journey=None) -> Request:
        """The :meth:`submit` body (runs under the ops lock when the
        HTTP ops plane is attached)."""
        if self._closed:
            raise RuntimeError(
                "InferenceServer is closed; no further submissions")
        # retire any launched-but-unretired step BEFORE the front
        # door decides anything: the breaker state, displacement
        # victims, and queue pressure must reflect the results of the
        # step the device already ran — the same state the synchronous
        # loop would show this submission (docs/serving.md,
        # "Pipelined serve loop")
        if self._inflight is not None:
            self._pending_produced += self._flush_window()
        prompt = [int(t) for t in prompt]
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        cap = self.engine.max_context - len(prompt)
        if cap <= 0:
            raise ValueError(
                f"prompt length {len(prompt)} leaves no room to "
                f"generate within max_context={self.engine.max_context}")
        if sampling is not None and not isinstance(sampling,
                                                   SamplingParams):
            raise TypeError(
                f"sampling must be a SamplingParams (or None for "
                f"greedy), got {type(sampling).__name__}")
        req = Request(prompt=prompt,
                      max_new_tokens=min(int(max_new_tokens), cap),
                      eos_id=eos_id,
                      priority=int(priority),
                      deadline_iters=deadline_iters,
                      deadline_s=deadline_s,
                      submit_iter=self._iter,
                      submitted_at=self.clock(),
                      sampling=sampling if sampling is not None
                      else SamplingParams())
        self.sampling_classes.incr(req.sampling.klass)
        if self.journeys.enabled:
            # continue the router's context when one travels in, else
            # open a fresh journey keyed by this request's uid (the
            # bare-server case); the front-door hop lands even for
            # submissions turned away below — their journey is just
            # enqueue -> finish
            req.journey = (journey if journey is not None
                           else self.journeys.start(req.uid))
            self.journeys.hop(req.journey, "enqueue", uid=req.uid,
                              prompt_tokens=len(prompt),
                              priority=req.priority)
        if self.tracer.enabled:
            if req.journey is not None:
                self.tracer.instant("request_enqueue", uid=req.uid,
                                    prompt_tokens=len(prompt),
                                    priority=req.priority,
                                    rid=req.journey.rid)
            else:
                self.tracer.instant("request_enqueue", uid=req.uid,
                                    prompt_tokens=len(prompt),
                                    priority=req.priority)
        if self._draining:
            return self._finish_at_submit(req, reasons.DRAINING)
        if self.breaker is not None and not self.breaker.allow():
            return self._finish_at_submit(req, reasons.BREAKER_OPEN)
        # predictive admission: a wall-deadlined arrival that cannot
        # meet its deadline even at the fastest service ever observed
        # for its class is shed HERE, before any prefill is spent on
        # it (docs/resilience.md, "Overload policy & lifecycle")
        if self.admission is not None and self.admission.doomed(req):
            return self._finish_at_submit(req, reasons.SHED)
        try:
            # under disaggregation every request enters through the
            # prefill pool's queue; the decode pool only ever admits
            # via the hand-off
            (self.prefill_scheduler if self.disagg
             else self.scheduler).submit(req)
        except QueueFullError:
            return self._finish_at_submit(req, reasons.REJECTED)
        # a displaced victim may have finished "shed" inside
        # scheduler.submit: stamp its finished_at at submission time
        if self._finalized < len(self.scheduler.finished):
            self._finalize_finished()
        return req

    def _finish_at_submit(self, req: Request, reason: str) -> Request:
        """Finish ``req`` without ever enqueueing it (rejected /
        breaker_open / draining): terminal reason set, failure
        counted, and ``finished_at`` stamped NOW — submit-time
        rejections must not wait for the next step to close their
        timeline, and being never-admitted they stay out of the
        TTFT/queue-wait histograms."""
        req.finished = True
        req.finish_reason = reason
        self.scheduler.finished.append(req)
        self.failures.incr(f"requests_failed_{reason}")
        self._finalize_finished()
        return req

    def _expire_deadlines(self) -> None:
        """Fail every live request whose iteration or wall budget is
        spent — waiting requests too, so a queue stall cannot hold a
        request past its deadline (both pools under disaggregation)."""
        now = self.clock()
        for sched in self._schedulers():
            for req in (list(sched.waiting)
                        + list(sched.running.values())):
                if req.finished:
                    continue
                over_iters = (req.deadline_iters is not None and
                              self._iter - req.submit_iter
                              > req.deadline_iters)
                over_wall = (req.deadline_s is not None and
                             now - req.submitted_at >= req.deadline_s)
                if over_iters or over_wall:
                    sched.fail(req, reasons.TIMEOUT)

    def _schedulers(self):
        """Every live scheduler — ``(decode, prefill)`` under
        disaggregation, the single one otherwise."""
        if self.disagg:
            return (self.scheduler, self.prefill_scheduler)
        return (self.scheduler,)

    @property
    def has_work(self) -> bool:
        """Queued, running, launched-but-unretired, or
        pending-hand-off work anywhere on this server (both pools
        under disaggregation)."""
        if self.scheduler.has_work or self._inflight is not None:
            return True
        if self.disagg:
            return (self.prefill_scheduler.has_work
                    or bool(self._handoff))
        return False

    def pressure(self) -> float:
        """The server-level overload signal a router balances on: the
        max over this server's pools (``Scheduler.pressure``) — under
        disaggregation a saturated prefill pool reads as pressure even
        while the decode pool idles, and vice versa."""
        p = self.scheduler.pressure()
        if self.disagg:
            p = max(p, self.prefill_scheduler.pressure())
        return p

    def step(self) -> int:
        """One continuous-batching iteration: retire the previous
        iteration's launched decode/verify results (pipelined loop),
        expire deadlines, admit newly schedulable requests, advance
        ONE prefill chunk per prefilling request, then one decode step
        across the rest of the running batch — LAUNCHED without
        materialization when pipelining is on (its tokens retire at
        the start of the next step), sampled synchronously otherwise.
        Chunk prefills interleave with decode iterations, so a long
        prompt stalls running requests by at most one chunk — and a
        prefix-cache hit skips straight to its uncached tail.  Returns
        the number of tokens applied to requests this call (0 = idle,
        though chunk prefills may still have run; under pipelining a
        token counts when it is RETIRED, one step after its launch).
        Per-request failures (capacity / timeout / nonfinite / shed)
        finish the affected request alone, and a transient engine
        ``MemoryError`` skips the affected call for one iteration
        (retried bit-identically) — no exception escapes the step
        loop for them.

        Ops-plane integration (``docs/observability.md``, "Ops plane
        & watchdog"): an armed watchdog gets a heartbeat pair around
        every step — attribute stores, guarded out entirely when
        disabled — and, when the HTTP ops plane is attached, the step
        body runs under the ops lock so ``/statusz`` and the POST
        triggers read consistent state; a server without an ops plane
        takes no lock at all."""
        wd = self.watchdog
        if wd.enabled:
            wd.step_started()
        try:
            with (self._ops_lock or _NO_LOCK):
                return self._step()
        finally:
            if wd.enabled:
                wd.step_finished(self.scheduler.has_work)

    def _step(self) -> int:
        """The :meth:`step` body (see its docstring)."""
        if self.disagg:
            return self._step_disagg()
        sched, engine, tr = self.scheduler, self.engine, self.tracer
        rec = self.recorder
        self._iter += 1
        produced, self._pending_produced = self._pending_produced, 0
        step_start = self.clock()
        self._phase = None
        if rec.enabled:
            # pre-step marks for the flight record's per-step deltas
            # (plain int binds — the disabled path skips even these)
            preempt0 = sched.preemption_count
            lk_grant0 = sched.lookahead_granted
            lk_roll0 = sched.lookahead_rolled_back
            evict0 = self.prefix.count("prefix_evicted_blocks")
            oom0 = self.oom.total
            drafted0 = self.spec.count("drafted_tokens")
            accepted0 = self.spec.count("accepted_tokens")
            off0 = self._offload_marks()
            self._phase = self._new_phase()
        # RETIRE: consume the previous iteration's launched step before
        # any host decision — deadlines, shedding, admission, and
        # drafts below then see exactly the state the synchronous loop
        # would have had at this point (docs/serving.md, "Pipelined
        # serve loop")
        retired = self._flush_window()
        produced += retired
        plan_start = self.clock()
        self._expire_deadlines()

        # overload: record the pressure signal at its pre-shed peak,
        # then shed best-effort waiting work while the policy says so
        self.pressure_gauge.update(sched.pressure())
        shed = sched.shed_overload()
        if shed and tr.enabled:
            for r in shed:
                tr.instant("request_shed", uid=r.uid,
                           priority=r.priority)

        with tr.span("admit"):
            admitted = sched.admit()
        if admitted:
            now = self.clock()
            for req in admitted:
                if req.admitted_at is None:
                    req.admitted_at = now
                if tr.enabled:
                    tr.instant("request_admit", uid=req.uid,
                               cached_tokens=req.cached_prefix_tokens)
        # whole-context cache hits first duplicate their final shared
        # block (copy-on-write) so the tail re-write stays private
        cows = [r for r in sched._admit_order if r.pending_cow]
        if cows:
            try:
                with tr.span("cow_copy", blocks=len(cows)):
                    engine.copy_blocks([r.pending_cow for r in cows])
            except MemoryError:
                # transient HBM burst: nothing was accounted, the same
                # copies re-launch next iteration bit-identically
                self._note_oom("copy_blocks")
            else:
                for req in cows:
                    sched.cow_done(req)

        chunks = 0
        pipelined = self.pipelining
        for req in [r for r in sched._admit_order if r.prefilling]:
            tokens, start, is_last = sched.prefill_plan(req)
            # the per-request stochastic params ride the fused twin
            # only when this launch's token will actually be sampled
            # (final chunk of a fresh prefill) — mid-prefill chunks
            # and preemption re-prefills keep the greedy program
            samp1 = (sched.prefill_sampling(req)
                     if pipelined and is_last and req.prefill_sample
                     else None)
            # kwarg omitted when greedy so duck-typed engine wrappers
            # predating the stochastic twins keep working
            skw = {"sampling": samp1} if samp1 is not None else {}
            try:
                if (start == 0 and is_last
                        and self.prefill_chunk is None):
                    # no cached prefix, no chunking: the monolithic
                    # bucketed prefill (the pre-chunking path,
                    # bit-for-bit)
                    with tr.span("prefill", uid=req.uid,
                                 tokens=len(tokens)):
                        out = (engine.prefill_sampled(
                            tokens, req.block_table,
                            **skw) if pipelined
                            else engine.prefill(tokens,
                                                req.block_table))
                else:
                    with tr.span("chunk_prefill", uid=req.uid,
                                 tokens=len(tokens), start=start):
                        out = (engine.chunk_prefill_sampled(
                            tokens, start, req.block_table,
                            pad_to=self.prefill_chunk,
                            **skw) if pipelined
                            else engine.chunk_prefill(
                                tokens, start, req.block_table,
                                pad_to=self.prefill_chunk))
                    chunks += 1
            except MemoryError:
                # chunk_done not called: this exact chunk replays
                # next iteration, so generation stays bit-stable
                self._note_oom("prefill")
                continue
            if self._phase is not None:
                self._phase["prefill_launches"] += 1
                self._phase["prefill_tokens"] += len(tokens)
            done = sched.chunk_done(req, len(tokens))
            if not done or not req.prefill_sample:
                # mid-prefill, or resumed after preemption (the
                # pending token continues instead of these logits)
                continue
            # prefill sampling stays synchronous either way — the
            # sampled twin just shrinks the transfer to one id + one
            # flag; only decode/verify dispatch ahead (a prefill's
            # token gates whether the request joins THIS iteration's
            # decode launch, so deferring it would change scheduling)
            if pipelined:
                ids, fin = out
                if not bool(np.asarray(fin)[0]):
                    sched.fail(req, reasons.NONFINITE)
                    if self.breaker is not None:
                        self.breaker.record_failure()
                    continue
                tok = int(np.asarray(ids)[0])
            else:
                logits = np.asarray(out)
                if not np.all(np.isfinite(logits)):
                    sched.fail(req, reasons.NONFINITE)
                    if self.breaker is not None:
                        self.breaker.record_failure()
                    continue
                tok = self._sample_prefill_host(req, logits)
            req.record_token(tok)
            self._note_first_token(req)
            produced += 1
            if req.finished:
                sched.retire(req)
                if self.breaker is not None:
                    self.breaker.record_success()
        self.chunk_iters.update(chunks)
        if chunks:
            self.prefix.incr("prefill_chunks", chunks)

        if sched.running:
            for req in list(sched.running.values()):
                if req.running and not req.prefilling:
                    # an earlier pass may have preempted it; a False
                    # return means the request outgrew the pool with no
                    # victim left — it fails alone instead of raising
                    # into the batch
                    if not sched.ensure_decode_capacity(req):
                        sched.fail(req, reasons.CAPACITY)
            running = [r for r in sched.running.values()
                       if not r.prefilling]
            if running:
                drafts = (self._propose_drafts(running)
                          if self.speculating else {})
                if pipelined:
                    # LAUNCH: enqueue the device step and stash the
                    # un-materialized result handles; its tokens
                    # retire at the start of the next step() (or at
                    # the next submit(), whichever comes first)
                    if drafts:
                        self._launch_verify(running, drafts)
                    else:
                        self._launch_decode(running)
                elif drafts:
                    produced += self._verify_step(running, drafts)
                else:
                    produced += self._decode_step(running)

        if pipelined:
            self.plan_time.record(self.clock() - plan_start)
        self.tokens.update(produced)
        self.queue_depth.update(sched.num_waiting)
        self.occupancy.update(sched.num_running
                              / self.engine.max_batch_size)
        step_s = self.clock() - step_start
        self.step_time.record(step_s)
        self._finalize_finished()
        # memory occupancy gauges (docs/observability.md, "Memory
        # accounting") — sampled once per step like queue depth
        alloc = engine.allocator
        self.mem_live.update(alloc.num_live)
        self.mem_free.update(alloc.num_free)
        self.mem_evictable.update(
            self.prefix_cache.num_evictable
            if self.prefix_cache is not None else 0)
        self.mem_frag.update(sched.frag_slots())
        if rec.enabled:
            fin = sched.finished
            new_fin = fin[self._rec_cursor:]
            finished_now = [
                {"uid": r.uid, "reason": r.finish_reason,
                 "tokens": len(r.generated)}
                for r in new_fin]
            self._rec_cursor = len(fin)
            step_rec = {
                "iter": self._iter,
                "produced": produced,
                "waiting": sched.num_waiting,
                "running": [r.uid for r in sched._admit_order],
                "prefilling": [r.uid for r in sched._admit_order
                               if r.prefilling],
                "admitted": [r.uid for r in admitted],
                "shed": [{"uid": r.uid, "priority": r.priority,
                          "debt_tokens":
                          OverloadPolicy.slo_debt_tokens(r)}
                         for r in shed],
                "finished": finished_now,
                "preemptions": sched.preemption_count - preempt0,
                "evicted_blocks":
                    self.prefix.count("prefix_evicted_blocks") - evict0,
                "oom": self.oom.total - oom0,
                "spec": {
                    "drafted":
                        self.spec.count("drafted_tokens") - drafted0,
                    "accepted":
                        self.spec.count("accepted_tokens") - accepted0,
                },
                "pressure": round(self.pressure_gauge.val, 4),
                "breaker": (self.breaker.state
                            if self.breaker is not None
                            else "disabled"),
                "memory": {
                    "free": alloc.num_free,
                    "live": alloc.num_live,
                    "evictable": (self.prefix_cache.num_evictable
                                  if self.prefix_cache is not None
                                  else 0),
                    "frag_slots": sched.frag_slots(),
                    "lookahead_granted":
                        sched.lookahead_granted - lk_grant0,
                    "lookahead_rolled_back":
                        sched.lookahead_rolled_back - lk_roll0,
                },
                "pipeline": {
                    "pending": 1 if self._inflight is not None else 0,
                    "retired_tokens": retired,
                },
                "offload": self._offload_delta(off0),
                "phase": self._phase,
                "step_s": step_s,
            }
            if self.journeys.enabled:
                # journey correlation: uid -> rid for every request
                # this step touched (admitted or finished), so a
                # flight record joins onto journeys/traces without a
                # per-uid search.  Conditional — journey-less flight
                # records keep the legacy shape byte-for-byte.
                step_rec["rids"] = {
                    str(r.uid): r.journey.rid
                    for r in list(admitted) + new_fin
                    if r.journey is not None}
            rec.record(step_rec)
            self._phase = None
        # breaker-open transition: the moment worth a black box — dump
        # a bundle while the ring still holds the steps leading up
        if self.breaker is not None:
            state = self.breaker.state
            if state != self._last_breaker_state:
                self._last_breaker_state = state
                if state == "open":
                    self._auto_postmortem("breaker_open")
        return produced

    def _sample_prefill_host(self, req, logits) -> int:
        """Sample one request's prefill token from materialized
        ``(V,)`` logits — the synchronous loop's half of the sampling
        contract.  Greedy requests (and every request on a legacy
        custom ``sample_fn``) keep the historical ``sample_fn`` call
        byte-for-byte; stochastic requests draw through the SAME
        jitted :func:`ops.sample_tokens` the fused programs use, with
        the same counter key (the token's sequence index ==
        ``num_cached`` after the final chunk accounted), so the two
        loops emit identical streams."""
        if req.sampling.is_greedy or self.sample_fn is not greedy_sample:
            return int(self.sample_fn(logits))
        samp = self.scheduler.prefill_sampling(req)
        counter = np.asarray([req.num_cached], np.int32)
        ids, _fin = sample_tokens_host(logits[None], *samp, counter)
        return int(np.asarray(ids)[0])

    @staticmethod
    def _new_phase() -> dict:
        """A fresh per-step phase-composition record (the flight
        record's ``phase`` block): launches issued per program family
        this step and the tokens/columns each fed — the direct
        interference view (prefill tokens vs decode tokens vs verify
        columns per step) that ``tools/postmortem.py`` renders and
        ``--assert-complete`` reconciles against
        ``stats()["programs"]``."""
        return {"prefill_launches": 0, "prefill_tokens": 0,
                "decode_launches": 0, "decode_tokens": 0,
                "verify_launches": 0, "verify_columns": 0,
                "handoff_blocks": 0}

    # per-step offload deltas for the flight record (docs/serving.md,
    # "Hierarchical KV offload") — the tier-crossing view per
    # iteration, same mark/delta pattern as evicted_blocks/oom above
    _OFFLOAD_EVENTS = ("demotes", "promotes_host", "promotes_disk",
                       "spills", "crc_rejects")

    def _offload_marks(self) -> tuple:
        c = self.offload.count
        return tuple(c(k) for k in self._OFFLOAD_EVENTS)

    def _offload_delta(self, marks: tuple) -> dict:
        c = self.offload.count
        return {k: c(k) - m
                for k, m in zip(self._OFFLOAD_EVENTS, marks)}

    def _decode_inputs(self, running):
        """The decode launch arrays — (tokens, positions, tables),
        inactive slots zeroed — shared by the synchronous and
        pipelined paths."""
        engine = self.engine
        b, mb = engine.max_batch_size, engine.blocks_per_seq
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        tables = np.zeros((b, mb), np.int32)
        for req in running:
            tokens[req.slot] = req.next_input
            positions[req.slot] = req.num_cached
            tables[req.slot, :len(req.block_table)] = req.block_table
        return tokens, positions, tables

    def _decode_step(self, running) -> int:
        """One batched single-token decode over ``running``,
        materialized and applied in the same call (the synchronous
        loop; also the custom-``sample_fn`` path).  Returns tokens
        produced."""
        engine, tr = self.engine, self.tracer
        tokens, positions, tables = self._decode_inputs(running)
        try:
            with tr.span("decode", batch=len(running)):
                logits = np.asarray(
                    engine.decode(tokens, positions, tables))
        except MemoryError:
            # transient HBM burst: no request state moved, the
            # identical decode re-runs next iteration
            self._note_oom("decode")
            return 0
        self.spec.incr("decode_steps")
        if self._phase is not None:
            self._phase["decode_launches"] += 1
            self._phase["decode_tokens"] += len(running)
        finite = np.all(np.isfinite(logits), axis=-1)
        samp = (self.scheduler.sampling_inputs(running)
                if self.sample_fn is greedy_sample else None)
        if samp is None:
            toks = self.sample_fn(logits)
        else:
            # the synchronous stochastic path: the SAME jitted
            # sampler as the fused twin, fed the same counter keys
            # (each slot's next sequence index), so sync and
            # pipelined streams agree byte-for-byte
            counters = np.zeros((logits.shape[0],), np.int32)
            for req in running:
                counters[req.slot] = req.num_cached + 1
            toks = np.asarray(sample_tokens_host(
                logits, *samp, counters)[0])
        return self._apply_decode_results(running, toks, finite)

    def _launch_decode(self, running) -> bool:
        """The pipelined decode launch: enqueue the fused sampled
        program and stash its un-materialized (ids, finite) handles as
        the in-flight window — the host returns immediately and the
        results retire next step.  False = the launch OOMed (skipped
        and retried bit-identically, exactly like the synchronous
        path)."""
        sched, engine, tr = self.scheduler, self.engine, self.tracer
        tokens, positions, tables = self._decode_inputs(running)
        samp = sched.sampling_inputs(running)
        # the kwarg is omitted on all-greedy launches so duck-typed
        # engine wrappers (chaos injection, tests) predating the
        # stochastic twins keep working unchanged
        kw = {"sampling": samp} if samp is not None else {}
        try:
            with tr.span("launch", program="decode",
                         batch=len(running)):
                ids, fin = engine.decode_sampled(tokens, positions,
                                                 tables, **kw)
        except MemoryError:
            self._note_oom("decode")
            return False
        self.spec.incr("decode_steps")
        if self._phase is not None:
            self._phase["decode_launches"] += 1
            self._phase["decode_tokens"] += len(running)
        self._inflight = _InflightStep(
            "decode", list(running), ids, fin, self.clock())
        sched.hold_inflight(running)
        self.pipe.incr("launches")
        return True

    def _apply_decode_results(self, running, toks, finite,
                              now: Optional[float] = None) -> int:
        """Apply one decode step's sampled results to ``running`` —
        the retire half shared by both loops.  ``toks``/``finite`` are
        (B,) host arrays; ``now`` backdates breaker failures to the
        launch time (pipelined retire observes them a step late).
        Returns tokens produced.

        Step guard: a False ``finite`` flag means that row's logits
        went non-finite — the request is evicted before its garbage
        token enters termination logic; every finite row proceeds
        normally."""
        sched = self.scheduler
        produced = 0
        for req in running:
            if req.finished or not req.running:
                continue      # failed between launch and retire
            if not finite[req.slot]:
                sched.fail(req, reasons.NONFINITE)
                if self.breaker is not None:
                    self.breaker.record_failure(now)
                continue
            req.num_cached += 1
            req.record_token(int(toks[req.slot]))
            self._note_first_token(req)
            produced += 1
            if req.finished:
                sched.retire(req)
                if self.breaker is not None:
                    self.breaker.record_success()
            else:
                # index any block this token just filled so a later
                # shared-prefix request can match it
                sched.register_progress(req)
        self.spec.incr("decode_tokens", produced)
        return produced

    # -- speculative decoding (docs/serving.md) ---------------------------

    def _propose_drafts(self, running) -> Dict[int, List[int]]:
        """uid -> drafted tokens for this iteration: the draft
        source's guesses, capped by the request's remaining token
        budget (drafting past ``max_new_tokens`` is wasted verify
        width) and by the lookahead blocks the scheduler can grant
        without preempting anyone."""
        sched = self.scheduler
        drafts: Dict[int, List[int]] = {}
        for req in running:
            budget = min(self.spec_tokens,
                         req.max_new_tokens - len(req.generated) - 1)
            if budget < 1:
                continue
            d = self.draft_source.propose(
                req.prompt + req.generated, budget)[:budget]
            # a draft is a hint from arbitrary user code: truncate at
            # the first out-of-vocab id rather than feeding it to the
            # embedding gather
            for i, t in enumerate(d):
                if not 0 <= int(t) < self.engine.cfg.vocab_size:
                    d = d[:i]
                    break
            if not d:
                continue
            fit = sched.lookahead_capacity(req, 1 + len(d))
            d = d[:fit - 1]
            if d:
                drafts[req.uid] = d
        return drafts

    def _verify_inputs(self, running, drafts):
        """The verify launch arrays — (tokens, lengths, positions,
        tables): every slot's pending token plus its drafts (none = a
        plain one-token column), zero-padded — shared by the
        synchronous and pipelined paths."""
        engine = self.engine
        kw = self.spec_tokens + 1
        b, mb = engine.max_batch_size, engine.blocks_per_seq
        tokens = np.zeros((b, kw), np.int32)
        lengths = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        tables = np.zeros((b, mb), np.int32)
        for req in running:
            d = drafts.get(req.uid, ())
            n = 1 + len(d)
            tokens[req.slot, 0] = req.next_input
            if d:
                tokens[req.slot, 1:n] = d
            lengths[req.slot] = n
            positions[req.slot] = req.num_cached
            tables[req.slot, :len(req.block_table)] = req.block_table
        return tokens, lengths, positions, tables

    def _verify_step(self, running, drafts) -> int:
        """One speculative verify step over ``running``, materialized
        and applied in the same call (the synchronous loop): every
        slot feeds its pending token plus its drafts through the
        fixed-width verify program, and greedy acceptance keeps, per
        slot, the longest draft prefix matching the model's own argmax
        plus the model's next token — so the emitted tokens are
        exactly what one-token decode would have produced, just
        several of them per engine step.  Rejected suffix K/V is
        rolled back (``Scheduler.rollback_lookahead``).  Returns
        tokens produced."""
        sched, engine, tr = self.scheduler, self.engine, self.tracer
        tokens, lengths, positions, tables = self._verify_inputs(
            running, drafts)
        try:
            with tr.span("verify", batch=len(running),
                         drafted=sum(len(v) for v in drafts.values())):
                logits = np.asarray(engine.verify(
                    tokens, lengths, positions, tables))
        except MemoryError:
            # skip-and-retry: no request state moved, and drafts are
            # pure functions of request history — the retry next
            # iteration recomputes them bit-identically.  Lookahead
            # blocks grown for this verify are returned so the skipped
            # iteration holds no extra pool space.
            self._note_oom("verify")
            for req in running:
                if req.running:
                    sched.rollback_lookahead(req)
            return 0
        self.spec.incr("verify_steps")
        if self._phase is not None:
            self._phase["verify_launches"] += 1
            self._phase["verify_columns"] += (
                len(running) + sum(len(d) for d in drafts.values()))
        finite = np.all(np.isfinite(logits), axis=-1)      # (B, K)
        samp = (self.scheduler.sampling_inputs(running)
                if self.sample_fn is greedy_sample else None)
        if samp is None:
            row_toks = self.sample_fn(logits)              # (B, K)
        else:
            # every verify column sampled with its own positional
            # counter key — acceptance below compares drafts to these
            # samples, which IS rejection sampling (the Gumbel-max
            # coupling, ops.sample_tokens) and keeps the stream
            # identical to plain decode
            b, kw = logits.shape[:2]
            counters = (positions[:, None].astype(np.int32) + 1
                        + np.arange(kw, dtype=np.int32)[None, :])
            samp2 = tuple(np.broadcast_to(a[:, None], (b, kw))
                          for a in samp)
            row_toks = np.asarray(sample_tokens_host(
                logits, *samp2, counters)[0])
        return self._apply_verify_results(running, drafts, lengths,
                                          row_toks, finite)

    def _launch_verify(self, running, drafts) -> bool:
        """The pipelined verify launch: enqueue the fused sampled
        program (every row's argmax + finite flag on device) and
        stash the un-materialized handles plus the draft map as the
        in-flight window; greedy acceptance runs at retire, next step.
        False = the launch OOMed — lookahead blocks grown for it are
        rolled back and the identical verify (drafts are deterministic
        functions of request history) retries next iteration."""
        sched, engine, tr = self.scheduler, self.engine, self.tracer
        tokens, lengths, positions, tables = self._verify_inputs(
            running, drafts)
        samp = sched.sampling_inputs(running)
        kw = {"sampling": samp} if samp is not None else {}
        try:
            with tr.span("launch", program="verify",
                         batch=len(running),
                         drafted=sum(len(v) for v in drafts.values())):
                ids, fin = engine.verify_sampled(tokens, lengths,
                                                 positions, tables,
                                                 **kw)
        except MemoryError:
            self._note_oom("verify")
            for req in running:
                if req.running:
                    sched.rollback_lookahead(req)
            return False
        self.spec.incr("verify_steps")
        if self._phase is not None:
            self._phase["verify_launches"] += 1
            # columns fed = each slot's pending token + its drafts
            # (host ints — lengths mirrors exactly this)
            self._phase["verify_columns"] += (
                len(running) + sum(len(d) for d in drafts.values()))
        self._inflight = _InflightStep(
            "verify", list(running), ids, fin, self.clock(),
            drafts=drafts, lengths=lengths)
        sched.hold_inflight(running)
        self.pipe.incr("launches")
        return True

    def _apply_verify_results(self, running, drafts, lengths,
                              row_toks, finite,
                              now: Optional[float] = None) -> int:
        """Greedy acceptance over one verify step's sampled results —
        the retire half shared by both loops.  ``row_toks``/``finite``
        are (B, K) host arrays (the model's argmax and finite flag at
        every fed position); ``now`` backdates breaker failures to
        launch time.  Accepts, per slot, the longest draft prefix
        matching the model's own argmax plus the model's next token,
        then rolls back rejected-suffix K/V blocks.  Returns tokens
        produced."""
        sched = self.scheduler
        produced = 0
        for req in running:
            if req.finished or not req.running:
                continue      # failed between launch and retire
            n = int(lengths[req.slot])
            if not np.all(finite[req.slot, :n]):
                sched.fail(req, reasons.NONFINITE)
                if self.breaker is not None:
                    self.breaker.record_failure(now)
                continue
            toks = row_toks[req.slot]                      # (K,)
            d = drafts.get(req.uid, ())
            req.num_cached += 1        # the pending token's K/V landed
            accepted = 0
            for j, guess in enumerate(d):
                if int(guess) != int(toks[j]):
                    break              # model disagrees: reject the
                    #                    rest of the draft
                req.record_token(int(guess))
                self._note_first_token(req)
                produced += 1
                req.num_cached += 1    # its verify-written K/V is valid
                accepted += 1
                if req.finished:
                    break
            resampled = False
            if not req.finished:
                # the model's own next token — the sample after the
                # last accepted token, exactly what a one-token decode
                # would draw there (its K/V is NOT yet written; it
                # becomes the pending token, same as decode).  Under
                # greedy this is the argmax correction; under
                # stochastic sampling a draft rejection makes it the
                # residual resample of rejection sampling (the
                # Gumbel-max coupling: the column's own sample, which
                # conditional on differing from the draft is exactly
                # the normalized-residual draw)
                resampled = accepted < len(d)
                req.record_token(int(toks[accepted]))
                self._note_first_token(req)
                produced += 1
            if d:
                req.spec_drafted += len(d)
                req.spec_accepted += accepted
                self.spec.incr("drafted_tokens", len(d))
                self.spec.incr("accepted_tokens", accepted)
                self.spec_drafted_hist.record(len(d))
                self.spec_accepted_hist.record(accepted)
                if not req.sampling.is_greedy:
                    # the stats()["sampling"]["rejection"] block:
                    # stochastic drafts accepted with prob p(draft),
                    # each rejection emitting one residual resample
                    self.spec.incr("stoch_drafted_tokens", len(d))
                    self.spec.incr("stoch_accepted_tokens", accepted)
                    if resampled:
                        self.spec.incr("stoch_resamples")
            if req.finished:
                sched.retire(req)
                if self.breaker is not None:
                    self.breaker.record_success()
            else:
                # index any blocks the accepted tokens just filled,
                # then release lookahead blocks holding only
                # rejected-suffix positions (KV rollback)
                sched.register_progress(req)
                sched.rollback_lookahead(req)
        self.spec.incr("decode_tokens", produced)
        return produced

    def _flush_window(self) -> int:
        """RETIRE: materialize and apply the in-flight launched step
        (no-op when the window is empty).  Blocks until the device
        finishes it — which, one step after launch, it usually already
        has; the measured wait is the device-bound share of the step
        (``stats()["pipeline"]["host_stall_ms"]``).  Returns tokens
        produced."""
        inf = self._inflight
        if inf is None:
            return 0
        self._inflight = None
        t0 = self.clock()
        with self.tracer.span("retire", program=inf.kind,
                              batch=len(inf.running)):
            toks = np.asarray(inf.ids)
            finite = np.asarray(inf.finite)
        self.retire_wait.record(self.clock() - t0)
        # the device step is fully consumed: its K/V writes landed, so
        # the window's block pin lifts before any request state moves
        self.scheduler.release_inflight()
        self.pipe.incr("retired_behind")
        if inf.kind == "decode":
            return self._apply_decode_results(
                inf.running, toks, finite, now=inf.launched_at)
        return self._apply_verify_results(
            inf.running, inf.drafts, inf.lengths, toks, finite,
            now=inf.launched_at)

    # -- disaggregated prefill/decode pools (docs/serving.md) --------------

    def _step_disagg(self) -> int:
        """One disaggregated iteration (``enable_disagg=True``): the
        DECODE pool retires, plans, and launches a pure decode/verify
        step — never a prefill — and the PREFILL pool then advances up
        to ``prefill_max_concurrent`` chunk launches whose device
        compute overlaps the already-in-flight decode (the two pools
        share no array, so nothing serializes them).  Finished
        prefills hand their blocks to the decode pool through the
        fixed-shape cross-pool block copy at the START of the next
        step; greedy output is bit-exact vs the monolithic loop by
        construction (same programs, same per-request context, the
        copy is byte-preserving)."""
        sched, tr = self.scheduler, self.tracer
        psched = self.prefill_scheduler
        rec = self.recorder
        self._iter += 1
        produced, self._pending_produced = self._pending_produced, 0
        step_start = self.clock()
        self._phase = None
        if rec.enabled:
            preempt0 = (sched.preemption_count
                        + psched.preemption_count)
            lk_grant0 = sched.lookahead_granted
            lk_roll0 = sched.lookahead_rolled_back
            evict0 = self.prefix.count("prefix_evicted_blocks")
            oom0 = self.oom.total
            drafted0 = self.spec.count("drafted_tokens")
            accepted0 = self.spec.count("accepted_tokens")
            off0 = self._offload_marks()
            self._phase = self._new_phase()
        # RETIRE the decode pool's in-flight step first — this is the
        # inter-token edge disaggregation protects
        retired = self._flush_window()
        produced += retired
        plan_start = self.clock()
        self._expire_deadlines()
        self.pressure_gauge.update(self.pressure())
        shed = psched.shed_overload()
        if shed and tr.enabled:
            for r in shed:
                tr.instant("request_shed", uid=r.uid,
                           priority=r.priority)
        # HAND-OFF: prefills that finished in an earlier step
        # materialize their first token and move pools (the copy and
        # this step's decode of the moved request share the decode
        # pool's data dependency, so ordering is automatic)
        produced += self._process_handoffs()
        # DECODE pool: pure decode/verify over its running batch
        if sched.running:
            for req in list(sched.running.values()):
                if req.running and not req.prefilling:
                    if not sched.ensure_decode_capacity(req):
                        sched.fail(req, reasons.CAPACITY)
            # a decode-pool preemption victim must re-prefill: it
            # re-enters through the PREFILL pool's queue front,
            # keeping its seniority (recompute is bit-stable — the
            # pending token continues, exactly as monolithic)
            while sched.waiting:
                psched.waiting.appendleft(sched.waiting.pop())
            running = [r for r in sched.running.values()
                       if not r.prefilling]
            if running:
                drafts = (self._propose_drafts(running)
                          if self.speculating else {})
                if self.pipelining:
                    if drafts:
                        self._launch_verify(running, drafts)
                    else:
                        self._launch_decode(running)
                elif drafts:
                    produced += self._verify_step(running, drafts)
                else:
                    produced += self._decode_step(running)
        # PREFILL pool: admission + one chunk per prefilling request,
        # launched AFTER the decode launch so its compute runs under
        # the in-flight decode instead of in front of it
        chunks, pf_produced, admitted = self._prefill_slice()
        produced += pf_produced
        self.chunk_iters.update(chunks)
        if chunks:
            self.prefix.incr("prefill_chunks", chunks)

        if self.pipelining:
            self.plan_time.record(self.clock() - plan_start)
        self.tokens.update(produced)
        self.queue_depth.update(psched.num_waiting)
        self.occupancy.update(sched.num_running
                              / self.engine.max_batch_size)
        step_s = self.clock() - step_start
        self.step_time.record(step_s)
        self._finalize_finished()
        alloc = self.engine.allocator
        palloc = self.prefill_engine.allocator
        self.mem_live.update(alloc.num_live)
        self.mem_free.update(alloc.num_free)
        self.mem_evictable.update(
            self.prefix_cache.num_evictable
            if self.prefix_cache is not None else 0)
        self.mem_frag.update(sched.frag_slots() + psched.frag_slots())
        self.handoff_pending.update(len(self._handoff))
        if rec.enabled:
            fin = sched.finished
            new_fin = fin[self._rec_cursor:]
            finished_now = [
                {"uid": r.uid, "reason": r.finish_reason,
                 "tokens": len(r.generated)}
                for r in new_fin]
            self._rec_cursor = len(fin)
            step_rec = {
                "iter": self._iter,
                "produced": produced,
                "waiting": psched.num_waiting,
                "running": [r.uid for r in sched._admit_order]
                + [r.uid for r in psched._admit_order],
                "prefilling": [r.uid for r in psched._admit_order
                               if r.prefilling],
                "admitted": [r.uid for r in admitted],
                "shed": [{"uid": r.uid, "priority": r.priority,
                          "debt_tokens":
                          OverloadPolicy.slo_debt_tokens(r)}
                         for r in shed],
                "finished": finished_now,
                "preemptions": (sched.preemption_count
                                + psched.preemption_count) - preempt0,
                "evicted_blocks":
                    self.prefix.count("prefix_evicted_blocks") - evict0,
                "oom": self.oom.total - oom0,
                "spec": {
                    "drafted":
                        self.spec.count("drafted_tokens") - drafted0,
                    "accepted":
                        self.spec.count("accepted_tokens") - accepted0,
                },
                "pressure": round(self.pressure_gauge.val, 4),
                "breaker": (self.breaker.state
                            if self.breaker is not None
                            else "disabled"),
                "memory": {
                    "free": alloc.num_free,
                    "live": alloc.num_live,
                    "evictable": (self.prefix_cache.num_evictable
                                  if self.prefix_cache is not None
                                  else 0),
                    "frag_slots": (sched.frag_slots()
                                   + psched.frag_slots()),
                    "lookahead_granted":
                        sched.lookahead_granted - lk_grant0,
                    "lookahead_rolled_back":
                        sched.lookahead_rolled_back - lk_roll0,
                },
                "pipeline": {
                    "pending": 1 if self._inflight is not None else 0,
                    "retired_tokens": retired,
                },
                "offload": self._offload_delta(off0),
                "phase": self._phase,
                "disagg": {
                    "handoff_pending": len(self._handoff),
                    "prefill_free": palloc.num_free,
                    "prefill_live": palloc.num_live,
                },
                "step_s": step_s,
            }
            if self.journeys.enabled:
                # same conditional uid -> rid join as the monolithic
                # step record
                step_rec["rids"] = {
                    str(r.uid): r.journey.rid
                    for r in list(admitted) + new_fin
                    if r.journey is not None}
            rec.record(step_rec)
            self._phase = None
        if self.breaker is not None:
            state = self.breaker.state
            if state != self._last_breaker_state:
                self._last_breaker_state = state
                if state == "open":
                    self._auto_postmortem("breaker_open")
        return produced

    def _prefill_slice(self):
        """The prefill pool's share of one disaggregated step: shed /
        admit / COW / one chunk per prefilling slot, all against the
        PREFILL engine and scheduler.  Chunk launches are asynchronous
        (mid-chunk results are never materialized, and the final
        chunk's sampled token is stashed as un-materialized handles
        under pipelining), so the slice costs the host little more
        than dispatch.  Returns ``(chunk launches, tokens produced,
        admitted requests)``."""
        psched, engine, tr = (self.prefill_scheduler,
                              self.prefill_engine, self.tracer)
        pipelined = self.pipelining
        with tr.span("admit"):
            admitted = psched.admit()
        if admitted:
            now = self.clock()
            for req in admitted:
                if req.admitted_at is None:
                    req.admitted_at = now
                if tr.enabled:
                    tr.instant("request_admit", uid=req.uid,
                               cached_tokens=req.cached_prefix_tokens)
        cows = [r for r in psched._admit_order if r.pending_cow]
        if cows:
            try:
                with tr.span("cow_copy", blocks=len(cows)):
                    engine.copy_blocks([r.pending_cow for r in cows])
            except MemoryError:
                self._note_oom("copy_blocks")
            else:
                for req in cows:
                    psched.cow_done(req)
        chunks = 0
        produced = 0
        for req in [r for r in psched._admit_order if r.prefilling]:
            tokens, start, is_last = psched.prefill_plan(req)
            samp1 = (psched.prefill_sampling(req)
                     if pipelined and is_last and req.prefill_sample
                     else None)
            skw = {"sampling": samp1} if samp1 is not None else {}
            try:
                if (start == 0 and is_last
                        and self.prefill_chunk is None):
                    with tr.span("prefill", uid=req.uid,
                                 tokens=len(tokens)):
                        out = (engine.prefill_sampled(
                            tokens, req.block_table,
                            **skw) if pipelined
                            else engine.prefill(tokens,
                                                req.block_table))
                else:
                    with tr.span("chunk_prefill", uid=req.uid,
                                 tokens=len(tokens), start=start):
                        out = (engine.chunk_prefill_sampled(
                            tokens, start, req.block_table,
                            pad_to=self.prefill_chunk,
                            **skw) if pipelined
                            else engine.chunk_prefill(
                                tokens, start, req.block_table,
                                pad_to=self.prefill_chunk))
                    chunks += 1
            except MemoryError:
                self._note_oom("prefill")
                continue
            if self._phase is not None:
                self._phase["prefill_launches"] += 1
                self._phase["prefill_tokens"] += len(tokens)
            done = psched.chunk_done(req, len(tokens))
            if not done:
                continue
            if not req.prefill_sample:
                # resumed after preemption: the pending token
                # continues — nothing to sample, straight to hand-off
                self._handoff.append(_Handoff(req))
                continue
            if pipelined:
                # the sampled token stays un-materialized until the
                # hand-off processes next step (its compute will long
                # be done) — the prefill slice never blocks on device
                self._handoff.append(_Handoff(req, handles=out))
                continue
            # synchronous path: materialize now, exactly like the
            # monolithic loop's prefill sampling
            logits = np.asarray(out)
            if not np.all(np.isfinite(logits)):
                psched.fail(req, reasons.NONFINITE)
                if self.breaker is not None:
                    self.breaker.record_failure()
                continue
            tok = self._sample_prefill_host(req, logits)
            req.record_token(tok)
            self._note_first_token(req)
            produced += 1
            if req.finished:
                psched.retire(req)
                if self.breaker is not None:
                    self.breaker.record_success()
                continue
            self._handoff.append(_Handoff(req))
        return chunks, produced, admitted

    def _process_handoffs(self) -> int:
        """Drain the hand-off queue (FIFO): materialize each finished
        prefill's first token (pipelined launches stashed handles a
        step ago), then move its blocks into the decode pool via the
        cross-pool block copy — or ship them to another replica
        through ``handoff_sink``.  A hand-off that cannot place yet
        (no decode slot / blocks, or a transient copy failure) stays
        queued, blocks intact on the prefill side, and retries next
        step — delayed, never torn: the copy is idempotent over whole
        tables, so a partial transfer is simply re-copied.  Returns
        tokens produced (hand-off-time first tokens)."""
        sched, psched = self.scheduler, self.prefill_scheduler
        q = self._handoff
        produced = 0
        while q:
            ent = q[0]
            req = ent.req
            if req.finished or not req.running:
                # expired / evacuated / failed while queued
                q.popleft()
                continue
            if ent.handles is not None:
                ids, fin = ent.handles
                ent.handles = None
                if not bool(np.asarray(fin)[0]):
                    psched.fail(req, reasons.NONFINITE)
                    if self.breaker is not None:
                        self.breaker.record_failure()
                    q.popleft()
                    continue
                req.record_token(int(np.asarray(ids)[0]))
                self._note_first_token(req)
                produced += 1
                if req.finished:
                    psched.retire(req)
                    if self.breaker is not None:
                        self.breaker.record_success()
                    self.handoffs.incr("finished_at_prefill")
                    q.popleft()
                    continue
            if self.handoff_sink is not None:
                # cross-replica: export the blocks (+ scale sidecars)
                # as a checksummed host payload and let the router
                # place the decode half; True = ownership moved
                payload = self.prefill_engine.export_blocks(
                    req.block_table)
                if self.handoff_sink(req, payload):
                    # a cancel() racing the sink call may have
                    # terminalized req already (freeing its prefill
                    # blocks on the standard fail path) — failing it
                    # AGAIN would double-free; the sink side handles
                    # the orphaned ingest
                    if not req.finished:
                        psched.register_progress(req)
                        psched.fail(req, reasons.HANDOFF)
                    self.handoffs.incr("sink_delivered")
                    q.popleft()
                    continue
                # nobody could take it: fall back to the LOCAL decode
                # pool below — monolithic placement on this replica
                self.handoffs.incr("sink_local_fallback")
                if req.finished:
                    # cancelled mid-sink and the sink declined: its
                    # blocks are already freed — nothing to place
                    q.popleft()
                    continue
            n = len(req.block_table)
            if not sched.has_free_slot:
                self.handoffs.incr("deferred")
                break
            dst = sched._try_alloc(n)
            if dst is None:
                self.handoffs.incr("deferred")
                break
            try:
                with self.tracer.span("handoff", uid=req.uid,
                                      blocks=n):
                    self.engine.copy_blocks_from(
                        self.prefill_engine,
                        list(zip(req.block_table, dst)))
            except MemoryError:
                # transient (or chaos-torn) transfer: return the
                # destination blocks and retry the WHOLE copy next
                # step — re-copying every block makes a torn transfer
                # indistinguishable from a delayed one
                sched.allocator.free(dst)
                self._note_oom("handoff")
                break
            if self._phase is not None:
                self._phase["handoff_blocks"] += n
            psched.release_handoff(req)
            sched.admit_handoff(req, dst)
            self.handoffs.incr("requests")
            self.handoffs.incr("blocks", n)
            q.popleft()
        return produced

    def ingest_handoff(self, prompt: Sequence[int],
                       generated: Sequence[int], payload: dict, *,
                       max_new_tokens: int,
                       num_cached: int,
                       eos_id: Optional[int] = None,
                       priority: int = 0,
                       deadline_iters: Optional[int] = None,
                       deadline_s: Optional[float] = None,
                       sampling: Optional[SamplingParams] = None,
                       submitted_at: Optional[float] = None,
                       first_token_at: Optional[float] = None,
                       journey=None) -> Optional[Request]:
        """The decode half of a CROSS-REPLICA hand-off: import an
        :meth:`DecodeEngine.export_blocks` payload into this server's
        (decode) pool and admit the request straight into the decode
        batch at its carried position — no prefill here, ever.

        Returns the admitted :class:`Request`, or ``None`` when this
        replica cannot take it right now (draining, no free decode
        slot, or no blocks) — the router then falls back to monolithic
        placement.  Raises :class:`ValueError` on a torn payload
        (checksum mismatch): nothing was imported, the caller must
        fall back to a fresh prefill (which is bit-identical)."""
        with (self._ops_lock or _NO_LOCK):
            if self._closed:
                raise RuntimeError(
                    "InferenceServer is closed; no further submissions")
            if self._draining:
                return None
            if self._inflight is not None:
                self._pending_produced += self._flush_window()
            generated = [int(t) for t in generated]
            if not generated:
                raise ValueError(
                    "ingest_handoff needs >= 1 generated token (the "
                    "prefill side samples the first token before "
                    "handing off)")
            sched = self.scheduler
            if not sched.has_free_slot:
                return None
            n = int(payload.get("num_blocks", 0))
            blocks = sched._try_alloc(n)
            if blocks is None:
                return None
            try:
                self.engine.import_blocks(blocks, payload)
            except ValueError:
                sched.allocator.free(blocks)
                raise
            except MemoryError:
                sched.allocator.free(blocks)
                return None
            req = Request(prompt=[int(t) for t in prompt],
                          max_new_tokens=int(max_new_tokens),
                          eos_id=eos_id,
                          priority=int(priority),
                          deadline_iters=deadline_iters,
                          deadline_s=deadline_s,
                          submit_iter=self._iter,
                          submitted_at=(submitted_at
                                        if submitted_at is not None
                                        else self.clock()),
                          sampling=sampling if sampling is not None
                          else SamplingParams())
            req.generated = generated
            req.next_input = generated[-1]
            req.num_cached = int(num_cached)
            req.admitted_at = self.clock()
            req.first_token_at = (first_token_at
                                  if first_token_at is not None
                                  else req.admitted_at)
            self.sampling_classes.incr(req.sampling.klass)
            if self.journeys.enabled and journey is not None:
                # the hand-off carries the journey context across
                # replicas: ingest hop here, then admit_handoff's
                # handoff=True admit hop — one rid, causal order
                req.journey = journey
                self.journeys.hop(journey, "handoff_ingest",
                                  uid=req.uid, blocks=n,
                                  carried_tokens=req.num_cached)
            sched.admit_handoff(req, blocks)
            self.handoffs.incr("ingested")
            self.handoffs.incr("blocks", n)
            if self.tracer.enabled:
                self.tracer.instant("handoff_ingest", uid=req.uid,
                                    blocks=n)
            return req

    def _offload_ingest(self, meta: dict, payload: dict) -> dict:
        """Receiver half of the offload-promote transfer: import the
        checksummed payload into the blocks the sender reserved.  The
        cache-home engine is resolved at call time (prefill pool under
        disagg, else the monolithic engine) so the handler survives a
        server reconfiguration.  A torn payload raises
        :class:`ValueError` natively — the transport reports it to the
        sender un-retried and caches nothing."""
        eng = self.prefill_engine if self.disagg else self.engine
        blocks = [int(b) for b in meta["blocks"]]
        eng.import_blocks(blocks, payload)
        return {"blocks": len(blocks)}

    def _note_oom(self, site: str) -> None:
        """Account one transient engine ``MemoryError``: the affected
        call was skipped (nothing mutated) and will retry next
        iteration; the circuit breaker counts it as a failure so a
        sustained OOM burst trips fast rejection at the front door."""
        self.oom.incr(site)
        if self.breaker is not None:
            self.breaker.record_failure()
        if self.tracer.enabled:
            self.tracer.instant("engine_oom", site=site)

    # -- per-request timelines --------------------------------------------

    def _note_first_token(self, req: Request) -> None:
        """Stamp the first-token edge of the request timeline (the
        TTFT numerator) the moment its first token is sampled, and —
        for every later token — the inter-token gap since the previous
        one (the ITL distribution behind
        ``stats()["latency"]["itl_ms"]`` and the per-request p99 the
        SLO tracker bounds).  Tokens accepted together in one verify
        step record one real gap plus near-zero followers — exactly
        the arrival pattern a streaming consumer sees."""
        now = self.clock()
        if req.first_token_at is None and req.generated:
            req.first_token_at = now
            if self.tracer.enabled:
                self.tracer.instant("request_first_token", uid=req.uid)
            if self.journeys.enabled and req.journey is not None:
                self.journeys.hop(req.journey, "first_token",
                                  uid=req.uid,
                                  ttft_s=now - req.submitted_at)
        elif req.last_token_at is not None:
            gap = now - req.last_token_at
            req.itl_gaps.append(gap)
            self.itl.record(gap)
            if self.journeys.enabled and req.journey is not None:
                # ITL exemplar: the worst gap per histogram bucket
                # remembers which rid produced it, so an SLO-miss
                # bucket resolves to a renderable journey
                self.journeys.exemplar("itl",
                                       self.itl.bucket_index(gap),
                                       gap, req.journey.rid)
        req.last_token_at = now
        # streaming fan-out rides the same edge: every applied token
        # funnels through here, so this is THE retire-time publish
        # point (docs/serving.md, "Streaming & cancellation")
        if self.stream_broker is not None:
            self.stream_broker.publish(req.uid, len(req.generated) - 1,
                                       req.generated[-1])

    def _finalize_finished(self) -> None:
        """Stamp ``finished_at`` on every request that finished since
        the last call (any path: retire, fail, rejected-at-submit) and
        feed the latency histograms from its timeline.  Cursor-based
        over ``scheduler.finished`` so each request is accounted
        exactly once."""
        fin = self.scheduler.finished
        while self._finalized < len(fin):
            req = fin[self._finalized]
            self._finalized += 1
            if req.finished_at is None:
                req.finished_at = self.clock()
            if self.tracer.enabled:
                self.tracer.instant("request_finish", uid=req.uid,
                                    reason=req.finish_reason or "",
                                    tokens=len(req.generated))
            # never-admitted requests (rejected / shed-from-queue /
            # breaker_open / draining / queued timeout) have no
            # admitted_at, so timeline() emits no queue_wait_s/ttft_s
            # — admission latency never mixes in requests that were
            # turned away at the front door
            tl = req.timeline()
            if "queue_wait_s" in tl:
                self.queue_wait.record(tl["queue_wait_s"])
                self._queue_wait_for(req.priority).record(
                    tl["queue_wait_s"])
            if "ttft_s" in tl:
                self.ttft.record(tl["ttft_s"])
                if self.journeys.enabled and req.journey is not None:
                    # TTFT exemplar: worst observation per bucket
                    # keeps its rid (the SLO-miss -> journey link)
                    self.journeys.exemplar(
                        "ttft", self.ttft.bucket_index(tl["ttft_s"]),
                        tl["ttft_s"], req.journey.rid)
            if "decode_token_s" in tl:
                self.decode_latency.record(tl["decode_token_s"])
            if (self.journeys.enabled and req.journey is not None
                    and req.finish_reason != reasons.HANDOFF):
                # HANDOFF is not a journey terminal: ownership moved
                # to the ingesting replica, which records the real
                # finish — a hop here would double-finish the journey
                self.journeys.hop(req.journey, "finish", uid=req.uid,
                                  reason=req.finish_reason or "",
                                  tokens=len(req.generated))
            # SLO/goodput classification (docs/observability.md,
            # "SLO & goodput"): served terminals count toward
            # attainment, shed work toward the debt counters
            self.slo.observe(req)
            if self.admission is not None:
                self.admission.observe(req)
            # terminal stream event: delivery backfills any tokens the
            # bounded queue never carried, so the consumer's stream is
            # complete the moment it sees the finish_reason
            if self.stream_broker is not None:
                self.stream_broker.finish(req.uid,
                                          req.finish_reason or "")

    def _queue_wait_for(self, priority: int):
        """The per-priority-class queue-wait histogram (a labeled
        series of ``serving_queue_wait_s``), created on first use."""
        h = self._queue_wait_prio.get(priority)
        if h is None:
            h = self.registry.histogram("serving_queue_wait_s",
                                        priority=str(priority))
            self._queue_wait_prio[priority] = h
        return h

    # -- postmortems (docs/observability.md) -------------------------------

    def dump_postmortem(self, path: str, *, reason: str = "on_demand",
                        extra: Optional[dict] = None) -> dict:
        """Write a postmortem bundle into ``path`` — the flight ring
        as JSONL, the full metrics snapshot, the tracer's Chrome
        trace, and a manifest — and return the manifest.  Meaningful
        whenever the flight recorder is on (``flight_recorder=`` /
        ``postmortem_dir=`` / ``APEX_TPU_POSTMORTEM``); with the null
        recorder the bundle still writes but its flight log is empty.
        Render/inspect with ``tools/postmortem.py``."""
        merged = {"iter": self._iter,
                  "engine": self.engine.memory_info()}
        if extra:
            merged.update(extra)
        return write_postmortem(path, recorder=self.recorder,
                                registry=self.registry,
                                tracer=self.tracer, reason=reason,
                                extra=merged,
                                journeys=dump_journeys([self.journeys])
                                if self.journeys.enabled else None)

    def journey(self, rid: int) -> Optional[dict]:
        """One merged journey by rid (``Journey.as_dict()`` shape), or
        None when unknown / journeys disabled — the programmatic twin
        of ``GET /debug/journey/<rid>`` (``tools/journey.py`` renders
        the bundle-side view)."""
        j = merge_journeys([self.journeys], rid=int(rid)).get(int(rid))
        return j.as_dict() if j is not None else None

    def _auto_postmortem(self, reason: str,
                         extra: Optional[dict] = None) -> Optional[str]:
        """Dump a bundle under ``postmortem_dir`` (when configured,
        with a live recorder) named ``<reason>_iter<N>``; returns the
        bundle path or None when auto-capture is off."""
        if not (self.recorder.enabled and self._postmortem_dir):
            return None
        path = os.path.join(self._postmortem_dir,
                            f"{reason}_iter{self._iter}")
        self.dump_postmortem(path, reason=reason, extra=extra)
        return path

    # apexlint: disable=lock-discipline — documented lock-free: runs on the watchdog thread while the serve thread is wedged, possibly holding the ops lock; taking it here would deadlock the black box
    def _on_watchdog_stall(self, info: dict) -> Optional[str]:
        """The armed watchdog's stall handler — runs ON THE WATCHDOG
        THREAD while the serve thread is still stuck, so it takes no
        locks: count the stall, then (when ``postmortem_dir`` is
        configured) capture every thread's stack via
        :mod:`faulthandler` — the wedged serve thread's frames are
        the payload — alongside a postmortem bundle whose manifest
        names the stall and the stack attachment
        (``tools/postmortem.py`` renders and gates both).  Returns
        the bundle path, or None when capture is off."""
        self._watchdog_stalls.incr()
        if self.tracer.enabled:
            self.tracer.instant("watchdog_stall", **info)
        if not self._postmortem_dir:
            return None
        path = os.path.join(self._postmortem_dir,
                            f"watchdog_stall_iter{self._iter}")
        os.makedirs(path, exist_ok=True)
        threads_name = "threads.txt"
        with open(os.path.join(path, threads_name), "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
        self.dump_postmortem(path, reason="watchdog_stall",
                             extra={"stall": info,
                                    "thread_stacks": threads_name})
        return path

    def audit(self) -> None:
        """The scheduler/allocator/prefix-cache invariant audit, with
        postmortem capture: an :class:`AssertionError` auto-dumps a
        bundle (when ``postmortem_dir`` + recorder are configured)
        before re-raising, so the steps leading up to the violated
        invariant are preserved, not just the assertion text.  Under
        disaggregation both pools' schedulers are audited."""
        try:
            for sched in self._schedulers():
                sched.audit()
        except AssertionError as e:
            self._auto_postmortem("audit_failure",
                                  extra={"error": str(e)})
            raise

    # -- front door -------------------------------------------------------

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int,
                 eos_id: Optional[int] = None, *,
                 priority: int = 0,
                 deadline_iters: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 sampling: Union[SamplingParams,
                                 Sequence[Optional[SamplingParams]],
                                 None] = None,
                 return_requests: bool = False):
        """Generate completions for ``prompts`` (token-id lists) and
        return the generated ids per prompt, in input order.

        ``sampling``: one :class:`SamplingParams` for every prompt, or
        a per-prompt sequence (None entries = greedy) — the batch
        twin of :meth:`submit`'s ``sampling``.

        A request that fails (capacity / timeout / rejected / shed /
        nonfinite) contributes whatever it generated before failing —
        inspect ``finish_reason`` via ``return_requests=True`` to tell
        a clean completion from an isolated failure."""
        if sampling is None or isinstance(sampling, SamplingParams):
            per_prompt = [sampling] * len(prompts)
        else:
            per_prompt = list(sampling)
            if len(per_prompt) != len(prompts):
                raise ValueError(
                    f"sampling sequence length {len(per_prompt)} != "
                    f"{len(prompts)} prompts")
        reqs = [self.submit(p, max_new_tokens, eos_id,
                            priority=priority,
                            deadline_iters=deadline_iters,
                            deadline_s=deadline_s,
                            sampling=s)
                for p, s in zip(prompts, per_prompt)]
        while self.has_work:
            self.step()
        if return_requests:
            return reqs
        return [list(r.generated) for r in reqs]

    # -- graceful lifecycle -----------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def closed(self) -> bool:
        return self._closed

    def begin_drain(self) -> None:
        """The NON-BLOCKING half of :meth:`drain`: stop admissions
        (subsequent submits finish ``"draining"``) but leave running
        the work off to the caller's step loop.  This is the rolling-
        restart shape the multi-replica router needs
        (``serving.router``): the router keeps stepping a draining
        replica alongside the healthy ones until its in-flight work
        reaches terminal states, instead of blocking the whole fleet
        inside one replica's synchronous :meth:`drain`.  Idempotent;
        in-flight generation is bit-identical either way (the same
        scheduler/engine steps run on the same state)."""
        self._draining = True

    def end_drain(self) -> None:
        """Reopen admissions after :meth:`begin_drain` WITHOUT
        replacing the server — the in-place weight-rollout shape
        (``serving/elastic``): a drained server keeps its compiled
        programs and swaps params in place, so "restart" is just
        flipping admissions back on.  Idempotent on a non-draining
        server; a CLOSED server cannot reopen (close released its
        pools)."""
        if self._closed:
            raise RuntimeError("cannot end_drain a closed server")
        self._draining = False

    def drain(self) -> dict:
        """Graceful shutdown, phase one: stop admissions (subsequent
        submits finish immediately with ``finish_reason="draining"``)
        and run every in-flight request to a terminal state.  Draining
        changes nothing about how in-flight work computes — the same
        scheduler/engine steps run on the same state — so a request's
        tokens are bit-identical whether or not a drain begins
        mid-generation (pinned by ``tests/L0/test_overload.py``).
        Idempotent; returns the flushed :meth:`stats` snapshot."""
        self.begin_drain()
        while self.has_work:
            self.step()
        self._account_pending_produced()
        self._finalize_finished()
        return self.stats()

    def withdraw_queued(self) -> List[Request]:
        """Remove and return every WAITING request without finishing
        it — the router's drain-time re-enqueue source
        (``serving.router``): queued work has generated nothing, so it
        restarts bit-identically on another replica instead of waiting
        behind this one's drain.  Flushes the pipelined window first
        so the withdrawal sees post-retire queue state."""
        if self._inflight is not None:
            self._pending_produced += self._flush_window()
        moved = self.scheduler.withdraw_waiting()
        if self.disagg:
            moved += self.prefill_scheduler.withdraw_waiting()
        self._finalize_finished()
        return moved

    # -- streaming & cancellation (docs/serving.md) ------------------------

    def _find_request(self, uid: int) -> Optional[Request]:
        """The live-or-finished request with ``uid``, or None.
        ``scheduler.running`` is keyed by SLOT, so uid lookups scan
        values; the finished list is shared across pools."""
        for sched in self._schedulers():
            for req in sched.running.values():
                if req.uid == uid:
                    return req
            for req in sched.waiting:
                if req.uid == uid:
                    return req
        for req in self.scheduler.finished:
            if req.uid == uid:
                return req
        return None

    def stream(self, req_or_uid, callback: Optional[Callable] = None
               ) -> TokenStream:
        """The per-token delivery stream for a submitted request
        (docs/serving.md, "Streaming & cancellation").

        Iterate it (``for tok in server.stream(req.uid)``), poll it
        (``drain()`` / ``take(timeout=)``), or pass ``callback`` to
        get ``callback("token", tok)`` at each retire plus one
        ``callback("end", finish_reason)``.  Opening late is fine —
        the stream backfills everything already generated.  Requires
        ``enable_streaming``; unknown uids raise ``KeyError``."""
        with (self._ops_lock or _NO_LOCK):
            if self.stream_broker is None:
                raise RuntimeError(
                    "streaming is disabled (enable_streaming=False)")
            if isinstance(req_or_uid, Request):
                req = req_or_uid
            else:
                req = self._find_request(int(req_or_uid))
                if req is None:
                    raise KeyError(f"no request with uid "
                                   f"{req_or_uid} on this server")
            if self.journeys.enabled and req.journey is not None \
                    and not req.finished:
                self.journeys.hop(req.journey, "stream_open",
                                  uid=req.uid,
                                  backfill=len(req.generated))
            return self.stream_broker.open(req.uid, req, callback)

    def cancel(self, uid: int) -> bool:
        """Cancel one request by uid — the client hung up (the SSE
        front door calls this on a broken socket) or explicitly
        abandoned it.  Frees its blocks / lookahead / in-flight holds
        immediately with ``finish_reason="cancelled"``; a queued
        request simply leaves the queue.  Returns True if a live
        request was cancelled, False if the uid is unknown or already
        terminal (double-cancel is an idempotent no-op).

        Safe mid-pipeline: the launched-but-unretired window is
        flushed FIRST (the ``submit()`` write-safety idiom), so the
        device step that may still reference the request's blocks has
        fully retired before ``fail()`` releases them; a cancel
        arriving between a later launch and its retire is handled by
        the apply-side discard guards (``req.finished`` requests'
        retired tokens are dropped)."""
        with (self._ops_lock or _NO_LOCK):
            return self._cancel(uid)

    def _cancel(self, uid: int) -> bool:
        # the flush can retire final tokens and FINISH requests —
        # possibly the victim itself (the lost-race path) — so the
        # finalize below must run even when nothing is failed
        if self._inflight is not None:
            self._pending_produced += self._flush_window()
        cancelled = False
        for sched in self._schedulers():
            for req in (list(sched.running.values())
                        + list(sched.waiting)):
                if req.uid == uid and not req.finished:
                    sched.fail(req, reasons.CANCELLED)
                    if self.tracer.enabled:
                        self.tracer.instant("request_cancel",
                                            uid=uid,
                                            tokens=len(req.generated))
                    cancelled = True
                    break
            if cancelled:
                break
        self._finalize_finished()
        return cancelled

    def _stream_stats(self) -> dict:
        """The ``stats()["streams"]`` block — broker counters plus the
        cancellation tally (meaningful even with streaming off)."""
        st = {"enabled": self.stream_broker is not None,
              "cancelled":
                  self.failures.count("requests_failed_cancelled")}
        if self.stream_broker is not None:
            st.update(self.stream_broker.stats())
            # bounded per-stream rows (``ops_probe --streams``)
            st["per_stream"] = self.stream_broker.snapshot()
        return st

    def evacuate(self, reason: str = reasons.REPLICA_FAILED) -> tuple:
        """Failover surgery for a server whose ENGINE is presumed dead
        (the router's circuit breaker tripped on repeated step
        failures — ``serving.router``).  Returns
        ``(requeueable, failed)``:

        - the launched-but-unretired window (if any) is dropped
          unconsumed — its device step belongs to a dead engine;
        - every admitted request that has not sampled a token yet
          (prefilling or pending its first decode) is preempted back
          to the queue — its K/V here is abandoned, and a fresh
          prefill elsewhere is bit-identical — then withdrawn along
          with the ordinary queued work as ``requeueable``;
        - every mid-stream request (tokens already emitted) fails
          with ``finish_reason=reason`` — its cache cannot move, and
          silently re-decoding it elsewhere would emit duplicate
          tokens to whoever is consuming the stream.  Its partial
          output stays on the request (the chaos oracle prefix-checks
          it).

        Host bookkeeping (scheduler/allocator/prefix cache) is purely
        host-side, so it stays audit-clean even when the engine is
        wedged — the pool is left consistent for a later recovery."""
        self._inflight = None
        self.scheduler.release_inflight()
        if self.disagg:
            # queued hand-offs' requests still live in the prefill
            # scheduler; the pool sweep below disposes of them, so the
            # queue entries just drop
            self._handoff.clear()
        failed = []
        for sched in self._schedulers():
            for req in list(sched.running.values()):
                if req.generated:
                    sched.fail(req, reason)
                    failed.append(req)
                else:
                    sched.preempt(req)
        requeueable = []
        for sched in self._schedulers():
            requeueable += sched.withdraw_waiting()
        self._finalize_finished()
        return requeueable, failed

    def _account_pending_produced(self) -> None:
        """Feed the token meter any production retired OUTSIDE a step
        (a ``submit()``-time window flush whose tokens no later step
        picked up — e.g. the submission was turned away and the
        server went idle)."""
        if self._pending_produced:
            self.tokens.update(self._pending_produced)
            self._pending_produced = 0

    def close(self) -> dict:
        """Graceful shutdown, phase two: :meth:`drain`, then refuse
        all further submissions (:class:`RuntimeError`).  Exactly-once:
        the drain runs on the first call only; repeated calls return
        the same final stats snapshot without re-running anything.
        An armed watchdog and an attached ops plane are stopped AFTER
        the drain completes, so ``/healthz`` reports ``draining``
        through the drain and the final scrape still answers."""
        if self._closed:
            return self._final_stats
        self._final_stats = self.drain()
        self._closed = True
        if self.watchdog.enabled:
            self.watchdog.stop()
        if self.ops is not None:
            self.ops.stop()
        self.kv_transport.close()
        return self._final_stats

    def reset_meters(self) -> None:
        """Zero the counters (after compile warmup, before a timed
        window) — a completed :meth:`generate` already returns every
        slot and block, so the server itself needs no reset."""
        self.tokens.reset()
        self.queue_depth.reset()
        self.pressure_gauge.reset()
        self.occupancy.reset()
        self.chunk_iters.reset()
        self.mem_live.reset()
        self.mem_free.reset()
        self.mem_evictable.reset()
        self.mem_frag.reset()
        self.ttft.reset()
        self.queue_wait.reset()
        for h in self._queue_wait_prio.values():
            h.reset()
        self.decode_latency.reset()
        self.itl.reset()
        self.handoff_pending.reset()
        self.step_time.reset()
        self.retire_wait.reset()
        self.plan_time.reset()
        self.spec_drafted_hist.reset()
        self.spec_accepted_hist.reset()
        self.offload_promote.reset()
        # journeys reset with the latency histograms their exemplars
        # index into — a bucket index only means anything within one
        # measurement window
        self.journeys.clear()
        self.scheduler.finished.clear()
        self._finalized = 0
        self._rec_cursor = 0
        # the flight ring resets with the step histograms — a bundle's
        # step accounting must reconcile against serving_step_s
        # (tools/postmortem.py --assert-complete), so their windows
        # have to start together
        self.recorder.clear()

    def _memory_stats(self) -> dict:
        """The ``stats()["memory"]`` block: live/free/evictable block
        occupancy with high-watermarks, the fragmentation gauge
        (allocated-but-unwritten token slots), and the speculation
        lookahead grant/rollback tallies.  Current values are read
        straight off the allocator/cache; the flight recorder carries
        the per-step time series behind them."""
        alloc = self.engine.allocator
        sched = self.scheduler
        usable = alloc.cfg.num_blocks - 1
        live = alloc.num_live
        frag = sched.frag_slots()
        info = self.engine.memory_info()
        # under disaggregation the prefix cache's evictable holds live
        # in the PREFILL pool — the decode pool's free/live/evictable
        # partition stays exact with evictable 0 here, and the
        # prefill pool's own partition rides in stats()["disagg"]
        cache_here = (self.prefix_cache
                      if self.prefix_cache is not None
                      and not self.disagg else None)
        out = {
            "blocks_usable": usable,
            "blocks_free": alloc.num_free,
            "blocks_live": live,
            "blocks_live_peak": alloc.live_peak,
            "blocks_evictable": (cache_here.num_evictable
                                 if cache_here is not None
                                 else 0),
            "blocks_evictable_peak": (cache_here.evictable_peak
                                      if cache_here is not None
                                      else 0),
            # the evictable holds PRICED in pool bytes (same
            # bytes_per_block math as pool_bytes, scale sidecars
            # included): the warm-but-reclaimable capacity an offload
            # sizing decision trades against host_bytes
            "evictable_bytes": (cache_here.num_evictable
                                if cache_here is not None else 0)
            * info["bytes_per_block"],
            "occupancy": round(live / usable, 3),
            "occupancy_peak": round(alloc.live_peak / usable, 3),
            "frag_slots": frag,
            "frag_frac": round(
                frag / (live * self.engine.block_size), 3)
            if live else 0.0,
            "lookahead_granted_blocks": sched.lookahead_granted,
            "lookahead_rolled_back_blocks": sched.lookahead_rolled_back,
            "pool_bytes": info["pool_bytes"],
            # the ACTUAL per-chip HBM cost, from the live arrays'
            # shard shape/dtype — equals pool_bytes unsharded, and
            # pool_bytes/tp under tensor parallelism; under
            # quantization both include the fp32 scale sidecar
            "pool_bytes_per_device": info["pool_bytes_per_device"],
            "bytes_per_block": info["bytes_per_block"],
            "cache_dtype": info["cache_dtype"],
            # quantized KV pool (docs/serving.md, "Quantized KV
            # cache"): storage mode + the compute dtype values widen
            # to at read (None / == cache_dtype when off)
            "quantize": info["quantize"],
            "compute_dtype": info["compute_dtype"],
        }
        return out

    def _disagg_stats(self) -> dict:
        """The pinned ``stats()["disagg"]`` block: hand-off counters
        plus the PREFILL pool's memory partition (the decode pool owns
        ``stats()["memory"]``)."""
        if not self.disagg:
            return {"enabled": False}
        palloc = self.prefill_engine.allocator
        usable = palloc.cfg.num_blocks - 1
        return {
            "enabled": True,
            "prefill_max_concurrent":
                self.prefill_scheduler.max_batch_size,
            "prefill_blocks_usable": usable,
            "prefill_blocks_free": palloc.num_free,
            "prefill_blocks_live": palloc.num_live,
            "prefill_blocks_live_peak": palloc.live_peak,
            "prefill_blocks_evictable": (
                self.prefix_cache.num_evictable
                if self.prefix_cache is not None else 0),
            "prefill_evictable_bytes": (
                self.prefix_cache.num_evictable
                if self.prefix_cache is not None else 0)
            * self.prefill_engine.memory_info()["bytes_per_block"],
            "prefill_pool_bytes":
                self.prefill_engine.memory_info()["pool_bytes"],
            "prefill_backlog_blocks":
                self.prefill_scheduler.prefill_backlog_blocks(),
            "handoff": {
                "pending": len(self._handoff),
                "pending_peak": int(self.handoff_pending.peak),
                **self.handoffs.as_dict(),
            },
            "sink_attached": self.handoff_sink is not None,
        }

    def _offload_stats(self) -> dict:
        """The pinned ``stats()["offload"]`` block (docs/serving.md,
        "Hierarchical KV offload"): demote/promote/spill/reject
        counters from the ``serving_offload`` meter, the store's tier
        occupancy, and the promote-latency histogram.  Counter keys
        are present (zero) even before the first event — and with
        offload disabled — so dashboards and the flight recorder
        never key-miss."""
        c = self.offload.count
        store = self.offload_store
        return {
            "enabled": self.kv_offload,
            "demotes": c("demotes"),
            "demote_failed": c("demote_failed"),
            "promotes_host": c("promotes_host"),
            "promotes_disk": c("promotes_disk"),
            "spills": c("spills"),
            "crc_rejects": c("crc_rejects"),
            "disk_torn": c("disk_torn"),
            "capacity_skips": c("capacity_skips"),
            "transport_skips": c("transport_skips"),
            "host_dropped": c("host_dropped"),
            "host_entries": (store.host_entries
                             if store is not None else 0),
            "host_bytes": (store.host_used_bytes
                           if store is not None else 0),
            "host_bytes_cap": (store.host_bytes
                               if store is not None else 0),
            "disk_entries": (store.disk_entries
                             if store is not None else 0),
            "spill_dir": (store.spill_dir
                          if store is not None else None),
            "promote_ms": _hist_ms(self.offload_promote),
        }

    def _program_stats(self) -> dict:
        """The ``stats()["programs"]`` block: the per-compiled-program
        table (call count, host wall time, compile count/time,
        steady-state per-call ms per program/shape key) plus the
        totals — empty ``by_program`` when accounting is off."""
        table = self.programs.table()
        return {
            "enabled": self.programs.enabled,
            "by_program": table,
            "total_wall_ms": round(
                sum(r["wall_ms"] for r in table.values()), 3),
            "total_compile_ms": round(
                sum(r["compile_ms"] for r in table.values()), 3),
        }

    def stats(self) -> dict:
        """Serving counters for logs and the bench harness.

        Prefix-cache keys: ``prefix_hit_rate`` is hit tokens over all
        admitted context tokens; ``kv_blocks_cached`` counts indexed
        blocks (shared or evictable), ``kv_blocks_free`` only the
        truly-free list — reclaimable capacity is their sum plus
        evictable holds.

        Telemetry keys (``docs/observability.md``):
        ``tokens_per_s_recent`` is the trailing-window rate (recent
        throughput, vs the lifetime-average ``tokens_per_s``);
        ``latency`` carries p50/p90/p99 from the TTFT / queue-wait /
        per-token-decode / step-time histograms fed by the per-request
        timelines; ``slo`` is per-priority-class attainment +
        goodput-vs-throughput + shed debt; ``memory`` is the KV-pool
        occupancy/high-watermark/fragmentation breakdown;
        ``trace_dropped_events`` / ``flight`` surface ring-buffer
        loss so a truncated trace or flight log is never mistaken for
        the full run.  ``programs`` is the per-compiled-program
        call/wall/compile table, ``watchdog`` the hang detector's
        state, and ``ops`` the embedded HTTP endpoint's
        (``docs/observability.md``, "Ops plane & watchdog").  Every
        pre-telemetry key is preserved unchanged (asserted in
        ``tests/L0/test_serving_engine.py``)."""
        with (self._ops_lock or _NO_LOCK):
            return self._stats()

    def _stats(self) -> dict:
        """The :meth:`stats` body (runs under the ops lock when the
        HTTP ops plane is attached — ``/statusz`` serves this)."""
        self._account_pending_produced()
        self._finalize_finished()
        pre, dec = self.engine.compile_counts()
        out = {
            "tokens_generated": self.tokens.total,
            "tokens_per_s": round(self.tokens.rate, 1),
            "tokens_per_s_recent": round(
                self.tokens.rate_over(RECENT_RATE_WINDOW_S), 1),
            "queue_depth_peak": self.queue_depth.peak,
            "batch_occupancy_avg": round(self.occupancy.avg, 3),
            "prefill_compiles": pre,
            "decode_compiles": dec,
            "kv_blocks_free": self.engine.allocator.num_free,
            "requests_finished": len(self.scheduler.finished),
            "preemptions": sum(r.preemptions
                               for r in self.scheduler.finished),
            "requests_failed": self.failures.as_dict(),
            "requests_failed_total": self.failures.total,
            "prefill_chunks": self.prefix.count("prefill_chunks"),
            "chunk_iters_peak": self.chunk_iters.peak,
            # overload / lifecycle telemetry (docs/resilience.md,
            # "Overload policy & lifecycle")
            "pressure": round(self.pressure_gauge.val, 3),
            "pressure_peak": round(self.pressure_gauge.peak, 3),
            "breaker_state": (self.breaker.state
                              if self.breaker is not None
                              else "disabled"),
            "breaker_events": self.breaker_events.as_dict(),
            "oom_events": self.oom.total,
            "draining": self._draining,
            # speculative decoding (docs/serving.md): acceptance-rate
            # counters, engine-step accounting, and the per-verify
            # drafted/accepted depth histograms.  decode_tokens /
            # decode_steps only count the decode phase (prefill-sampled
            # first tokens excluded), so tokens_per_engine_step is the
            # speculation speedup axis the bench floors.
            "speculation": {
                "enabled": self.speculating,
                "spec_tokens": self.spec_tokens,
                "drafted_tokens": self.spec.count("drafted_tokens"),
                "accepted_tokens": self.spec.count("accepted_tokens"),
                "acceptance_rate": round(self.spec.ratio(
                    "accepted_tokens", "drafted_tokens"), 3),
                "verify_steps": self.spec.count("verify_steps"),
                "decode_steps": self.spec.count("decode_steps"),
                "decode_tokens": self.spec.count("decode_tokens"),
                "tokens_per_engine_step": round(
                    self.spec.count("decode_tokens")
                    / max(1, self.spec.count("verify_steps")
                          + self.spec.count("decode_steps")), 3),
                "verify_compiles": self.engine.verify_compiles(),
                "drafted_per_step": _hist_counts(self.spec_drafted_hist),
                "accepted_per_step": _hist_counts(
                    self.spec_accepted_hist),
            },
            # stochastic sampling (docs/serving.md, "Stochastic
            # sampling"): per-class request traffic, the legacy
            # custom-sample_fn downgrade flag, and the
            # rejection-sampling accounting — stochastic drafts
            # accept with prob p(draft) under the Gumbel-max
            # coupling, each first rejection emitting one residual
            # resample
            "sampling": {
                "requests": self.sampling_classes.as_dict(),
                "custom_sample_fn":
                    self.sample_fn is not greedy_sample,
                "rejection": {
                    "drafted_tokens":
                        self.spec.count("stoch_drafted_tokens"),
                    "accepted_tokens":
                        self.spec.count("stoch_accepted_tokens"),
                    "acceptance_rate": round(self.spec.ratio(
                        "stoch_accepted_tokens",
                        "stoch_drafted_tokens"), 3),
                    "resamples": self.spec.count("stoch_resamples"),
                },
            },
            # pipelined serve loop (docs/serving.md, "Pipelined serve
            # loop"): dispatch-ahead depth and the host-stall /
            # device-stall split — host_stall_ms is the retire-time
            # wait on device results (device-bound share),
            # host_plan_ms the host scheduling+launch work the device
            # overlaps (host-bound share); a well-overlapped step
            # costs ~max of the two, a serial one their sum.
            "pipeline": {
                "enabled": self.pipelining,
                "depth": 1 if self.pipelining else 0,
                "launches": self.pipe.count("launches"),
                "retired_behind": self.pipe.count("retired_behind"),
                "pending": 1 if self._inflight is not None else 0,
                "host_stall_ms": _hist_ms(self.retire_wait),
                "host_plan_ms": _hist_ms(self.plan_time),
            },
            "latency": {
                "ttft_ms": _hist_ms(self.ttft),
                "queue_wait_ms": _hist_ms(self.queue_wait),
                "decode_token_ms": _hist_ms(self.decode_latency),
                # per-TOKEN inter-token gaps (vs decode_token_ms's
                # per-request average): the tail the disaggregation
                # bench floors (docs/serving.md)
                "itl_ms": _hist_ms(self.itl),
                "step_ms": _hist_ms(self.step_time),
                "queue_wait_by_priority_ms": {
                    p: _hist_ms(h) for p, h in
                    sorted(self._queue_wait_prio.items())},
            },
            # per-compiled-program accounting (docs/observability.md,
            # "Ops plane & watchdog"): where does the step go, per
            # program and shape key — steady_ms excludes compile calls
            "programs": self._program_stats(),
            # hang watchdog: armed state, latched stall flag (what
            # /healthz keys on), and the exactly-once stall count
            "watchdog": {
                "enabled": self.watchdog.enabled,
                "stalled": self.watchdog.stalled,
                "stalls": self.watchdog.stalls,
                "deadline_s": self.watchdog.deadline_s,
            },
            # embedded HTTP ops plane: bound port + served requests
            "ops": {
                "enabled": self.ops is not None,
                "port": self.ops.port if self.ops is not None else None,
                "requests": self.ops_requests.total,
            },
            # streaming delivery (docs/serving.md, "Streaming &
            # cancellation"): broker fan-out counters + cancellations
            "streams": self._stream_stats(),
            # disaggregated prefill/decode pools (docs/serving.md,
            # "Disaggregated prefill/decode"): the prefill pool's own
            # free/live/evictable partition plus the hand-off
            # counters; {enabled: False} on a monolithic server
            "disagg": self._disagg_stats(),
            # hierarchical KV offload (docs/serving.md, "Hierarchical
            # KV offload"): tier-crossing counters (demote / promote
            # by hit tier / spill / integrity rejects), store
            # occupancy, and the promote-latency histogram;
            # {"enabled": False} with zeroed counters when off —
            # shape-stable either way
            "offload": self._offload_stats(),
            # KV transport (docs/serving.md, "KV transport"): the
            # retry/deadline/breaker envelope every cross-pool block
            # movement rides — totals plus per-peer counters and
            # breaker state; shape-stable, backend-tagged
            "transport": self.kv_transport.stats(),
            # tensor-parallel serving (docs/serving.md,
            # "Tensor-parallel serving"): mesh geometry, tp degree,
            # per-shard KV bytes, and the mesh-lowered program count —
            # pinned like the blocks above; {enabled: False, tp: 1}
            # on a single-chip server
            "sharding": self.engine.sharding_info(),
            # SLO attainment + goodput-vs-throughput
            # (docs/observability.md, "SLO & goodput")
            "slo": self.slo.as_stats(),
            # predictive admission (docs/resilience.md): learned
            # per-class service floors + submit-time shed tally;
            # {enabled: False} unless the policy armed it
            "admission": (self.admission.as_stats()
                          if self.admission is not None
                          else {"enabled": False}),
            # KV memory occupancy, high-watermarks, fragmentation
            # (docs/observability.md, "Memory accounting")
            "memory": self._memory_stats(),
            # ring-buffer loss accounting: a saturated tracer or
            # recorder silently truncates history — surface it
            "trace_dropped_events": self.tracer.dropped,
            "flight": {
                "enabled": self.recorder.enabled,
                "steps_recorded": self.recorder.steps_recorded,
                "dropped": self.recorder.dropped,
            },
            # journey correlation plane (docs/observability.md,
            # "Request journeys & exemplars"): pinned census —
            # shape-stable enabled or not, like flight/offload
            "journeys": self.journeys.census(),
        }
        if self.prefix_cache is not None:
            out.update({
                "prefix_hit_tokens":
                    self.prefix.count("prefix_hit_tokens"),
                "prefix_miss_tokens":
                    self.prefix.count("prefix_miss_tokens"),
                "prefix_hit_requests":
                    self.prefix.count("prefix_hit_requests"),
                "prefix_hit_rate": round(self.prefix.ratio(
                    "prefix_hit_tokens",
                    "prefix_hit_tokens", "prefix_miss_tokens"), 3),
                "prefix_evicted_blocks":
                    self.prefix.count("prefix_evicted_blocks"),
                "prefix_cow_blocks":
                    self.prefix.count("prefix_cow_blocks"),
                "kv_blocks_cached": self.prefix_cache.num_cached_blocks,
                "kv_blocks_evictable": self.prefix_cache.num_evictable,
            })
        return out
