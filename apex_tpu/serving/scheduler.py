"""Continuous-batching request scheduler (Orca-style iteration-level).

The unit of scheduling is one DECODE ITERATION, not one request: every
iteration the scheduler (1) admits waiting requests into free batch
slots while the block pool can hold their prompts, (2) grows each
running request's block table just-in-time for its next token —
preempting the youngest request back to the waiting queue when the
pool runs dry — and (3) retires finished requests immediately, so
their slot and blocks are reusable on the very next iteration.  A
short request never waits for a long one to finish (the ~10x
throughput result of iteration-level batching), and memory is
committed a block at a time instead of worst-case up front.

The scheduler is pure host-side bookkeeping over the engine's
geometry; it never touches device arrays.  ``serving.api`` composes it
with the :class:`serving.engine.DecodeEngine` into the step loop.

Preemption = recompute (vLLM's default): the victim's blocks are
freed, and on re-admission its full sequence so far re-prefills as a
pseudo-prompt.  The already-sampled tokens are NOT re-sampled — the
re-prefilled context is ``prompt + generated[:-1]``, its logits are
discarded, and the pending last token re-enters the decode loop
unchanged — so generation is bit-stable across preemptions under
greedy decoding.

Failure isolation: a pathological request fails ALONE.  A request
whose context can never fit the pool — at admission or by outgrowing
it mid-flight with no victim left to preempt — is finished with
``finish_reason="capacity"`` via :meth:`Scheduler.fail` instead of
raising ``MemoryError`` into the step loop (which killed every
in-flight request).  A bounded waiting queue (``max_waiting``) rejects
at submission with :class:`QueueFullError`; expired deadlines and
non-finite logits are detected by ``serving.api`` and routed through
the same :meth:`Scheduler.fail` (reasons ``timeout`` / ``nonfinite``).
``docs/resilience.md`` has the full failure taxonomy.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional

from apex_tpu.serving.kv_cache import BlockAllocator

_uid = itertools.count()


class QueueFullError(RuntimeError):
    """The bounded waiting queue is at ``max_waiting``; the request was
    NOT enqueued.  Explicit backpressure beats an unbounded queue whose
    tail silently times out."""


@dataclasses.dataclass
class Request:
    """One generation request and its full lifecycle state."""

    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    uid: int = dataclasses.field(default_factory=lambda: next(_uid))

    # per-request budgets (None = unbounded).  ``deadline_iters`` is a
    # count of scheduler iterations from submission; ``deadline_s`` a
    # wall budget.  Both expire to ``finish_reason="timeout"``, checked
    # by the step loop (``serving.api``) at the top of each iteration.
    deadline_iters: Optional[int] = None
    deadline_s: Optional[float] = None
    submit_iter: int = 0            # server iteration at submission
    submitted_at: float = 0.0       # server clock at submission

    # runtime state (owned by the scheduler)
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1                  # decode batch slot; -1 = not running
    block_table: List[int] = dataclasses.field(default_factory=list)
    num_cached: int = 0             # tokens with K/V materialized
    next_input: Optional[int] = None  # pending token for the next decode
    finished: bool = False
    finish_reason: Optional[str] = None
    preemptions: int = 0

    @property
    def running(self) -> bool:
        return self.slot >= 0 and not self.finished

    def record_token(self, token: int) -> None:
        """Account one sampled token and evaluate termination."""
        self.generated.append(int(token))
        self.next_input = int(token)
        if self.eos_id is not None and int(token) == self.eos_id:
            self.finished = True
            self.finish_reason = "eos"
        elif len(self.generated) >= self.max_new_tokens:
            self.finished = True
            self.finish_reason = "length"


class Scheduler:
    """Slot + block bookkeeping for continuous batching.

    Args mirror the engine's geometry: ``max_batch_size`` decode
    slots, ``block_size`` tokens per block, ``max_context`` per
    request, and the shared :class:`BlockAllocator`.  ``max_waiting``
    bounds the waiting queue (:class:`QueueFullError` past it);
    ``counters`` is an optional :class:`apex_tpu.utils.CounterMeter`
    fed one ``requests_failed_<reason>`` increment per failure."""

    def __init__(self, allocator: BlockAllocator, *,
                 max_batch_size: int, block_size: int,
                 max_context: int, max_waiting: Optional[int] = None,
                 counters=None):
        self.allocator = allocator
        self.max_batch_size = max_batch_size
        self.block_size = block_size
        self.max_context = max_context
        if max_waiting is not None and max_waiting < 1:
            raise ValueError(
                f"max_waiting must be >= 1, got {max_waiting}")
        self.max_waiting = max_waiting
        self.counters = counters
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}      # slot -> request
        self._free_slots = list(range(max_batch_size - 1, -1, -1))
        self.finished: List[Request] = []
        # admission order among running requests — the preemption
        # victim is always the youngest (LIFO), which converges:
        # the oldest request monotonically keeps its blocks
        self._admit_order: List[Request] = []

    # -- submission -------------------------------------------------------

    def submit(self, req: Request) -> Request:
        if len(req.prompt) == 0:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{req.max_new_tokens}")
        if len(req.prompt) >= self.max_context:
            raise ValueError(
                f"prompt length {len(req.prompt)} must be < "
                f"max_context {self.max_context}")
        if self.max_waiting is not None \
                and len(self.waiting) >= self.max_waiting:
            raise QueueFullError(
                f"waiting queue full ({self.max_waiting} requests); "
                f"request {req.uid} rejected")
        self.waiting.append(req)
        return req

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- iteration-level decisions ---------------------------------------

    def admit(self) -> List[Request]:
        """Fill free slots from the waiting queue (FIFO) while the
        pool can hold each candidate's prefill context plus one decode
        block.  Returns the newly admitted requests, which the caller
        must prefill before the next decode step.

        A head request whose context can NEVER fit — it needs more
        blocks than the whole pool owns — is failed alone with
        ``finish_reason="capacity"`` and admission moves on to the
        next waiting request; one oversized request must not raise
        into the step loop or wedge the queue behind it."""
        admitted = []
        pool_blocks = self.allocator.cfg.num_blocks - 1
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            ctx = self._prefill_context(req)
            need = BlockAllocator.blocks_for(len(ctx) + 1,
                                             self.block_size)
            if need > pool_blocks:
                self.fail(req, "capacity")
                continue
            if not self.allocator.can_alloc(need):
                break               # fits once running requests retire
            self.waiting.popleft()
            req.slot = self._free_slots.pop()
            req.block_table = self.allocator.alloc(need)
            req.num_cached = 0          # set by the caller post-prefill
            self.running[req.slot] = req
            self._admit_order.append(req)
            admitted.append(req)
        return admitted

    def _prefill_context(self, req: Request) -> List[int]:
        """The tokens whose K/V the prefill must materialize: the
        prompt, plus — after a preemption — every generated token
        except the pending one (see module docstring)."""
        if req.generated:
            return req.prompt + req.generated[:-1]
        return list(req.prompt)

    def prefill_plan(self, req: Request):
        """(context_tokens, reuse_last_logits): when the context is
        the pristine prompt the prefill's logits sample the first
        token; after preemption they are discarded and the pending
        ``next_input`` continues instead."""
        ctx = self._prefill_context(req)
        return ctx, bool(req.generated)

    def ensure_decode_capacity(self, req: Request) -> bool:
        """Grow ``req``'s block table if its next token write needs a
        fresh block, preempting younger requests while the pool is
        dry.  False = ``req`` has outgrown the pool with nothing left
        to preempt (it is alone and the pool is STILL dry); the caller
        must fail it with ``finish_reason="capacity"`` — preempting it
        would livelock, and raising would take the whole batch down."""
        need_blocks = req.num_cached // self.block_size + 1
        while len(req.block_table) < need_blocks:
            if self.allocator.can_alloc(1):
                req.block_table.extend(self.allocator.alloc(1))
                continue
            victim = self._youngest_running(exclude=req)
            if victim is None:
                return False
            self.preempt(victim)
        return True

    def _youngest_running(self, exclude: Request) -> Optional[Request]:
        for req in reversed(self._admit_order):
            if req is not exclude:
                return req
        return None

    def preempt(self, req: Request) -> None:
        """Evict ``req`` to the waiting queue's FRONT (it has seniority
        over never-started requests), freeing its slot and blocks."""
        assert req.running, "can only preempt a running request"
        req.preemptions += 1
        self._release(req)
        req.num_cached = 0
        self.waiting.appendleft(req)

    def retire(self, req: Request) -> None:
        """Return a finished request's slot and blocks to the pools."""
        assert req.finished, "retire() is for finished requests"
        self._release(req)
        self.finished.append(req)

    def fail(self, req: Request, reason: str) -> None:
        """Finish ``req`` with ``finish_reason=reason`` wherever it is
        in its lifecycle (waiting or running), returning any held slot
        and blocks — the single exit for ``capacity`` / ``timeout`` /
        ``nonfinite`` isolation.  Tokens generated so far stay on the
        request (a timed-out request returns its partial output)."""
        assert not req.finished, "fail() is for live requests"
        if req.running:
            self._release(req)
        elif req in self.waiting:
            self.waiting.remove(req)
        req.finished = True
        req.finish_reason = reason
        self.finished.append(req)
        if self.counters is not None:
            self.counters.incr(f"requests_failed_{reason}")

    def _release(self, req: Request) -> None:
        del self.running[req.slot]
        self._admit_order.remove(req)
        self._free_slots.append(req.slot)
        req.slot = -1
        if req.block_table:
            self.allocator.free(req.block_table)
            req.block_table = []
