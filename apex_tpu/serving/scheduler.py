"""Continuous-batching request scheduler (Orca-style iteration-level).

The unit of scheduling is one DECODE ITERATION, not one request: every
iteration the scheduler (1) admits waiting requests into free batch
slots while the block pool can hold their prompts, (2) grows each
running request's block table just-in-time for its next token —
preempting the youngest request back to the waiting queue when the
pool runs dry — and (3) retires finished requests immediately, so
their slot and blocks are reusable on the very next iteration.  A
short request never waits for a long one to finish (the ~10x
throughput result of iteration-level batching), and memory is
committed a block at a time instead of worst-case up front.

Two serving-perf layers ride on top (``docs/serving.md``):

**Prefix caching** (:mod:`serving.prefix_cache`).  At admission the
request's context is matched against the block-level prefix index;
matched full blocks enter the table SHARED (one
``BlockAllocator.incref`` per table) and only the uncached tail is
prefilled.  Blocks are registered into the index as they fill (during
prefill chunks and as decode crosses block boundaries), and a
finished request's registered blocks are held evictable-LRU instead
of freed — reclaimed by :meth:`Scheduler._try_alloc` only when the
pool actually runs low.  When the ENTIRE context is cached (token
count block-aligned and fully matched) the last matched block is
duplicated copy-on-write — the request must recompute the final
token's logits and re-write its K/V, which may not touch a shared
block; the engine performs the device copy and :meth:`cow_done` drops
the extra ref.

**Chunked prefill** (Sarathi-style).  :meth:`prefill_plan` hands out
the uncached tail ``chunk_size`` tokens at a time; the step loop runs
ONE chunk per prefilling request per iteration, interleaved with the
decode step, so a long prompt stalls running decodes by at most one
chunk rather than one full prefill.  The chunk engine program carries
the KV position (``start``), so generation is bit-stable across any
chunking of the same context.

**Speculative lookahead** (``serving.speculation``).  A decoding
request with drafts needs room for up to K token writes this
iteration, not one: :meth:`lookahead_capacity` grows the table
opportunistically (evicting idle cache holds but never preempting — a
bad drafter must not degrade its neighbors; a draft that doesn't fit
is trimmed), and :meth:`rollback_lookahead` frees the blocks holding
only rejected-suffix positions after every verify step, so
speculation borrows pool space within an iteration instead of
keeping it.  Under a quantized pool (``docs/serving.md``, "Quantized
KV cache") a freed block releases its scale-sidecar rows with it —
scales are indexed by the same slots — and the rejected-suffix
garbage (int8 payload AND scales) sits beyond ``num_cached`` where
the context bias masks it, exactly like the full-width pool's.

The scheduler is pure host-side bookkeeping over the engine's
geometry; it never touches device arrays.  ``serving.api`` composes it
with the :class:`serving.engine.DecodeEngine` into the step loop.

Preemption = recompute (vLLM's default): the victim's blocks are
freed, and on re-admission its full sequence so far re-prefills as a
pseudo-prompt.  The already-sampled tokens are NOT re-sampled — the
re-prefilled context is ``prompt + generated[:-1]``, its logits are
discarded, and the pending last token re-enters the decode loop
unchanged — so generation is bit-stable across preemptions under
greedy decoding.  (With the prefix cache on, the victim's registered
blocks usually survive as LRU holds and re-admission matches them
back — preemption recovery becomes a cache hit.)

Failure isolation: a pathological request fails ALONE.  A request
whose context can never fit the pool — at admission or by outgrowing
it mid-flight with no victim left to preempt — is finished with
``finish_reason="capacity"`` via :meth:`Scheduler.fail` instead of
raising ``MemoryError`` into the step loop (which killed every
in-flight request).  A bounded waiting queue (``max_waiting``) rejects
at submission with :class:`QueueFullError`; expired deadlines and
non-finite logits are detected by ``serving.api`` and routed through
the same :meth:`Scheduler.fail` (reasons ``timeout`` / ``nonfinite``).
``docs/resilience.md`` has the full failure taxonomy.

Overload control (:mod:`serving.overload`, on by default through
``InferenceServer``): requests carry a priority class and a
block-cost estimate; when the queue or pool crosses the policy's
pressure threshold the scheduler sheds the lowest-priority, newest
waiting work (``finish_reason="shed"``) instead of blindly bouncing
the next arrival, queue-full arrivals displace lower-priority queued
work, and the preemption victim is chosen worst-priority-first.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.observability import NULL_JOURNEY_LOG, NULL_TRACER
from apex_tpu.ops.sampling import SamplingParams
from apex_tpu.serving.kv_cache import BlockAllocator
from apex_tpu.serving import reasons
from apex_tpu.serving.overload import OverloadPolicy
from apex_tpu.serving.prefix_cache import ROOT, PrefixCache

_uid = itertools.count()

# registration-cursor sentinel: once a request's chain breaks (COW
# duplicate or a key collision) none of its later blocks may register —
# their chain parent is unindexed, and an entry dangling off a reusable
# block id could alias onto garbage after that id is reallocated
_REG_STOPPED = 1 << 60


class QueueFullError(RuntimeError):
    """The bounded waiting queue is at ``max_waiting``; the request was
    NOT enqueued.  Explicit backpressure beats an unbounded queue whose
    tail silently times out."""


@dataclasses.dataclass
class Request:
    """One generation request and its full lifecycle state."""

    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    uid: int = dataclasses.field(default_factory=lambda: next(_uid))

    # per-request sampling knobs (``docs/serving.md``, "Stochastic
    # sampling"): the default instance is greedy argmax, bit-identical
    # to the historical path.  Stochastic params keep BOTH fast paths
    # (pipelined loop + speculation) — the scheduler batches them into
    # per-slot launch arrays, and the counter-keyed draws make the
    # stream deterministic across preemption/replay/speculation.
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams)

    # overload-control inputs (``serving.overload``): ``priority`` is
    # nice-style — 0 is the default/foreground class, larger numbers
    # are lower priority and sheddable under pressure.  ``cost_blocks``
    # is the completion-size estimate (prompt + budget, in KV blocks),
    # stamped by ``Scheduler.submit``; queued demand feeds the
    # pressure signal.
    priority: int = 0
    cost_blocks: int = 0

    # per-request budgets (None = unbounded).  ``deadline_iters`` is a
    # count of scheduler iterations from submission; ``deadline_s`` a
    # wall budget.  Both expire to ``finish_reason="timeout"``, checked
    # by the step loop (``serving.api``) at the top of each iteration.
    deadline_iters: Optional[int] = None
    deadline_s: Optional[float] = None
    submit_iter: int = 0            # server iteration at submission
    submitted_at: float = 0.0       # server clock at submission

    # per-request timeline (server clock, stamped by ``serving.api``):
    # enqueue -> admit -> first token -> finish.  ``admitted_at`` keeps
    # its FIRST value across preemption re-admits so queue-wait and
    # TTFT measure the user-visible request, not scheduler internals.
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None

    # inter-token-latency accounting (``docs/observability.md``,
    # "SLO & goodput"): the wall gap before each token after the
    # first, stamped by the server as tokens are APPLIED — tokens
    # accepted together in one verify step land as one real gap plus
    # near-zero followers, which is exactly what a streaming consumer
    # would see.  Feeds the per-request ITL p99 the SLO tracker bounds
    # and the disaggregation bench floors.
    itl_gaps: List[float] = dataclasses.field(default_factory=list)
    last_token_at: Optional[float] = None

    # runtime state (owned by the scheduler)
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1                  # decode batch slot; -1 = not running
    block_table: List[int] = dataclasses.field(default_factory=list)
    num_cached: int = 0             # tokens with K/V materialized
    next_input: Optional[int] = None  # pending token for the next decode
    finished: bool = False
    finish_reason: Optional[str] = None
    preemptions: int = 0

    # speculation accounting (``serving.speculation``): lifetime drafted
    # and accepted token counts for this request — the per-request view
    # behind the server-level acceptance rate.  Drafts themselves are
    # stateless (recomputed from history each iteration), so nothing
    # here needs resetting across preemption.
    spec_drafted: int = 0
    spec_accepted: int = 0

    # prefill state machine (owned by the scheduler): the context being
    # chunk-prefilled, whether the final chunk's logits sample a token
    # (False after preemption — the pending token continues instead),
    # an admission-time COW copy the engine must perform before the
    # first chunk, prefix-cache accounting, and the block-registration
    # cursor (full blocks [0, _reg_blocks) are already in the index)
    prefill_ctx: Optional[List[int]] = None
    prefill_sample: bool = True
    pending_cow: Optional[Tuple[int, int]] = None   # (src, dst)
    cached_prefix_tokens: int = 0
    _reg_blocks: int = 0

    # journey correlation (``observability.journey``): the
    # :class:`JourneyContext` traveling with this request across
    # replicas — None when journeys are off, so every stamping site
    # can guard on it and the disabled path allocates nothing
    journey: Optional[object] = None

    @property
    def running(self) -> bool:
        return self.slot >= 0 and not self.finished

    @property
    def prefilling(self) -> bool:
        """Admitted but with context K/V still being materialized — the
        decode batch skips it until the last chunk lands."""
        return self.prefill_ctx is not None

    def record_token(self, token: int) -> None:
        """Account one sampled token and evaluate termination."""
        self.generated.append(int(token))
        self.next_input = int(token)
        if self.eos_id is not None and int(token) == self.eos_id:
            self.finished = True
            self.finish_reason = reasons.EOS
        elif len(self.generated) >= self.max_new_tokens:
            self.finished = True
            self.finish_reason = reasons.LENGTH

    def timeline(self) -> dict:
        """The request's lifecycle timestamps (server clock seconds)
        plus derived waits — the per-request record behind the TTFT /
        queue-wait / decode-latency histograms
        (``docs/observability.md``)."""
        out = {
            "uid": self.uid,
            "priority": self.priority,
            "submitted_at": self.submitted_at,
            "admitted_at": self.admitted_at,
            "first_token_at": self.first_token_at,
            "finished_at": self.finished_at,
            "finish_reason": self.finish_reason,
            "tokens": len(self.generated),
            "preemptions": self.preemptions,
        }
        if self.admitted_at is not None:
            out["queue_wait_s"] = self.admitted_at - self.submitted_at
        if self.first_token_at is not None:
            out["ttft_s"] = self.first_token_at - self.submitted_at
        if (self.finished_at is not None
                and self.first_token_at is not None
                and len(self.generated) >= 2):
            out["decode_token_s"] = (
                (self.finished_at - self.first_token_at)
                / (len(self.generated) - 1))
        if self.itl_gaps:
            gaps = sorted(self.itl_gaps)
            n = len(gaps)
            out["itl_p99_s"] = gaps[min(n - 1, -(-99 * n // 100) - 1)]
            out["itl_max_s"] = gaps[-1]
        if self.journey is not None:
            # journey correlation: the fleet-stable rid this timeline
            # belongs to (absent when journeys are off, so the legacy
            # timeline shape is untouched)
            out["rid"] = self.journey.rid
        return out


class Scheduler:
    """Slot + block bookkeeping for continuous batching.

    Args mirror the engine's geometry: ``max_batch_size`` decode
    slots, ``block_size`` tokens per block, ``max_context`` per
    request, and the shared :class:`BlockAllocator`.  ``max_waiting``
    bounds the waiting queue (:class:`QueueFullError` past it);
    ``counters`` is an optional :class:`apex_tpu.utils.CounterMeter`
    fed one ``requests_failed_<reason>`` increment per failure.

    ``prefix_cache``: optional :class:`PrefixCache` enabling
    block-level prefix sharing at admission (None = every prompt
    prefills from scratch, the pre-cache behavior).  ``chunk_size``:
    prefill tail chunk in tokens (None = the whole tail in one
    :meth:`prefill_plan` call, i.e. chunked prefill off).

    ``overload``: optional :class:`OverloadPolicy` enabling
    priority-aware load shedding (queue-full displacement,
    pressure shedding of best-effort waiting work, worst-priority
    preemption victims — :mod:`serving.overload`).  None preserves
    the pre-overload behavior exactly: queue-full raises
    :class:`QueueFullError`, preemption evicts the youngest."""

    def __init__(self, allocator: BlockAllocator, *,
                 max_batch_size: int, block_size: int,
                 max_context: int, max_waiting: Optional[int] = None,
                 counters=None,
                 prefix_cache: Optional[PrefixCache] = None,
                 chunk_size: Optional[int] = None,
                 overload: Optional[OverloadPolicy] = None,
                 tracer=None, journeys=None):
        self.allocator = allocator
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # journey correlation plane (``observability.journey``): the
        # server's hop log; scheduler decisions (admit / preempt /
        # hand-off / offload promote) stamp hops for requests carrying
        # a JourneyContext.  NULL by default — zero cost when off.
        self.journeys = journeys if journeys is not None \
            else NULL_JOURNEY_LOG
        self.max_batch_size = max_batch_size
        self.block_size = block_size
        self.max_context = max_context
        if max_waiting is not None and max_waiting < 1:
            raise ValueError(
                f"max_waiting must be >= 1, got {max_waiting}")
        if chunk_size is not None and chunk_size < 1:
            raise ValueError(
                f"chunk_size must be >= 1, got {chunk_size}")
        self.max_waiting = max_waiting
        self.counters = counters
        self.prefix_cache = prefix_cache
        self.chunk_size = chunk_size
        self.overload = overload
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}      # slot -> request
        self._free_slots = list(range(max_batch_size - 1, -1, -1))
        self.finished: List[Request] = []
        # memory-observability tallies (``stats()["memory"]`` and the
        # flight recorder): lifetime preemptions and speculative
        # lookahead blocks granted / rolled back
        self.preemption_count = 0
        self.lookahead_granted = 0
        self.lookahead_rolled_back = 0
        # admission order among running requests — the preemption
        # victim is always the youngest (LIFO), which converges:
        # the oldest request monotonically keeps its blocks
        self._admit_order: List[Request] = []
        # in-flight hold (docs/serving.md, "Pipelined serve loop"):
        # requests whose launched device step has NOT been retired yet.
        # Their blocks are pinned — the pending program is still going
        # to write K/V through those tables, so preempting or failing
        # them out from under the launch would let the write land in
        # reallocated blocks.  The serve loop holds at launch and
        # releases at retire; audit() checks the pin.
        self.inflight: Dict[int, Request] = {}      # uid -> request

    # -- submission -------------------------------------------------------

    def submit(self, req: Request) -> Request:
        if len(req.prompt) == 0:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{req.max_new_tokens}")
        if len(req.prompt) >= self.max_context:
            raise ValueError(
                f"prompt length {len(req.prompt)} must be < "
                f"max_context {self.max_context}")
        req.cost_blocks = BlockAllocator.blocks_for(
            len(req.prompt) + req.max_new_tokens, self.block_size)
        if self.max_waiting is not None \
                and len(self.waiting) >= self.max_waiting:
            # overload control: an arrival that outranks the worst
            # queued request displaces it (victim finishes "shed")
            # instead of being bounced by arrival order; an arrival
            # that outranks nobody is rejected exactly as before
            victim = (self._shed_candidate()
                      if self.overload is not None
                      and self.overload.displace else None)
            if victim is None or victim.priority <= req.priority:
                raise QueueFullError(
                    f"waiting queue full ({self.max_waiting} "
                    f"requests); request {req.uid} rejected")
            self.fail(victim, reasons.SHED)
        self.waiting.append(req)
        return req

    def withdraw_waiting(self) -> List[Request]:
        """Remove and return EVERY waiting request WITHOUT finishing
        it — the multi-replica router's failover/drain re-enqueue
        path (``serving.router``): queued work on a sick or draining
        replica has generated nothing yet, so it can restart on a
        healthy replica bit-identically instead of dying here.  The
        withdrawn requests hold no slots or blocks (waiting requests
        never do — :meth:`audit` pins that), so this is pure queue
        surgery; the caller owns re-submission and the terminal
        exactly-once guarantee."""
        out = list(self.waiting)
        self.waiting.clear()
        return out

    def _shed_candidate(self) -> Optional[Request]:
        """The waiting request overload policy would shed first:
        lowest priority class (highest number), newest among equals.
        None when the queue is empty."""
        if not self.waiting:
            return None
        return max(self.waiting, key=lambda r: (r.priority, r.uid))

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- overload pressure (``serving.overload``) --------------------------

    def pressure(self) -> float:
        """The overload signal: max of the queue fill fraction and
        ``(live blocks + queued demand + prefill backlog) / usable
        blocks``.  Queued demand is the sum of waiting requests'
        ``cost_blocks``, so a burst of expensive prompts reads as
        pressure before the pool physically fills; the value may
        exceed 1.0.

        The prefill backlog term prices the REMAINING chunk tokens of
        partially-prefilled running requests (their blocks are already
        live, but the compute to fill them is still queued) — without
        it a replica midway through a long chunked prefill looks idle
        to the router and keeps receiving placements it cannot start
        for many iterations (``serving.router``)."""
        q = (len(self.waiting) / self.max_waiting
             if self.max_waiting else 0.0)
        usable = self.allocator.cfg.num_blocks - 1
        reclaimable = self.allocator.num_free + (
            self.prefix_cache.num_evictable
            if self.prefix_cache is not None else 0)
        live = usable - reclaimable
        demand = sum(r.cost_blocks for r in self.waiting)
        demand += self.prefill_backlog_blocks()
        return max(q, (live + demand) / usable)

    def prefill_backlog_blocks(self) -> int:
        """Remaining-to-prefill tokens of running requests, in block
        equivalents — the compute-backlog term of :meth:`pressure`
        (those blocks are already allocated; this prices the work
        still owed to fill them)."""
        bs = self.block_size
        backlog = 0
        for r in self.running.values():
            if r.prefill_ctx is not None:
                rem = len(r.prefill_ctx) - r.num_cached
                if rem > 0:
                    backlog += -(-rem // bs)
        return backlog

    def shed_overload(self) -> List[Request]:
        """Shed best-effort waiting work (priority >=
        ``overload.best_effort_priority``), worst-first, while
        :meth:`pressure` sits at or above ``overload.shed_threshold``.
        Foreground (priority-0) work is never pressure-shed.  Called
        once per step by the serve loop; returns the shed requests
        (each finished ``"shed"`` via :meth:`fail`)."""
        if self.overload is None or not self.waiting:
            return []
        shed: List[Request] = []
        while self.pressure() >= self.overload.shed_threshold:
            candidates = [r for r in self.waiting
                          if self.overload.sheddable(r.priority)]
            if not candidates:
                break
            victim = max(candidates, key=lambda r: (r.priority, r.uid))
            self.fail(victim, reasons.SHED)
            shed.append(victim)
        return shed

    # -- allocation with cache pressure -----------------------------------

    def _try_alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks, evicting prefix-cache LRU holds as
        needed; None when the pool is genuinely dry (nothing free,
        nothing evictable)."""
        if n <= 0:
            return []
        while not self.allocator.can_alloc(n):
            # reclaim the whole deficit in ONE evict call: victims
            # demote to the offload tier (when attached) as a single
            # batched export, not one device gather per block
            deficit = n - self.allocator.num_free
            if self.prefix_cache is None \
                    or not self.prefix_cache.evict(max(1, deficit)):
                return None
            if self.tracer.enabled:
                self.tracer.instant("evict", blocks=max(1, deficit))
        return self.allocator.alloc(n)

    # -- iteration-level decisions ---------------------------------------

    def admit(self) -> List[Request]:
        """Fill free slots from the waiting queue (FIFO) while the
        pool can hold each candidate's prefill context plus one decode
        block.  Matched prefix blocks come shared from the cache; only
        the uncached tail needs fresh blocks (and one extra for a
        whole-context match's COW duplicate).  Returns the newly
        admitted requests, now in the prefilling state — the caller
        runs their chunks via :meth:`prefill_plan` (resolving any
        ``pending_cow`` first).

        A head request whose context can NEVER fit — it needs more
        blocks than the whole pool owns — is failed alone with
        ``finish_reason="capacity"`` and admission moves on to the
        next waiting request; one oversized request must not raise
        into the step loop or wedge the queue behind it."""
        admitted = []
        bs = self.block_size
        pool_blocks = self.allocator.cfg.num_blocks - 1
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            ctx = self._prefill_context(req)
            need = BlockAllocator.blocks_for(len(ctx) + 1, bs)
            if need > pool_blocks:
                self.fail(req, reasons.CAPACITY)
                continue
            if self.prefix_cache is not None:
                with self.tracer.span("prefix_match", uid=req.uid,
                                      ctx_tokens=len(ctx)):
                    matched = self.prefix_cache.match(ctx)
                # hierarchical offload (docs/serving.md,
                # "Hierarchical KV offload"): where the device-tier
                # walk stopped, continue by content hash through the
                # host/disk store — promoted blocks re-materialize
                # into fresh device blocks (checksummed import) and
                # extend `matched` in place BEFORE the hit/cow/fresh
                # math below, so a three-tier hit plans its prefill
                # exactly like a device-tier hit of the same depth
                promoted = self.prefix_cache.promote(ctx, matched,
                                                     self._try_alloc)
            else:
                matched = []
                promoted = 0
            hit = len(matched) * bs
            # a whole-context match (len(ctx) block-aligned and every
            # block cached) still must recompute the last token's
            # logits — and its K/V write may not land in a shared
            # block, so the final matched block is duplicated COW
            cow = bool(matched) and hit >= len(ctx)
            fresh = self._try_alloc(need - len(matched) + (1 if cow else 0))
            if fresh is None:
                if matched:
                    self.prefix_cache.cancel(matched)
                break               # fits once running requests retire
            self.waiting.popleft()
            req.slot = self._free_slots.pop()
            if cow:
                req.pending_cow = (matched[-1], fresh[0])
                req.block_table = matched[:-1] + [fresh[0]] + fresh[1:]
                req.num_cached = len(ctx) - 1
            else:
                req.block_table = matched + fresh
                req.num_cached = hit
            req.cached_prefix_tokens = min(hit, len(ctx))
            req.prefill_ctx = ctx
            req.prefill_sample = not req.generated
            # matched full blocks are already indexed; start the
            # registration cursor past them.  A COW duplicate stays
            # private (its key belongs to the original), which breaks
            # the chain — registration stops for good (_REG_STOPPED)
            req._reg_blocks = _REG_STOPPED if cow else len(matched)
            self.running[req.slot] = req
            self._admit_order.append(req)
            admitted.append(req)
            if self.journeys.enabled and req.journey is not None:
                # offload promotion is part of THIS admission's story:
                # blocks re-materialized from the host/disk tier to
                # satisfy the prefix match (0 when the device tier
                # covered it) — recorded before the admit hop so the
                # journey reads promote -> admit in causal order
                if promoted:
                    self.journeys.hop(req.journey, "offload_promote",
                                      uid=req.uid, blocks=promoted)
                self.journeys.hop(req.journey, "admit", uid=req.uid,
                                  cached=req.cached_prefix_tokens)
            if self.prefix_cache is not None:
                c = self.prefix_cache.counters
                c.incr("prefix_hit_tokens", req.cached_prefix_tokens)
                c.incr("prefix_miss_tokens",
                       len(ctx) - req.cached_prefix_tokens)
                c.incr("prefix_hit_requests" if matched
                       else "prefix_miss_requests")
                if cow:
                    c.incr("prefix_cow_blocks")
        return admitted

    def _prefill_context(self, req: Request) -> List[int]:
        """The tokens whose K/V the prefill must materialize: the
        prompt, plus — after a preemption — every generated token
        except the pending one (see module docstring)."""
        if req.generated:
            return req.prompt + req.generated[:-1]
        return list(req.prompt)

    def cow_done(self, req: Request) -> None:
        """The engine finished duplicating ``pending_cow``; drop the
        admission's extra ref on the shared source block."""
        src, _ = req.pending_cow
        req.pending_cow = None
        self.allocator.free([src])

    def prefill_plan(self, req: Request) -> Tuple[List[int], int, bool]:
        """The next chunk of ``req``'s pending prefill:
        ``(tokens, start, is_last)`` with ``start`` the absolute
        position of ``tokens[0]`` (== K/V already materialized).
        ``chunk_size=None`` returns the whole remaining tail at once.
        The caller runs the chunk through the engine, then
        :meth:`chunk_done`."""
        ctx = req.prefill_ctx
        assert ctx is not None, "prefill_plan on a non-prefilling request"
        start = req.num_cached
        n = len(ctx) - start
        if self.chunk_size is not None:
            n = min(n, self.chunk_size)
        return ctx[start:start + n], start, start + n == len(ctx)

    def chunk_done(self, req: Request, n: int) -> bool:
        """Account ``n`` freshly prefilled tokens; registers any newly
        full blocks into the prefix index.  True = the prefill is
        complete and ``req`` joins the decode batch (the caller samples
        from the final chunk's logits when ``req.prefill_sample``)."""
        req.num_cached += n
        self.register_progress(req)
        if req.num_cached == len(req.prefill_ctx):
            req.prefill_ctx = None
            return True
        return False

    def register_progress(self, req: Request) -> None:
        """Index every newly FULL block of ``req`` (prefill chunks and
        decode steps crossing a block boundary).  Stops for good at the
        first chain collision — descendants of an unindexed block can
        never be matched."""
        if self.prefix_cache is None:
            return
        bs = self.block_size
        full = req.num_cached // bs
        seq = req.prompt + req.generated
        while req._reg_blocks < full:
            i = req._reg_blocks
            parent = req.block_table[i - 1] if i else ROOT
            if not self.prefix_cache.register(
                    parent, tuple(seq[i * bs:(i + 1) * bs]),
                    req.block_table[i]):
                req._reg_blocks = _REG_STOPPED  # chain broken for good
                break
            req._reg_blocks += 1

    # -- disaggregated prefill/decode hand-off (docs/serving.md) -----------

    @property
    def has_free_slot(self) -> bool:
        return bool(self._free_slots)

    def admit_handoff(self, req: Request, block_table: List[int]) -> None:
        """Admit a request whose context K/V is ALREADY materialized in
        this scheduler's pool — the decode half of the disaggregated
        prefill/decode hand-off (``docs/serving.md``, "Disaggregated
        prefill/decode").  ``block_table`` must hold blocks allocated
        from THIS scheduler's allocator (the caller copied the K/V in
        via the engine's block-copy program, or imported it from
        another replica).  The request skips the prefill state machine
        entirely: it enters the decode batch at its carried
        ``num_cached`` position with ``next_input`` pending — exactly
        the state a just-finished local prefill would leave it in, so
        greedy decode from here is bit-identical to the monolithic
        engine's."""
        assert self._free_slots, "admit_handoff with no free slot"
        assert req.num_cached > 0 and req.next_input is not None, \
            (f"handoff request {req.uid} has no carried KV position "
             f"(num_cached={req.num_cached}, "
             f"next_input={req.next_input})")
        req.slot = self._free_slots.pop()
        req.block_table = list(block_table)
        req.prefill_ctx = None
        req.cached_prefix_tokens = 0
        # the handed-off blocks' contents are the request's own
        # context, so they register into this pool's prefix index (when
        # one exists) exactly like locally-prefilled blocks would
        req._reg_blocks = 0 if self.prefix_cache is not None \
            else _REG_STOPPED
        self.running[req.slot] = req
        self._admit_order.append(req)
        if self.journeys.enabled and req.journey is not None:
            self.journeys.hop(req.journey, "admit", uid=req.uid,
                              handoff=True,
                              carried_tokens=req.num_cached)

    def release_handoff(self, req: Request) -> None:
        """Free a request's slot and blocks in THIS pool after its
        context was copied out to another pool/replica — the prefill
        half of the hand-off.  Newly full blocks register into the
        prefix index first, so a prefill pool doubles as a warm
        shared-prefix cache: the handed-off request's blocks survive
        here as evictable LRU holds and the next shared-prefix
        admission matches them instead of re-prefilling."""
        self.register_progress(req)
        if self.journeys.enabled and req.journey is not None:
            self.journeys.hop(req.journey, "handoff_export",
                              uid=req.uid,
                              carried_tokens=req.num_cached)
        self._release(req)

    def ensure_decode_capacity(self, req: Request) -> bool:
        """Grow ``req``'s block table if its next token write needs a
        fresh block — evicting idle prefix-cache holds first, then
        preempting younger requests while the pool stays dry.  False =
        ``req`` has outgrown the pool with nothing left to evict or
        preempt; the caller must fail it with
        ``finish_reason="capacity"`` — preempting it would livelock,
        and raising would take the whole batch down."""
        need_blocks = req.num_cached // self.block_size + 1
        while len(req.block_table) < need_blocks:
            fresh = self._try_alloc(1)
            if fresh is not None:
                req.block_table.extend(fresh)
                continue
            victim = self._preempt_victim(exclude=req)
            if victim is None:
                return False
            self.preempt(victim)
        return True

    def lookahead_capacity(self, req: Request, tokens: int) -> int:
        """Grow ``req``'s table OPPORTUNISTICALLY so up to ``tokens``
        tokens can write at positions ``num_cached..`` — the K-token
        speculation lookahead.  Unlike :meth:`ensure_decode_capacity`
        this never preempts: lookahead is an optimization, and taking
        another request's blocks to verify guesses would let a bad
        drafter degrade its neighbors.  Evicting idle prefix-cache
        holds (via :meth:`_try_alloc`) is allowed — the same reclaim
        decode growth makes.  Returns how many tokens actually fit
        (>= 1 once :meth:`ensure_decode_capacity` succeeded); the
        caller trims its draft to ``fit - 1``."""
        bs = self.block_size
        tokens = min(tokens, self.max_context - req.num_cached)
        while len(req.block_table) * bs - req.num_cached < tokens:
            fresh = self._try_alloc(1)
            if fresh is None:
                break
            req.block_table.extend(fresh)
            self.lookahead_granted += 1
        return max(0, min(tokens,
                          len(req.block_table) * bs - req.num_cached))

    def rollback_lookahead(self, req: Request) -> int:
        """KV rollback after a verify step: free table blocks holding
        ONLY rejected-suffix positions (everything past the block the
        next token writes into).  Those blocks were lookahead-fresh —
        allocated this iteration, never registered, refcount 1 — so
        freeing them is exact; the garbage K/V inside the kept partial
        block sits beyond ``num_cached`` where the context bias masks
        it until a future write overwrites it.  Returns the number of
        blocks released."""
        keep = req.num_cached // self.block_size + 1
        tail = req.block_table[keep:]
        if not tail:
            return 0
        del req.block_table[keep:]
        self.allocator.free(tail)
        self.lookahead_rolled_back += len(tail)
        return len(tail)

    # -- pipelined in-flight hold (docs/serving.md) ------------------------

    def hold_inflight(self, reqs: List[Request]) -> None:
        """Pin ``reqs`` for the duration of a launched-but-not-retired
        device step: until :meth:`release_inflight`, they may not be
        preempted (their pending K/V writes would land in reallocated
        blocks).  One launch window at a time — holding while a hold
        is live is a serve-loop sequencing bug."""
        assert not self.inflight, \
            "hold_inflight while a launch window is already held"
        for req in reqs:
            assert req.running, \
                f"in-flight hold on non-running request {req.uid}"
            self.inflight[req.uid] = req

    def release_inflight(self) -> None:
        """The launched step's results were consumed (or its launch
        failed before enqueue): the window's requests are ordinary
        running requests again."""
        self.inflight.clear()

    # -- sampling-param batching (docs/serving.md, "Stochastic sampling") --

    @staticmethod
    def _pack_sampling(by_slot, width: int) -> Tuple[np.ndarray, ...]:
        """``{slot: SamplingParams}`` -> the per-slot launch arrays
        ``(temperature f32, top_k i32, top_p f32, seed i32)``, each
        ``(width,)``.  Unlisted slots get temperature 0 — the in-trace
        greedy lane — so idle and greedy rows cost the argmax path
        they always did."""
        temp = np.zeros((width,), np.float32)
        tk = np.zeros((width,), np.int32)
        tp = np.ones((width,), np.float32)
        seed = np.zeros((width,), np.int32)
        for slot, s in by_slot.items():
            temp[slot] = s.temperature
            tk[slot] = 0 if s.top_k is None else int(s.top_k)
            tp[slot] = s.top_p
            seed[slot] = int(s.seed) & 0x7FFFFFFF
        return temp, tk, tp, seed

    def sampling_inputs(self, requests) -> Optional[Tuple]:
        """The per-slot :class:`SamplingParams` arrays for one batched
        decode/verify launch — part of the engine's ONE-``device_put``
        launch struct.  None when every request is greedy: the caller
        then launches the historical argmax-only program (zero
        stochastic-lane cost for default traffic)."""
        if all(r.sampling.is_greedy for r in requests):
            return None
        return self._pack_sampling(
            {r.slot: r.sampling for r in requests},
            self.max_batch_size)

    @staticmethod
    def prefill_sampling(req: Request) -> Optional[Tuple]:
        """The ``(1,)``-wide sampling arrays for one request's
        prefill/chunk launch (None = greedy, the historical
        program)."""
        if req.sampling.is_greedy:
            return None
        return Scheduler._pack_sampling({0: req.sampling}, 1)

    def frag_slots(self) -> int:
        """Allocated-but-unwritten token slots across running tables —
        each request's last partial block's slack plus any lookahead
        slack it holds this instant.  The fragmentation numerator of
        ``stats()["memory"]`` (``docs/observability.md``): these slots
        cost HBM but hold no K/V yet."""
        bs = self.block_size
        return sum(len(r.block_table) * bs - r.num_cached
                   for r in self.running.values())

    def _preempt_victim(self, exclude: Request) -> Optional[Request]:
        """Priority-aware victim choice: the worst priority class
        (highest number) among running requests, youngest-admitted
        within the class — so foreground work monotonically keeps its
        blocks while best-effort work recomputes.  With uniform
        priorities this is exactly the historical youngest-first
        (LIFO) choice, so preemption bit-stability is unchanged."""
        victim = None
        victim_key = None
        for i, req in enumerate(self._admit_order):
            if req is exclude:
                continue
            if req.uid in self.inflight:
                # a launched-but-not-retired request's blocks are
                # pinned: its pending device step still writes K/V
                # through them (docs/serving.md, "Pipelined serve
                # loop")
                continue
            key = (req.priority, i)
            if victim_key is None or key > victim_key:
                victim, victim_key = req, key
        return victim

    def preempt(self, req: Request) -> None:
        """Evict ``req`` to the waiting queue's FRONT (it has seniority
        over never-started requests), freeing its slot and blocks."""
        assert req.running, "can only preempt a running request"
        req.preemptions += 1
        self.preemption_count += 1
        if self.tracer.enabled:
            self.tracer.instant("preempt", uid=req.uid,
                                blocks=len(req.block_table))
        if self.journeys.enabled and req.journey is not None:
            self.journeys.hop(req.journey, "preempt", uid=req.uid,
                              blocks=len(req.block_table))
        self._release(req)
        req.num_cached = 0
        self.waiting.appendleft(req)

    def retire(self, req: Request) -> None:
        """Return a finished request's slot and blocks to the pools
        (registered blocks become evictable cache holds — the shared
        prefix outlives the request)."""
        assert req.finished, "retire() is for finished requests"
        self.register_progress(req)
        self._release(req)
        self.finished.append(req)

    def fail(self, req: Request, reason: str) -> None:
        """Finish ``req`` with ``finish_reason=reason`` wherever it is
        in its lifecycle (waiting or running), returning any held slot
        and blocks — the single exit for ``capacity`` / ``timeout`` /
        ``nonfinite`` isolation.  Tokens generated so far stay on the
        request (a timed-out request returns its partial output)."""
        assert not req.finished, "fail() is for live requests"
        if req.running:
            self._release(req)
        elif req in self.waiting:
            self.waiting.remove(req)
        req.finished = True
        req.finish_reason = reason
        self.finished.append(req)
        if self.counters is not None:
            self.counters.incr(f"requests_failed_{reason}")

    def _release(self, req: Request) -> None:
        del self.running[req.slot]
        self._admit_order.remove(req)
        self.inflight.pop(req.uid, None)
        self._free_slots.append(req.slot)
        req.slot = -1
        req.prefill_ctx = None
        req._reg_blocks = 0
        req.cached_prefix_tokens = 0
        if req.pending_cow is not None:
            # admission COW never executed (failed/preempted before the
            # engine ran): drop the extra ref on the shared source
            self.allocator.free([req.pending_cow[0]])
            req.pending_cow = None
        if req.block_table:
            self.allocator.free(req.block_table)
            req.block_table = []

    # -- invariants (tests + bench) ---------------------------------------

    def audit(self) -> None:
        """Refcount/free-list invariants, asserted after scheduler
        steps in tests and the bench smoke: every block's refcount
        equals the number of running tables referencing it (plus a
        pending COW's source hold), ref-0 blocks are exactly free XOR
        cache-held, the free list and free set mirror each other, and
        waiting requests hold nothing."""
        alloc = self.allocator
        table_refs: Dict[int, int] = {}
        for req in self.running.values():
            for b in req.block_table:
                table_refs[b] = table_refs.get(b, 0) + 1
            if req.pending_cow is not None:
                src = req.pending_cow[0]
                table_refs[src] = table_refs.get(src, 0) + 1
        for req in self.waiting:
            assert not req.block_table, \
                f"waiting request {req.uid} holds blocks"
            assert req.pending_cow is None
        # the pipelined launch window: every in-flight request must
        # still be running with its table intact — a preempted/failed/
        # retired request lingering in the hold means the pending
        # device step will write through blocks the scheduler already
        # recycled (docs/serving.md, "Pipelined serve loop")
        for uid, req in self.inflight.items():
            assert req.running and self.running.get(req.slot) is req, \
                f"in-flight request {uid} is no longer running"
            assert req.block_table, \
                f"in-flight request {uid} holds no blocks"
        free = set(alloc._free)
        assert len(alloc._free) == len(free) == len(alloc._free_set)
        assert free == alloc._free_set, "free list / free set diverged"
        held = (self.prefix_cache.held_blocks()
                if self.prefix_cache is not None else set())
        for b in range(1, alloc.cfg.num_blocks):
            r = alloc.refs(b)
            t = table_refs.get(b, 0)
            assert r == t, \
                f"block {b}: refcount {r} != {t} table references"
            if r == 0:
                assert (b in free) != (b in held), \
                    (f"ref-0 block {b}: free={b in free} "
                     f"held={b in held} (must be exactly one)")
            else:
                assert b not in free and b not in held, \
                    f"live block {b} also free/held"
        if self.prefix_cache is not None:
            self.prefix_cache.audit()
