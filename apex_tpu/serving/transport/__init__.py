"""KV transport layer (``docs/serving.md``, "KV transport").

The interchangeable-backend abstraction every KV block movement rides
— disagg hand-off, elastic prefix warm, offload promote — with a
retry/deadline/breaker robustness envelope and exactly-once ingest.
"""

from .base import (KVTransport, ReceiverLedger,
                   TransportConnectionError, TransportError,
                   TransportFrameError, TransportPolicy,
                   TransportTimeoutError)
from .inprocess import InProcessTransport
from .sockets import (MAX_FRAME_BYTES, FrameReader, SocketTransport,
                      decode_payload, encode_frame, encode_payload)

__all__ = [
    "FrameReader",
    "InProcessTransport",
    "KVTransport",
    "MAX_FRAME_BYTES",
    "ReceiverLedger",
    "SocketTransport",
    "TransportConnectionError",
    "TransportError",
    "TransportFrameError",
    "TransportPolicy",
    "TransportTimeoutError",
    "decode_payload",
    "encode_frame",
    "encode_payload",
]
