"""The in-process KV transport backend — the historical direct copy.

``_deliver`` is a function call into the receiver's handler on the
sender's thread: no serialization, no copy, no extra RNG draws, and
(with ``chaos=None``) not a single branch the direct-call era didn't
take — which is why it is the DEFAULT backend everywhere and why the
legacy seed-0 chaos soak reports stay byte-identical with transport
on (``docs/serving.md``, "KV transport").

It still runs the full :class:`~.base.TransportPolicy` envelope —
deadline, bounded retry, per-peer breaker, exactly-once dedup ledger
— so the fault model is testable without a socket: the chaos plane
injects resets/stalls/duplicates at the ``_deliver`` seam and every
consumer's degradation path exercises for real.
"""

from __future__ import annotations

from .base import KVTransport

__all__ = ["InProcessTransport"]


class InProcessTransport(KVTransport):
    """Direct-call backend: ``send`` == ``handler(meta, payload)``
    under the policy envelope.  ``carries_objects`` is True — meta may
    carry live objects (journey contexts) because nothing is ever
    serialized."""

    backend = "inprocess"
    carries_objects = True

    def _deliver(self, st, tid, meta, payload):
        return self._ingest(st, tid, meta, payload)
