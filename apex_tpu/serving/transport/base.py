"""KV transport — the robustness envelope around block movement.

Every serving tier that moves KV blocks between pools — the disagg
hand-off (``docs/serving.md``, "Disaggregated prefill/decode"), the
elastic scale-up prefix warm ("Elastic fleet"), the hierarchical
offload promote ("Hierarchical KV offload") — used to call the
checksummed ``export_blocks``/``import_blocks`` pair directly.  That
path verifies payload integrity (torn payloads rejected WHOLE) but has
no deadline, no retry policy, no duplicate suppression, and no fault
model beyond corruption; the first real socket adds connection resets,
stalls, duplicated delivery, and reordering.  :class:`KVTransport` is
the promotion of that path into a first-class interface with
interchangeable backends:

- :class:`~apex_tpu.serving.transport.InProcessTransport` — the
  direct call, byte- and schedule-identical to the historical path;
  the default everywhere.
- :class:`~apex_tpu.serving.transport.SocketTransport` —
  length-prefixed crc-framed payloads over a loopback TCP stream with
  a stdlib server thread; the codebase's first true cross-process
  network surface, and the template the multi-host topology
  (ROADMAP.md) composes on.

Both backends run under the same :class:`TransportPolicy` envelope:

- **per-transfer deadline** — a send is bounded by
  ``policy.deadline_s`` of (injected) clock across all attempts;
- **bounded retry with decorrelated jitter** — transport-level
  failures (:class:`TransportConnectionError`) retry through
  :func:`apex_tpu.resilience.retry.retry`; application-level
  rejections by the receiving handler (``ValueError`` for a torn
  payload, ``MemoryError`` for a full pool) are NOT retried — they
  re-raise natively so every consumer's existing degradation path
  (monolithic fallback / cold prefill / skip warm) fires unchanged;
- **per-peer circuit breaker** — a dead endpoint fast-fails new
  sends (:class:`~apex_tpu.resilience.breaker.CircuitBreaker`)
  instead of burning the full retry budget per transfer;
- **exactly-once ingest** — each send carries a monotonic transfer
  id; the receiver keeps a bounded :class:`ReceiverLedger` of
  completed transfers, so a duplicated delivery (or a
  retried-after-partial-ack transfer whose first attempt DID land)
  returns the cached ack instead of double-importing blocks.

The exactly-once argument, precisely: the ledger records a transfer
id *only after* its handler returned (blocks imported, ack computed).
A transfer that failed before the handler ran leaves no ledger entry,
so its retry imports normally; a transfer whose ack was lost in
flight finds its ledger entry on retry and returns the recorded ack
without touching the handler — the import happened exactly once
either way.  The ledger is bounded (``policy.dedup_window``), which
is sound because transfer ids are monotonic and retries are bounded:
a duplicate can only arrive within ``policy.attempts`` sends of the
original, far inside the window.
"""

from __future__ import annotations

import random
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ...resilience.breaker import CircuitBreaker
from ...resilience.retry import RetryError, retry

__all__ = [
    "KVTransport",
    "ReceiverLedger",
    "TransportConnectionError",
    "TransportError",
    "TransportFrameError",
    "TransportPolicy",
    "TransportTimeoutError",
]


class TransportError(RuntimeError):
    """A transfer failed at the TRANSPORT level (never an
    application-level rejection — those re-raise natively as
    ``ValueError``/``MemoryError`` so consumer degradation paths stay
    unchanged)."""


class TransportConnectionError(TransportError):
    """Connection-class failure: refused, reset mid-frame, closed
    before the ack.  Retried by the policy envelope."""


class TransportTimeoutError(TransportError):
    """The transfer stalled past its deadline.  NOT retried — the
    deadline already bounds the whole send; the consumer degrades."""


class TransportFrameError(TransportError):
    """A malformed wire frame: bad magic, oversized, crc mismatch.
    The receiving side closes the connection without ingesting
    anything (torn frames are rejected whole, like torn payloads)."""


@dataclass
class TransportPolicy:
    """The robustness envelope both backends run under.  Everything
    time-like is injectable (``clock``/``sleep``/``rng``) so chaos
    soaks and unit tests replay byte-identically with zero real
    sleeping — the :func:`~apex_tpu.resilience.retry.retry`
    convention."""

    deadline_s: float = 5.0        # total wall budget per send
    attempts: int = 3              # tries per send, incl. the first
    backoff: float = 0.01          # decorrelated-jitter base delay
    max_backoff: float = 0.25      # per-delay cap
    breaker_failures: int = 3      # consecutive failures to open
    breaker_recovery_s: float = 30.0
    dedup_window: int = 256        # receiver ledger entries per peer
    clock: Callable[[], float] = time.monotonic
    sleep: Callable[[float], None] = time.sleep
    rng: Optional[random.Random] = None   # default: seeded per transport


class ReceiverLedger:
    """Bounded memory of completed transfers — the receiver half of
    exactly-once.  Records ``tid -> ack`` only for transfers whose
    handler SUCCEEDED; a duplicate of a recorded tid is answered from
    the ledger (``dedup_hits``) without re-running the handler."""

    def __init__(self, window: int):
        self.window = max(1, int(window))
        self._acks: "OrderedDict[int, Any]" = OrderedDict()
        self.dedup_hits = 0

    def lookup(self, tid: int):
        """``(hit, ack)`` — a hit counts toward ``dedup_hits``."""
        if tid in self._acks:
            self.dedup_hits += 1
            return True, self._acks[tid]
        return False, None

    def record(self, tid: int, ack) -> None:
        self._acks[tid] = ack
        while len(self._acks) > self.window:
            self._acks.popitem(last=False)

    def __len__(self) -> int:
        return len(self._acks)


_PEER_COUNTER_KEYS = (
    "attempts", "retries", "delivered", "rejects", "failures",
    "deadline_exceeded", "breaker_fastfail", "ingested")


@dataclass
class _PeerState:
    """Everything the envelope tracks per registered peer."""

    name: str
    handler: Optional[Callable[[dict, dict], Any]]
    breaker: CircuitBreaker
    ledger: ReceiverLedger
    address: Optional[tuple] = None      # socket backend routes
    counters: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        for k in _PEER_COUNTER_KEYS:
            self.counters.setdefault(k, 0)


class KVTransport:
    """The backend-agnostic half: peer registry, transfer-id counter,
    retry/deadline/breaker envelope, and the exactly-once receiver.
    Subclasses implement :meth:`_deliver` (move one framed payload to
    the peer and return its ack).

    ``chaos`` is the fault-injection seam
    (:class:`apex_tpu.resilience.chaos.ChaosTransport`): ``None`` (the
    default) short-circuits to zero overhead and zero extra RNG draws,
    so default-on transport is behavior-identical to the direct-call
    path it replaced.
    """

    backend = "abstract"
    # whether meta may carry non-serializable objects (journey
    # contexts); only the in-process backend can
    carries_objects = False

    def __init__(self, policy: Optional[TransportPolicy] = None):
        self.policy = policy or TransportPolicy()
        # guards the peer registry, ledgers, and counters against the
        # socket backend's server threads (lock-discipline scope,
        # pyproject [tool.apexlint."lock-discipline"]); RLock because
        # _dispatch -> _ingest nests
        self._lock = threading.RLock()
        self._peers: Dict[str, _PeerState] = {}
        self._next_tid = 0
        # retry jitter: seeded per transport, independent of global
        # random state (the resilience/retry convention)
        self._rng = self.policy.rng if self.policy.rng is not None \
            else random.Random(0)
        self.chaos = None

    # -- registry ----------------------------------------------------------

    def register_peer(self, name: str,
                      handler: Callable[[dict, dict], Any]) -> None:
        """Register a locally-served peer: ``handler(meta, payload)``
        ingests one transfer and returns its ack.  Handler exceptions
        are application-level: they propagate to the sender natively
        and are never cached in the dedup ledger."""
        pol = self.policy
        with self._lock:
            self._peers[name] = _PeerState(
                name=name, handler=handler,
                breaker=CircuitBreaker(
                    failure_threshold=pol.breaker_failures,
                    recovery_time=pol.breaker_recovery_s,
                    clock=pol.clock),
                ledger=ReceiverLedger(pol.dedup_window))

    def peers(self):
        with self._lock:
            return sorted(self._peers)

    # -- the send envelope -------------------------------------------------

    def send(self, peer: str, meta: dict, payload: dict):
        """Move one checksummed payload to ``peer`` under the policy
        envelope; returns the peer handler's ack.

        Raises :class:`TransportError` subclasses for transport-level
        failures (after retries / deadline / breaker), and re-raises
        the handler's ``ValueError``/``MemoryError`` natively so the
        consumer's torn-payload and at-capacity degradation paths are
        indistinguishable from the direct-call era."""
        with self._lock:
            st = self._peers.get(peer)
            if st is None:
                raise TransportError(
                    f"unknown transport peer {peer!r} "
                    f"(registered: {sorted(self._peers)})")
            tid = self._next_tid
            self._next_tid += 1
        if not st.breaker.allow():
            with self._lock:
                st.counters["breaker_fastfail"] += 1
                st.counters["failures"] += 1
            raise TransportConnectionError(
                f"peer {peer!r} circuit open — transfer {tid} "
                f"fast-failed (degrade, don't wait)")
        plan = self.chaos.plan_send(peer) if self.chaos is not None \
            else None
        pol = self.policy

        def _attempt():
            with self._lock:
                st.counters["attempts"] += 1
            p = payload
            if plan is not None:
                p = plan.before(p)       # may raise / corrupt a copy
            ack = self._deliver(st, tid, meta, p)
            if plan is not None:
                plan.after(lambda: self._deliver(st, tid, meta,
                                                 payload))
            return ack

        def _on_retry(attempt, err):
            with self._lock:
                st.counters["retries"] += 1

        try:
            ack = retry(_attempt,
                        attempts=pol.attempts,
                        backoff=pol.backoff,
                        max_backoff=pol.max_backoff,
                        deadline=pol.deadline_s,
                        retry_on=(TransportConnectionError,),
                        sleep=pol.sleep, clock=pol.clock,
                        rng=self._rng, on_retry=_on_retry)
        except (ValueError, MemoryError):
            # application-level rejection: the peer answered, so it is
            # HEALTHY — the payload (or its capacity) is the problem
            st.breaker.record_success()
            with self._lock:
                st.counters["rejects"] += 1
            raise
        except TransportTimeoutError:
            st.breaker.record_failure()
            with self._lock:
                st.counters["deadline_exceeded"] += 1
                st.counters["failures"] += 1
            raise
        except RetryError as e:
            st.breaker.record_failure()
            with self._lock:
                st.counters["failures"] += 1
            raise TransportConnectionError(
                f"transfer {tid} to {peer!r} failed: {e}") from e
        except TransportError:
            st.breaker.record_failure()
            with self._lock:
                st.counters["failures"] += 1
            raise
        st.breaker.record_success()
        with self._lock:
            st.counters["delivered"] += 1
        return ack

    # -- the receive side --------------------------------------------------

    def _ingest(self, st: _PeerState, tid: int, meta: dict,
                payload: dict):
        """Exactly-once ingest: answer duplicates from the ledger,
        otherwise run the handler and record its ack.  Handler
        exceptions are NOT recorded — the transfer did not happen, so
        its retry must import for real."""
        with self._lock:
            hit, cached = st.ledger.lookup(tid)
            if hit:
                return cached
            if st.handler is None:
                raise TransportError(
                    f"peer {st.name!r} has no local handler")
            ack = st.handler(meta, payload)
            st.counters["ingested"] += 1
            st.ledger.record(tid, ack)
            return ack

    def _deliver(self, st: _PeerState, tid: int, meta: dict,
                 payload: dict):
        raise NotImplementedError

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The pinned ``stats()["transport"]`` shape
        (``docs/serving.md``, "KV transport"): aggregate counters plus
        a per-peer table with breaker state.  Key set is shape-stable
        — dashboards and ``ops_probe --transport`` rely on it."""
        with self._lock:
            totals = {k: 0 for k in _PEER_COUNTER_KEYS}
            totals["dedup_hits"] = 0
            per_peer = {}
            for name, st in sorted(self._peers.items()):
                row = dict(st.counters)
                row["dedup_hits"] = st.ledger.dedup_hits
                row["breaker"] = st.breaker.state
                per_peer[name] = row
                for k in _PEER_COUNTER_KEYS:
                    totals[k] += st.counters[k]
                totals["dedup_hits"] += st.ledger.dedup_hits
            out = {"backend": self.backend, "peers": len(per_peer)}
            out.update(totals)
            out["per_peer"] = per_peer
            return out

    def close(self) -> None:
        """Release backend resources (the socket backend's server
        thread); the in-process backend has none."""
