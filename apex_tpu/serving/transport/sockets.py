"""The socket KV transport backend — crc-framed payloads over TCP.

The codebase's first true cross-process network surface: each
transfer opens a one-shot loopback TCP connection, writes one
length-prefixed crc-framed request, and waits (deadline-bounded) for
one ack frame.  A stdlib server thread accepts connections and
dispatches frames into the shared exactly-once receiver
(:meth:`~.base.KVTransport._ingest`), so the dedup ledger, breaker,
and retry envelope are IDENTICAL to the in-process backend — only the
wire differs.

Wire format (one frame)::

    magic   b"KVTX"          4 bytes
    version u8               currently 1
    kind    u8               1=REQ  2=ACK  3=ERR
    hlen    u32 (big-endian) JSON header length
    blen    u64 (big-endian) raw body length
    crc     u32 (big-endian) zlib.crc32(header_bytes + body)
    header  hlen bytes       JSON
    body    blen bytes       concatenated raw leaf buffers

A REQ header carries ``peer`` / ``tid`` / ``meta`` plus the payload
geometry (``num_blocks``/``block_size``), the per-leaf crc dict, the
optional per-block crc sidecar, and a ``manifest`` of
``[name, dtype, shape]`` rows locating each leaf inside the body —
every cache leaf rides the same frame, int8 scale sidecars included.
An ACK header carries the handler's ack; an ERR header carries
``etype``/``message`` and maps application-level rejections
(``ValueError``/``MemoryError``) back to NATIVE exceptions at the
sender, so torn-payload semantics cross the wire unchanged.

Frame-level integrity is separate from payload-level integrity: a
frame whose crc fails, whose magic is wrong, or whose declared size
exceeds ``max_frame_bytes`` raises
:class:`~.base.TransportFrameError` and the connection closes with
NOTHING ingested (torn frames rejected whole, like torn payloads).
The sender sees a connection-class failure and retries — and the
dedup ledger makes the retry safe even if the frame died after
dispatch.

Reordering: TCP preserves byte order within a connection, and each
transfer uses its own connection, so cross-transfer reordering cannot
interleave frames — but :class:`FrameReader` is still a strict
incremental parser (split reads across frame boundaries are
reassembled; trailing garbage is a frame error), which the codec
units in ``tests/L0/test_transport.py`` pin directly.

When NOT to use this backend: same-process pools (the default
everywhere).  It exists for the cross-process topology and costs a
host serialize/deserialize round-trip per transfer plus a connection
setup — ``serving_bench --transport`` records the gap.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import zlib
from typing import Optional

import numpy as np

from ...resilience.breaker import CircuitBreaker
from .base import (KVTransport, ReceiverLedger,
                   TransportConnectionError, TransportError,
                   TransportFrameError, TransportPolicy,
                   TransportTimeoutError, _PeerState)

__all__ = [
    "FrameReader",
    "KIND_ACK",
    "KIND_ERR",
    "KIND_REQ",
    "MAX_FRAME_BYTES",
    "SocketTransport",
    "decode_payload",
    "encode_frame",
    "encode_payload",
]

MAGIC = b"KVTX"
VERSION = 1
KIND_REQ, KIND_ACK, KIND_ERR = 1, 2, 3
# 64 MiB default ceiling: a warm/hand-off payload at serving scale is
# a few MiB; anything bigger is a corrupt length field, not a payload
MAX_FRAME_BYTES = 64 << 20

_PRELUDE = struct.Struct(">4sBBIQI")     # magic ver kind hlen blen crc


def encode_frame(kind: int, header: dict, body: bytes = b"") -> bytes:
    """One wire frame; ``header`` must be JSON-serializable (the
    socket backend never carries live objects — ``carries_objects``
    is False)."""
    try:
        hbytes = json.dumps(header, separators=(",", ":")).encode()
    except TypeError as e:
        raise TransportError(
            f"socket transport header is not JSON-serializable "
            f"({e}) — live objects cannot cross the wire") from e
    crc = zlib.crc32(body, zlib.crc32(hbytes))
    return _PRELUDE.pack(MAGIC, VERSION, kind, len(hbytes),
                         len(body), crc) + hbytes + body


class FrameReader:
    """Incremental frame parser: :meth:`feed` raw socket bytes in any
    split, get back complete ``(kind, header, body)`` frames.  Every
    malformation — bad magic, bad version, oversized declared length,
    crc mismatch, unparseable header — raises
    :class:`~.base.TransportFrameError` with nothing partially
    delivered; the caller closes the connection."""

    def __init__(self, max_frame_bytes: int = MAX_FRAME_BYTES):
        self.max_frame_bytes = max_frame_bytes
        self._buf = bytearray()

    def feed(self, data: bytes):
        self._buf.extend(data)
        frames = []
        while len(self._buf) >= _PRELUDE.size:
            magic, ver, kind, hlen, blen, crc = _PRELUDE.unpack_from(
                self._buf)
            if magic != MAGIC:
                raise TransportFrameError(
                    f"bad frame magic {bytes(magic)!r} "
                    f"(expected {MAGIC!r})")
            if ver != VERSION:
                raise TransportFrameError(
                    f"unsupported frame version {ver} "
                    f"(speak version {VERSION})")
            total = hlen + blen
            if total > self.max_frame_bytes:
                raise TransportFrameError(
                    f"frame of {total} bytes exceeds the "
                    f"{self.max_frame_bytes}-byte ceiling — corrupt "
                    f"length field or oversized payload; rejected "
                    f"whole, connection closed")
            if len(self._buf) < _PRELUDE.size + total:
                break                     # wait for more bytes
            start = _PRELUDE.size
            hbytes = bytes(self._buf[start:start + hlen])
            body = bytes(self._buf[start + hlen:start + total])
            del self._buf[:start + total]
            if zlib.crc32(body, zlib.crc32(hbytes)) != crc:
                raise TransportFrameError(
                    "frame crc mismatch — torn in flight; rejected "
                    "whole, nothing ingested")
            try:
                header = json.loads(hbytes)
            except ValueError as e:
                raise TransportFrameError(
                    f"frame header is not JSON ({e})") from e
            frames.append((kind, header, body))
        return frames


def _dtype_tag(dt) -> str:
    """Wire tag for a leaf dtype.  Standard numerics use the numpy
    byte-order string (``<f4``); extended ml_dtypes types (bfloat16 —
    the DEFAULT cache dtype — float8s, ...) register as numpy void
    records whose ``.str`` is ``<V2``, which would silently decode as
    non-numeric void on the far side, so they ride by NAME instead."""
    return dt.str if dt.kind != "V" else dt.name


def _resolve_dtype(tag: str) -> "np.dtype":
    """Inverse of :func:`_dtype_tag`.  Name tags resolve through
    ml_dtypes (jax's own extended-dtype registry); an unknown tag is a
    frame error, not a silent void reinterpretation."""
    try:
        dt = np.dtype(tag)
    except TypeError:
        dt = None
    if dt is not None and dt.kind != "V":
        return dt
    try:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, tag))
    except (ImportError, AttributeError, TypeError):
        raise TransportFrameError(
            f"manifest names unknown leaf dtype {tag!r}; rejected "
            f"whole, nothing ingested")


def encode_payload(payload: dict):
    """``(header_fields, body)``: the checksummed payload dict
    (``engine.export_blocks`` shape) flattened to a leaf manifest +
    one contiguous byte body.  Every leaf rides — K, V, and the int8
    pool's scale sidecars alike."""
    manifest, chunks = [], []
    for name in sorted(payload["leaves"]):
        arr = np.asarray(payload["leaves"][name])
        manifest.append([name, _dtype_tag(arr.dtype), list(arr.shape)])
        chunks.append(arr.tobytes())
    fields = {"num_blocks": int(payload["num_blocks"]),
              "block_size": int(payload["block_size"]),
              "manifest": manifest,
              "crc": {k: int(v) for k, v in payload["crc"].items()}}
    if payload.get("block_crc") is not None:
        fields["block_crc"] = {
            name: [int(c) for c in crcs]
            for name, crcs in payload["block_crc"].items()}
    return fields, b"".join(chunks)


def decode_payload(header: dict, body: bytes) -> dict:
    """Rebuild the payload dict from a REQ frame.  Leaf byte counts
    must tile the body exactly — a mismatch is a frame error (the crc
    already matched, so this is a corrupt manifest)."""
    leaves = {}
    off = 0
    for name, dtype, shape in header["manifest"]:
        dt = _resolve_dtype(dtype)
        n = dt.itemsize * int(np.prod(shape, dtype=np.int64)) \
            if shape else dt.itemsize
        if off + n > len(body):
            raise TransportFrameError(
                f"manifest overruns frame body at leaf {name!r} "
                f"({off + n} > {len(body)} bytes)")
        leaves[name] = np.frombuffer(
            body, dtype=dt, count=int(np.prod(shape, dtype=np.int64)),
            offset=off).reshape(shape).copy()
        off += n
    if off != len(body):
        raise TransportFrameError(
            f"frame body has {len(body) - off} trailing bytes the "
            f"manifest does not claim")
    out = {"num_blocks": header["num_blocks"],
           "block_size": header["block_size"],
           "leaves": leaves,
           "crc": {k: int(v) for k, v in header["crc"].items()}}
    if header.get("block_crc") is not None:
        out["block_crc"] = {
            name: [int(c) for c in crcs]
            for name, crcs in header["block_crc"].items()}
    return out


class SocketTransport(KVTransport):
    """Loopback-TCP backend: a stdlib server thread serves the
    locally-registered peers; ``send`` opens a one-shot connection
    (to a routed address, or back to the own server for local peers)
    per transfer.  Registered in the apexlint lock-discipline scope:
    the server thread reaches shared transport state only through
    :meth:`_dispatch`, which serializes on the transport lock."""

    backend = "socket"
    carries_objects = False

    def __init__(self, policy: Optional[TransportPolicy] = None, *,
                 host: str = "127.0.0.1",
                 max_frame_bytes: int = MAX_FRAME_BYTES):
        super().__init__(policy)
        self.max_frame_bytes = max_frame_bytes
        self._listener = socket.create_server((host, 0))
        self.address = self._listener.getsockname()
        self._thread = threading.Thread(
            target=self._serve, name="kv-transport-server", daemon=True)
        self._thread.start()

    def register_route(self, name: str, address) -> None:
        """Route ``name`` to another transport's server address (the
        cross-process shape).  The peer gets the full envelope —
        breaker, ledger for its OWN inbound — but no local handler."""
        pol = self.policy
        with self._lock:
            self._peers[name] = _PeerState(
                name=name, handler=None,
                breaker=CircuitBreaker(
                    failure_threshold=pol.breaker_failures,
                    recovery_time=pol.breaker_recovery_s,
                    clock=pol.clock),
                ledger=ReceiverLedger(pol.dedup_window),
                address=tuple(address))

    # -- sender ------------------------------------------------------------

    def _deliver(self, st, tid, meta, payload):
        fields, body = encode_payload(payload)
        header = dict(fields, peer=st.name, tid=tid, meta=meta)
        frame = encode_frame(KIND_REQ, header, body)
        addr = st.address or self.address
        # the per-attempt socket timeout; the retry envelope's
        # deadline bounds the whole send on top
        timeout = self.policy.deadline_s
        try:
            with socket.create_connection(addr,
                                          timeout=timeout) as conn:
                conn.sendall(frame)
                reader = FrameReader(self.max_frame_bytes)
                frames = []
                while not frames:
                    chunk = conn.recv(1 << 16)
                    if not chunk:
                        raise TransportConnectionError(
                            f"transfer {tid} to {st.name!r}: "
                            f"connection closed before the ack")
                    frames = reader.feed(chunk)
        except socket.timeout as e:
            raise TransportTimeoutError(
                f"transfer {tid} to {st.name!r} stalled past "
                f"{timeout}s") from e
        except TransportError:
            raise
        except OSError as e:
            raise TransportConnectionError(
                f"transfer {tid} to {st.name!r}: {e}") from e
        kind, hdr, _ = frames[0]
        if kind == KIND_ACK:
            return hdr.get("ack")
        if kind == KIND_ERR:
            etype, msg = hdr.get("etype"), hdr.get("message", "")
            # application-level rejections cross the wire as their
            # native types — consumer degradation paths must not be
            # able to tell the backends apart
            if etype == "ValueError":
                raise ValueError(msg)
            if etype == "MemoryError":
                raise MemoryError(msg)
            raise TransportError(
                f"peer {st.name!r} answered {etype}: {msg}")
        raise TransportFrameError(
            f"unexpected frame kind {kind} in ack position")

    # -- server ------------------------------------------------------------

    def _serve(self):
        # the accept loop is the documented lock-free path: it holds
        # no shared transport state beyond the listener handle, and
        # blocking in accept() under the lock would wedge every sender
        # apexlint: disable=lock-discipline
        listener = self._listener
        while True:
            try:
                conn, _ = listener.accept()
            except OSError:
                return                    # listener closed by close()
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        # per-connection framing is connection-private state; shared
        # transport state is only reached via _dispatch (which takes
        # the transport lock) — the lock-discipline boundary
        # apexlint: disable=lock-discipline
        reader = FrameReader(self.max_frame_bytes)
        with conn:
            while True:
                try:
                    chunk = conn.recv(1 << 16)
                except OSError:
                    return
                if not chunk:
                    return
                try:
                    frames = reader.feed(chunk)
                except TransportFrameError as e:
                    # torn/oversized frame: answer with a messaged
                    # error, ingest nothing, close the connection
                    try:
                        conn.sendall(encode_frame(
                            KIND_ERR,
                            {"etype": "TransportFrameError",
                             "message": str(e)}))
                    except OSError:
                        pass
                    return
                for kind, header, body in frames:
                    try:
                        conn.sendall(self._dispatch(kind, header,
                                                    body))
                    except OSError:
                        return

    def _dispatch(self, kind, header, body) -> bytes:
        """One REQ frame -> one ACK/ERR frame.  Every touch of shared
        transport state (peer registry, dedup ledger, counters)
        happens under the transport lock — the server thread's only
        entry into it."""
        with self._lock:
            if kind != KIND_REQ:
                return encode_frame(
                    KIND_ERR, {"etype": "TransportFrameError",
                               "message": f"unexpected frame kind "
                                          f"{kind}"})
            st = self._peers.get(header.get("peer"))
            if st is None or st.handler is None:
                return encode_frame(
                    KIND_ERR,
                    {"etype": "TransportError",
                     "message": f"no local handler for peer "
                                f"{header.get('peer')!r}"})
            try:
                payload = decode_payload(header, body)
                ack = self._ingest(st, int(header["tid"]),
                                   header.get("meta") or {}, payload)
            except (ValueError, MemoryError) as e:
                return encode_frame(
                    KIND_ERR, {"etype": type(e).__name__,
                               "message": str(e)})
            except TransportError as e:
                return encode_frame(
                    KIND_ERR, {"etype": type(e).__name__,
                               "message": str(e)})
            except Exception as e:   # noqa: BLE001 — a handler crash
                # must answer the sender (who degrades immediately),
                # not kill this thread and leave it waiting out its
                # whole deadline on a silent connection
                return encode_frame(
                    KIND_ERR, {"etype": type(e).__name__,
                               "message": str(e)})
            return encode_frame(
                KIND_ACK, {"tid": int(header["tid"]), "ack": ack})

    def close(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)
