"""apex_tpu.serving — batched inference: KV-cache + continuous batching.

The training stack (amp, optimizers, parallel, models) answers "how
fast can we learn"; this package answers "how much traffic can we
serve".  Three layers, bottom-up:

- :mod:`serving.kv_cache` — a preallocated, block-table-indexed KV
  pool (vLLM's PagedAttention memory model, fixed-shape for
  jit-stability; dtype from the amp half policy) with a host-side
  free-list allocator;
- :mod:`serving.engine` — the jitted device steps: bucketed causal
  prefill (reusing the training forward, flash-attention pluggable)
  and a single-token batched decode through
  ``ops.cached_attention``;
- :mod:`serving.prefix_cache` — a block-level prefix index
  (RadixAttention-style, keyed on full-block token chunks chained by
  physical parent id) over the allocator's refcounts: shared-prefix
  traffic maps its longest cached prefix onto shared blocks and only
  prefills the tail, idle cached blocks evict LRU under pool
  pressure, and whole-context hits duplicate their last block
  copy-on-write;
- :mod:`serving.scheduler` / :mod:`serving.api` — Orca-style
  iteration-level continuous batching (admit-on-slot-free, per-request
  EOS/max-token termination, preempt-youngest on memory pressure) with
  Sarathi-style CHUNKED PREFILL (one fixed-size chunk per prefilling
  request per iteration, interleaved with decode, so long prompts
  stall running requests by at most one chunk) and the synchronous
  :class:`InferenceServer` front door, with failure isolation: one
  pathological request finishes alone (``finish_reason`` ``capacity``
  / ``timeout`` / ``rejected`` / ``nonfinite``) instead of raising
  into the batch (``docs/resilience.md``);
- :mod:`serving.speculation` — speculative decoding with BIT-EXACT
  greedy acceptance (on by default, ``enable_speculation=False`` opts
  out): zero-weight n-gram/prompt-lookup drafts from each request's
  own history (a small-model drafter plugs in via
  :class:`~serving.speculation.DraftSource`) are scored K-at-a-time by
  the engine's fixed-width verify program
  (``ops.chunk_cached_attention`` over the live block-table cache);
  the accepted tokens are exactly the drafts matching the model's own
  argmax plus the model's next token, so output is bit-identical to
  one-token decode while repetitive traffic decodes several tokens
  per engine step;
- on-device stochastic sampling (``docs/serving.md``, "Stochastic
  sampling"): per-request :class:`~apex_tpu.ops.sampling.SamplingParams`
  (temperature / top-k / top-p / seed; default greedy, bit-identical
  to the historical argmax path) sample INSIDE the fused programs
  with counter-based PRNG keys — streams are pure functions of
  (prompt, params, seed), so same-seed replay, preemption resume,
  and the chaos oracle stay byte-exact — and speculation generalizes
  to stochastic drafts via rejection sampling (Gumbel-max coupling:
  accept a draft iff it equals the column's own sample), so sampled
  traffic keeps BOTH fast paths instead of falling back to the
  synchronous logits path (a legacy custom ``sample_fn`` still
  forces the fallback, now with a loud warning);
- tensor-parallel sharded serving (``docs/serving.md``,
  "Tensor-parallel serving"): pass ``mesh=`` (+ optional
  ``tp_rules=``) and the engine lowers every compiled program through
  GSPMD over a device mesh — params split Megatron-style
  (``parallel.gpt_tp_rules``), the KV pool shards its heads dim while
  block tables stay replicated host state, and the fused sampling
  twins take the vocab-parallel argmax path
  (``ops.vocab_parallel_sample``) so logits never gather; greedy
  output is bit-identical to the unsharded engine
  (``tests/L0/test_serving_tp.py``);
- quantized int8 KV cache (``docs/serving.md``, "Quantized KV
  cache"): ``kv_quant="int8"`` (env twin ``APEX_TPU_KV_QUANT``)
  stores the pool int8 with a per-slot per-head fp32 absmax scale
  sidecar — quantization fused into every write program,
  dequantization fused into every read (in-kernel on the Pallas
  decode path), ~1.9x concurrent live blocks per HBM byte net of the
  sidecar at head_dim 64; quant-on output is held to a decode-parity
  tolerance budget vs the full-width pool and is BIT-STABLE across
  COW / preemption / eviction / chunking / speculation / pipeline /
  tensor parallelism (``tests/L0/test_kv_quant.py``);
- :mod:`serving.overload` + the lifecycle layer — priority-aware load
  shedding (``finish_reason="shed"``) under queue/pool pressure, a
  circuit breaker in front of ``submit``
  (``finish_reason="breaker_open"``), and graceful ``drain()`` /
  ``close()`` with bit-identical in-flight completions
  (``docs/resilience.md``, "Overload policy & lifecycle");
- :mod:`serving.router` — the multi-replica front door
  (``docs/serving.md``, "Multi-replica routing"):
  :class:`~serving.router.RouterFleet` fronts N in-process replicas
  with one ``submit()/step()/drain()/stats()`` surface —
  least-pressure placement on the scheduler's ``pressure()`` signal,
  prefix AFFINITY via a router-side radix index (shared-prefix
  sessions land on the replica already holding their cached blocks,
  spilling under pressure), per-replica circuit breakers with
  exactly-once failover (queued work re-enqueues onto survivors
  bit-identically), rolling-restart ``drain_replica()``/``revive()``,
  and Router x TP composition (each replica on its own disjoint
  device mesh);
- disaggregated prefill/decode (``docs/serving.md``, "Disaggregated
  prefill/decode"): ``enable_disagg=True`` splits the server into
  phase-separated execution pools — a dedicated prefill pool (its own
  engine, KV pool, scheduler, and the prefix cache's home) runs every
  chunked prefill and hands finished KV blocks to a PURE-decode pool
  through the fixed-shape cross-pool block copy, so a 10x long-prompt
  burst queues against prefill capacity instead of inflating the
  decode inter-token tail; output is bit-exact vs the monolithic
  engine, and ``RouterFleet(disagg_prefill=k)`` extends the hand-off
  cross-replica (checksummed block payloads via
  ``DecodeEngine.export_blocks`` / ``InferenceServer.ingest_handoff``,
  torn transfers detected whole, failover back to monolithic
  placement);
- hierarchical KV offload (``docs/serving.md``, "Hierarchical KV
  offload"): ``enable_kv_offload=True`` (env twin
  ``APEX_TPU_KV_OFFLOAD``) backs the prefix cache with a bounded
  host-RAM tier and an optional checksummed disk spill tier
  (:class:`~serving.offload.OffloadStore`) — cold evictable blocks
  DEMOTE (``DecodeEngine.export_blocks``) instead of dying, and
  admission-time radix hits PROMOTE them back through the
  checksummed ``import_blocks`` path into fresh device blocks, so a
  cache hit spans device -> host -> disk at fixed HBM; every
  integrity/capacity failure on the offload path falls back to cold
  prefill bit-identically;
- :mod:`serving.transport` — the KV transport layer
  (``docs/serving.md``, "KV transport"): every cross-pool block
  movement above (disagg hand-off, elastic prefix warm, offload
  promote) rides a :class:`~serving.transport.KVTransport` backend —
  :class:`~serving.transport.InProcessTransport` (the direct copy,
  default, behavior-identical) or
  :class:`~serving.transport.SocketTransport` (crc-framed payloads
  over loopback TCP) — under one
  :class:`~serving.transport.TransportPolicy` robustness envelope:
  per-transfer deadline, bounded retry with decorrelated jitter,
  per-peer circuit breaker fast-failing into each consumer's existing
  degradation path, and exactly-once ingest via monotonic transfer
  ids + a bounded receiver dedup ledger.

Quick start::

    from apex_tpu.serving import InferenceServer
    server = InferenceServer(gpt_cfg, params, max_batch_size=8)
    completions = server.generate(prompts, max_new_tokens=64,
                                  eos_id=eos)

See ``docs/serving.md`` for cache-sizing math and the
bucket/recompile tradeoff; ``tools/serving_bench.py`` measures
continuous batching against naive one-request-at-a-time decoding.
"""

from apex_tpu.ops.sampling import SamplingParams
from apex_tpu.serving.api import InferenceServer, greedy_sample
from apex_tpu.serving.engine import DecodeEngine, default_prefill_buckets
from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    dequantize_kv,
    init_kv_cache,
    quantize_kv,
    resolve_cache_dtype,
    resolve_kv_quant,
)
from apex_tpu.serving.offload import OffloadStore, resolve_kv_offload
from apex_tpu.serving.overload import OverloadPolicy
from apex_tpu.serving.prefix_cache import PrefixCache
from apex_tpu.serving.router import (
    ReplicaRouter,
    RouterFleet,
    RouterPolicy,
    RouterRequest,
)
from apex_tpu.serving.scheduler import QueueFullError, Request, Scheduler
from apex_tpu.serving.speculation import DraftSource, NgramDraft
from apex_tpu.serving.transport import (
    InProcessTransport,
    KVTransport,
    SocketTransport,
    TransportError,
    TransportPolicy,
)

__all__ = [
    "BlockAllocator",
    "DecodeEngine",
    "DraftSource",
    "InProcessTransport",
    "InferenceServer",
    "KVCacheConfig",
    "KVTransport",
    "NgramDraft",
    "OffloadStore",
    "OverloadPolicy",
    "PrefixCache",
    "QueueFullError",
    "ReplicaRouter",
    "Request",
    "RouterFleet",
    "RouterPolicy",
    "RouterRequest",
    "SamplingParams",
    "Scheduler",
    "SocketTransport",
    "TransportError",
    "TransportPolicy",
    "default_prefill_buckets",
    "dequantize_kv",
    "greedy_sample",
    "init_kv_cache",
    "quantize_kv",
    "resolve_cache_dtype",
    "resolve_kv_offload",
    "resolve_kv_quant",
]
