"""Overload policy — who gets shed, and when, under serving pressure.

A bounded queue alone turns overload into a coin flip: whichever
request happens to arrive after the queue fills is rejected, however
important it is, while low-value work that arrived a moment earlier
keeps its slot.  Production schedulers in the continuous-batching
lineage (Orca's iteration-level admission, vLLM's priority-aware
preemption) treat overload as a *policy* decision instead: requests
carry a priority class and a cost estimate, and when pressure crosses
a threshold the system sheds the lowest-priority, newest work —
explicitly, with ``finish_reason="shed"`` — rather than blindly
bouncing the next arrival.

Vocabulary (used by :mod:`serving.scheduler` and ``serving.api``):

- **priority** (``Request.priority``): an integer class, nice-style —
  ``0`` is the default/foreground class, larger numbers are *lower*
  priority.  Anything at or above
  :attr:`OverloadPolicy.best_effort_priority` is *best-effort*:
  sheddable under pool pressure, first in line for displacement and
  preemption.
- **cost estimate** (``Request.cost_blocks``): the KV blocks the
  request will hold if it runs to completion —
  ``blocks_for(len(prompt) + max_new_tokens)`` — stamped at
  submission.  Queued demand is the sum of waiting costs; it feeds
  the pressure signal so a burst of expensive prompts registers as
  overload *before* the pool physically fills.
- **pressure** (:meth:`Scheduler.pressure`): the max of the queue
  fill fraction and ``(live blocks + queued demand) / usable
  blocks``.  May exceed 1.0 — demand is unbounded even though the
  pool is not.

Policy knobs, all with safe defaults (the layer is ON by default in
``InferenceServer``; ``overload_policy=None`` opts out):

- queue-full **displacement**: when the bounded queue is full, an
  arrival that outranks the worst queued request displaces it (the
  victim finishes ``"shed"``); an arrival that doesn't outrank anyone
  is rejected exactly as before (``"rejected"``), so equal-priority
  traffic behaves byte-for-byte like the pre-overload server.
- pressure **shedding**: each step, while pressure is at or above
  ``shed_threshold``, best-effort waiting work is shed worst-first
  (highest priority number, newest first).  Foreground (priority <
  ``best_effort_priority``) work is never pressure-shed.
- priority-aware **preemption**: the preemption victim is the worst
  (priority, then youngest-admitted) running request, so foreground
  work keeps its blocks while best-effort work recomputes.  With all
  priorities equal this degenerates to the historical
  youngest-first choice — preemption bit-stability tests are
  unaffected.

``docs/resilience.md`` ("Overload policy & lifecycle") has the full
shed / reject / breaker decision table.
"""

from __future__ import annotations

import dataclasses

__all__ = ["OverloadPolicy"]


@dataclasses.dataclass(frozen=True)
class OverloadPolicy:
    """Thresholds for the shed/displace/preempt decisions above.

    ``shed_threshold``: pressure (see module docstring) at or above
    which best-effort waiting work is shed each step.  ``1.0`` means
    "only when queued demand already exceeds what the pool could ever
    deliver promptly"; the 0.9 default sheds slightly before the
    cliff.  ``best_effort_priority``: the priority class at which
    work becomes sheddable (default 1: every non-default class).
    ``displace``: whether queue-full arrivals may displace
    lower-priority queued work."""

    shed_threshold: float = 0.9
    best_effort_priority: int = 1
    displace: bool = True

    def __post_init__(self):
        if self.shed_threshold <= 0:
            raise ValueError(
                f"shed_threshold must be > 0, got {self.shed_threshold}")
        if self.best_effort_priority < 1:
            raise ValueError(
                "best_effort_priority must be >= 1 (priority 0 is the "
                f"never-shed default class), got "
                f"{self.best_effort_priority}")

    def sheddable(self, priority: int) -> bool:
        return priority >= self.best_effort_priority

    @staticmethod
    def slo_debt_tokens(req) -> int:
        """The SLO debt one shed/displace decision incurs: the
        unearned remainder of the victim's token budget.  Stamped into
        flight-recorder shed annotations and accumulated by
        :class:`observability.slo.SLOTracker` — so "what did
        protecting the SLO cost" is a counter per priority class, not
        a guess (``docs/observability.md``, "SLO & goodput")."""
        return max(0, req.max_new_tokens - len(req.generated))
