"""Overload policy — who gets shed, and when, under serving pressure.

A bounded queue alone turns overload into a coin flip: whichever
request happens to arrive after the queue fills is rejected, however
important it is, while low-value work that arrived a moment earlier
keeps its slot.  Production schedulers in the continuous-batching
lineage (Orca's iteration-level admission, vLLM's priority-aware
preemption) treat overload as a *policy* decision instead: requests
carry a priority class and a cost estimate, and when pressure crosses
a threshold the system sheds the lowest-priority, newest work —
explicitly, with ``finish_reason="shed"`` — rather than blindly
bouncing the next arrival.

Vocabulary (used by :mod:`serving.scheduler` and ``serving.api``):

- **priority** (``Request.priority``): an integer class, nice-style —
  ``0`` is the default/foreground class, larger numbers are *lower*
  priority.  Anything at or above
  :attr:`OverloadPolicy.best_effort_priority` is *best-effort*:
  sheddable under pool pressure, first in line for displacement and
  preemption.
- **cost estimate** (``Request.cost_blocks``): the KV blocks the
  request will hold if it runs to completion —
  ``blocks_for(len(prompt) + max_new_tokens)`` — stamped at
  submission.  Queued demand is the sum of waiting costs; it feeds
  the pressure signal so a burst of expensive prompts registers as
  overload *before* the pool physically fills.
- **pressure** (:meth:`Scheduler.pressure`): the max of the queue
  fill fraction and ``(live blocks + queued demand) / usable
  blocks``.  May exceed 1.0 — demand is unbounded even though the
  pool is not.

Policy knobs, all with safe defaults (the layer is ON by default in
``InferenceServer``; ``overload_policy=None`` opts out):

- queue-full **displacement**: when the bounded queue is full, an
  arrival that outranks the worst queued request displaces it (the
  victim finishes ``"shed"``); an arrival that doesn't outrank anyone
  is rejected exactly as before (``"rejected"``), so equal-priority
  traffic behaves byte-for-byte like the pre-overload server.
- pressure **shedding**: each step, while pressure is at or above
  ``shed_threshold``, best-effort waiting work is shed worst-first
  (highest priority number, newest first).  Foreground (priority <
  ``best_effort_priority``) work is never pressure-shed.
- priority-aware **preemption**: the preemption victim is the worst
  (priority, then youngest-admitted) running request, so foreground
  work keeps its blocks while best-effort work recomputes.  With all
  priorities equal this degenerates to the historical
  youngest-first choice — preemption bit-stability tests are
  unaffected.
- predictive **admission** (``predictive_admission=True``, OFF by
  default): :class:`AdmissionEstimator` learns per-priority service
  rates from finished requests' timelines and sheds a
  wall-deadlined arrival at SUBMIT time when even the
  fastest-ever-observed service for its class provably cannot beat
  its ``deadline_s`` — the prefill such a request would burn is pure
  waste, it times out regardless.  The bound is deliberately
  one-sided (fastest observed TTFT/decode, never the mean) and armed
  only after ``admission_min_history`` observations per class, so an
  empty-history server behaves byte-identically to today.

``docs/resilience.md`` ("Overload policy & lifecycle") has the full
shed / reject / breaker decision table.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

__all__ = ["OverloadPolicy", "AdmissionEstimator"]


@dataclasses.dataclass(frozen=True)
class OverloadPolicy:
    """Thresholds for the shed/displace/preempt decisions above.

    ``shed_threshold``: pressure (see module docstring) at or above
    which best-effort waiting work is shed each step.  ``1.0`` means
    "only when queued demand already exceeds what the pool could ever
    deliver promptly"; the 0.9 default sheds slightly before the
    cliff.  ``best_effort_priority``: the priority class at which
    work becomes sheddable (default 1: every non-default class).
    ``displace``: whether queue-full arrivals may displace
    lower-priority queued work.

    ``predictive_admission``: arm submit-time shedding of provably
    deadline-doomed work (module docstring; OFF by default — the
    cold-start path is byte-identical to a server without it).
    ``admission_min_history``: finished-request observations a
    priority class needs before its estimate is trusted.
    ``admission_margin``: multiplier on the fastest-observed service
    time before comparing against the deadline; ``1.0`` (default)
    sheds only what the best case cannot save, larger values shed
    earlier."""

    shed_threshold: float = 0.9
    best_effort_priority: int = 1
    displace: bool = True
    predictive_admission: bool = False
    admission_min_history: int = 8
    admission_margin: float = 1.0

    def __post_init__(self):
        if self.shed_threshold <= 0:
            raise ValueError(
                f"shed_threshold must be > 0, got {self.shed_threshold}")
        if self.best_effort_priority < 1:
            raise ValueError(
                "best_effort_priority must be >= 1 (priority 0 is the "
                f"never-shed default class), got "
                f"{self.best_effort_priority}")
        if self.admission_min_history < 1:
            raise ValueError(
                f"admission_min_history must be >= 1, got "
                f"{self.admission_min_history}")
        if self.admission_margin < 1.0:
            raise ValueError(
                "admission_margin must be >= 1.0 (below the "
                "fastest-observed bound the shed is no longer "
                f"provable), got {self.admission_margin}")

    def sheddable(self, priority: int) -> bool:
        return priority >= self.best_effort_priority

    @staticmethod
    def slo_debt_tokens(req) -> int:
        """The SLO debt one shed/displace decision incurs: the
        unearned remainder of the victim's token budget.  Stamped into
        flight-recorder shed annotations and accumulated by
        :class:`observability.slo.SLOTracker` — so "what did
        protecting the SLO cost" is a counter per priority class, not
        a guess (``docs/observability.md``, "SLO & goodput")."""
        return max(0, req.max_new_tokens - len(req.generated))


class _ClassTrack:
    """Fastest-observed service profile for one priority class."""

    __slots__ = ("observed", "min_ttft_s", "min_decode_token_s")

    def __init__(self):
        self.observed = 0
        self.min_ttft_s: Optional[float] = None
        self.min_decode_token_s: Optional[float] = None


class AdmissionEstimator:
    """Per-priority service-rate learner behind predictive admission.

    Feeds on finished requests' :meth:`Request.timeline` (only ones
    that actually produced a first token — front-door rejections and
    queue-only timeouts carry no service evidence) and keeps, per
    priority class, the FASTEST observed submit-to-first-token and
    per-token decode times.  :meth:`doomed` then answers one
    question: can this arrival's ``deadline_s`` be met even if the
    server serves it as fast as it has EVER served that class?  "No"
    is a proof, not a prediction — the minimum over history is a
    lower bound on service time — so shedding on it never discards a
    request the live server could have saved.  Two conservative
    guards keep false sheds out:

    - with ``eos_id`` set (or fewer than ``min_history``
      observations) only the first-token bound applies — the model
      may stop after one token, so the full-budget bound is not a
      proof;
    - without a wall deadline nothing is ever predicted.
    """

    def __init__(self, *, min_history: int = 8, margin: float = 1.0):
        self.min_history = int(min_history)
        self.margin = float(margin)
        self._tracks: Dict[int, _ClassTrack] = {}
        self.predicted_sheds = 0

    def observe(self, req) -> None:
        """Fold one finished request's timeline into its class."""
        tl = req.timeline()
        ttft = tl.get("ttft_s")
        if ttft is None:
            return
        tr = self._tracks.get(req.priority)
        if tr is None:
            tr = self._tracks[req.priority] = _ClassTrack()
        tr.observed += 1
        if tr.min_ttft_s is None or ttft < tr.min_ttft_s:
            tr.min_ttft_s = ttft
        dec = tl.get("decode_token_s")
        if dec is not None and (tr.min_decode_token_s is None
                                or dec < tr.min_decode_token_s):
            tr.min_decode_token_s = dec

    def doomed(self, req) -> bool:
        """True iff ``req`` provably cannot meet its wall deadline."""
        if req.deadline_s is None:
            return False
        tr = self._tracks.get(req.priority)
        if tr is None or tr.observed < self.min_history \
                or tr.min_ttft_s is None:
            return False    # cold start: admit exactly as today
        best = tr.min_ttft_s
        if req.eos_id is None and req.max_new_tokens > 1 \
                and tr.min_decode_token_s is not None:
            # no early stop possible: the full token budget must land
            best = best + (req.max_new_tokens - 1) \
                * tr.min_decode_token_s
        if best * self.margin > req.deadline_s:
            self.predicted_sheds += 1
            return True
        return False

    def as_stats(self) -> dict:
        """The ``stats()["admission"]`` block (JSON-safe)."""
        return {
            "enabled": True,
            "min_history": self.min_history,
            "margin": self.margin,
            "predicted_sheds": self.predicted_sheds,
            "by_priority": {
                p: {"observed": tr.observed,
                    "min_ttft_s": tr.min_ttft_s,
                    "min_decode_token_s": tr.min_decode_token_s}
                for p, tr in sorted(self._tracks.items())},
        }
