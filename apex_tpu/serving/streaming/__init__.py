"""apex_tpu.serving.streaming — per-token delivery (docs/serving.md,
"Streaming & cancellation").

The server retires each pipelined ``(B,)`` transfer host-side and
applies tokens request-by-request; :class:`StreamBroker` fans that
same retire edge out to per-request bounded queues, giving three
consumer surfaces over one contract (delivered tokens are always a
byte-identical prefix of the non-streaming ``Request.output``):

- iterator: ``for tok in server.stream(uid): ...`` — blocking, with
  non-blocking ``drain()`` / bounded ``take(timeout=)`` underneath;
- callback: ``server.stream(uid, callback=fn)`` — ``fn("token", t)``
  per token at retire time plus one ``fn("end", finish_reason)``;
- SSE over HTTP: the ops plane's ``POST /generate`` +
  ``GET /stream/<uid>`` front door (:mod:`observability.opsplane`),
  where a broken client socket cancels the request mid-decode
  (``finish_reason="cancelled"``).

Backpressure contract: queues are bounded (``stream_queue_tokens``);
a slow consumer drops the OLDEST queued notification instead of ever
stalling ``step()``, and the stream backfills the dropped range from
the request's own token list on the next read — so delivery stays
byte-identical and only the broker's ``backpressure_drops`` counter
records the lag.
"""

from apex_tpu.serving.streaming.broker import StreamBroker, TokenStream

__all__ = ["StreamBroker", "TokenStream"]
