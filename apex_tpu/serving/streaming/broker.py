"""StreamBroker — retire-time token fan-out to bounded per-request
queues (package docstring; docs/serving.md, "Streaming & cancellation").

Threading model: ``publish()``/``finish()`` run on the serve thread
(inside ``step()``, at the point each retired token is applied);
``open()``/``drain()``/``take()``/``close()`` run on consumer threads
(SSE handlers, client iterators).  Everything serializes through ONE
``RLock`` (``broker.lock``) with a condition variable for blocking
readers — never the ops lock, so a blocked consumer can never hold up
the step loop, and the step loop's publish is a bounded O(1) append.

Delivery indices, not just tokens, ride the queue: ``publish`` dedups
``index < already-published`` (the failover re-enqueue case — a moved
request regenerates its prefix bit-identically, and the fleet pump
republishes it), and a reader seeing ``index > delivered`` backfills
the gap straight from the request's own ``generated`` list (the
backpressure-drop case).  Both rules together give the acceptance
invariant: the delivered stream is always a byte-identical prefix of
the non-streaming output, bounded queue or not.
"""

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class TokenStream:
    """One request's delivery surface.  Created via
    :meth:`StreamBroker.open`; shares the broker's lock/condition.

    ``source`` duck-types a live request: ``.generated`` (the
    append-only token list — the backfill authority), ``.finished``,
    and ``.finish_reason``.  Both :class:`~serving.scheduler.Request`
    and :class:`~serving.router.RouterRequest` qualify, so the same
    stream object serves single-server and fleet consumers.
    """

    def __init__(self, broker: "StreamBroker", key: int, source: Any,
                 callback: Optional[Callable[[str, Any], None]] = None):
        self.key = key
        self.lock = broker.lock          # shared: one lock, one cond
        self._broker = broker
        self._cond = broker._cond
        self._source = source
        self._callback = callback
        self._q: deque = deque()         # (index, token), bounded
        self._delivered = 0              # tokens handed to the consumer
        self._published = 0              # high-water publish index + 1
        self._terminal: Optional[str] = None   # published, undelivered
        self.finish_reason: Optional[str] = None  # delivered terminal
        self.drops = 0                   # this stream's overflow count
        self.closed = False

    # -- consumer surface (foreign threads; every path locks) ---------------

    @property
    def done(self) -> bool:
        """True once the terminal event has been delivered — at that
        point every token has been too (terminal delivery backfills)."""
        return self.finish_reason is not None

    def drain(self) -> List[int]:
        """Non-blocking: every token available now, in order (queued +
        gap backfill), absorbing the terminal if published."""
        with self.lock:
            return self._drain_locked()

    def take(self, timeout: Optional[float] = None) -> List[int]:
        """Block until at least one token or the terminal is
        deliverable (or ``timeout`` elapses); returns possibly-empty
        list — check :attr:`finish_reason` / :attr:`done` after."""
        with self.lock:
            got = self._drain_locked()
            if got or self.done:
                return got
            self._cond.wait(timeout)
            return self._drain_locked()

    def __iter__(self):
        """Yield tokens until the terminal event; blocking (bounded
        per-wait by the broker's ``iter_wait_s`` so an abandoned
        producer can't hang a consumer forever)."""
        while True:
            toks = self.take(timeout=self._broker.iter_wait_s)
            for tok in toks:
                yield tok
            if self.done:
                return

    def close(self) -> None:
        """Detach the consumer: the broker stops publishing to this
        stream and forgets it.  Idempotent; does NOT cancel the
        request (the server owns cancellation)."""
        with self.lock:
            self.closed = True
            self._broker._forget(self.key, self)

    # -- internals (broker lock held) ---------------------------------------

    def _tokens(self):
        return self._source.generated

    def _drain_locked(self) -> List[int]:
        out: List[int] = []
        while self._q:
            idx, tok = self._q.popleft()
            if idx < self._delivered:
                continue                  # duplicate (failover replay)
            if idx > self._delivered:     # backpressure gap: backfill
                gen = self._tokens()
                out.extend(gen[self._delivered:idx])
                self._delivered = idx
            out.append(tok)
            self._delivered += 1
        if self._terminal is not None and self.finish_reason is None:
            gen = self._tokens()          # late-open / post-drop tail
            if self._delivered < len(gen):
                out.extend(gen[self._delivered:])
                self._delivered = len(gen)
            self.finish_reason = self._terminal
            self._broker._forget(self.key, self)
        return out

    def _deliver_callback(self) -> None:
        """Push everything deliverable through the callback (serve
        thread, broker lock held): callback streams bypass the bounded
        queue entirely, so they never drop."""
        for tok in self._drain_locked():
            self._callback("token", tok)
        if self.finish_reason is not None:
            self._callback("end", self.finish_reason)


class StreamBroker:
    """Fan retired tokens out to per-request :class:`TokenStream`\\ s.

    ``publish``/``finish`` are no-ops for keys nobody opened — the
    broker costs nothing for non-streamed traffic — and a stream
    opened late backfills from the request itself, so open-time never
    races token delivery.
    """

    def __init__(self, *, queue_tokens: int = 256,
                 iter_wait_s: float = 60.0):
        if queue_tokens < 1:
            raise ValueError("queue_tokens must be >= 1")
        self.lock = threading.RLock()
        self._cond = threading.Condition(self.lock)
        self.queue_tokens = queue_tokens
        self.iter_wait_s = iter_wait_s
        self._streams: Dict[int, TokenStream] = {}
        self.opened = 0                  # streams ever opened
        self.published_tokens = 0        # tokens fanned out
        self.backpressure_drops = 0      # oldest-dropped notifications
        self.finished = 0                # terminal events published

    # -- consumer side -------------------------------------------------------

    def open(self, key: int, source: Any,
             callback: Optional[Callable[[str, Any], None]] = None
             ) -> TokenStream:
        """The stream for ``key``, creating it bound to ``source`` (a
        live request — see :class:`TokenStream`).  Re-opening an
        active key returns the existing stream (one consumer cursor
        per request)."""
        with self.lock:
            s = self._streams.get(key)
            if s is None:
                s = TokenStream(self, key, source, callback)
                self._streams[key] = s
                self.opened += 1
                if source.finished:      # already terminal at open
                    s._terminal = source.finish_reason
                if callback is not None:
                    s._deliver_callback()
            return s

    # -- producer side (serve thread, at token-retire time) ------------------

    def publish(self, key: int, index: int, token: int) -> None:
        """Fan one applied token out; O(1), never blocks on the
        consumer.  ``index`` is the token's position in the request's
        stream — re-published prefixes (failover replay) dedup here."""
        with self.lock:
            s = self._streams.get(key)
            if s is None or s.closed:
                return
            if index < s._published:
                return                   # already fanned out: dedup
            s._published = index + 1
            self.published_tokens += 1
            if s._callback is not None:
                s._q.append((index, token))
                s._deliver_callback()
            else:
                if len(s._q) >= self.queue_tokens:
                    s._q.popleft()       # slow consumer: drop oldest,
                    s.drops += 1         # reader backfills the gap
                    self.backpressure_drops += 1
                s._q.append((index, token))
            self._cond.notify_all()

    def finish(self, key: int, reason: str) -> None:
        """Publish the terminal event (``finish_reason``); delivery
        backfills any tokens the queue never carried."""
        with self.lock:
            s = self._streams.get(key)
            if s is None or s.closed:
                return
            if s._terminal is None:
                s._terminal = reason
                self.finished += 1
            if s._callback is not None:
                s._deliver_callback()
            self._cond.notify_all()

    # -- introspection -------------------------------------------------------

    @property
    def active(self) -> int:
        """Streams opened and not yet fully delivered/closed — the
        ``/healthz`` ``active_streams`` gauge."""
        with self.lock:
            return len(self._streams)

    def stats(self) -> dict:
        """The pinned ``stats()["streams"]`` sub-block."""
        with self.lock:
            return {
                "active": len(self._streams),
                "opened": self.opened,
                "published_tokens": self.published_tokens,
                "backpressure_drops": self.backpressure_drops,
                "finished": self.finished,
                "queue_tokens": self.queue_tokens,
            }

    def snapshot(self, limit: int = 64) -> List[dict]:
        """Per-stream rows for ``ops_probe --streams`` (open streams
        only; delivery cursors read under the broker lock)."""
        with self.lock:
            rows = []
            for key, s in list(self._streams.items())[:limit]:
                rows.append({
                    "key": key,
                    "delivered": s._delivered,
                    "queued": len(s._q),
                    "drops": s.drops,
                    "terminal": s._terminal,
                })
            return rows

    # -- internal ------------------------------------------------------------

    def _forget(self, key: int, stream: TokenStream) -> None:
        # lock held by caller (close/_drain_locked); keep the dict
        # bounded: consumed/closed streams leave the broker but stay
        # readable by their holder
        if self._streams.get(key) is stream:
            del self._streams[key]
