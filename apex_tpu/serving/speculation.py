"""Draft proposers for speculative decoding (``docs/serving.md``).

Speculative decoding splits one decode iteration into *draft* (guess
the next few tokens cheaply) and *verify* (score every guess in one
multi-token engine step — ``DecodeEngine.verify``, built on
``ops.chunk_cached_attention``).  Greedy acceptance keeps the output
bit-identical to plain one-token decode by construction: the accepted
tokens are exactly the drafts that MATCH the model's own argmax at
their position, followed by the model's own next token — so a wrong
draft costs one wasted verify column, never a wrong output token.

This module is the draft half.  The default proposer is zero-weight
**prompt-lookup / n-gram drafting** (Saxena's prompt-lookup decoding;
the LLMA observation): generation frequently copies spans that already
occurred in the request's own context — few-shot templates, quoted
retrieval passages, code identifiers, and the self-generated suffix of
any repetitive completion — so the best free guess for "what follows
the current suffix" is "what followed it last time it appeared".  No
extra weights, no extra compiled programs, no second model to keep in
HBM.

:class:`DraftSource` is the pluggable interface: a small-model drafter
(the classic Leviathan et al. setup) is a subclass whose
:meth:`~DraftSource.propose` greedily decodes ``k`` tokens from its
own cheap model — the verify/acceptance machinery upstream is
identical and stays bit-exact regardless of where drafts come from,
because acceptance only ever compares drafts against the target
model's own argmax.

Stochastic requests (``docs/serving.md``, "Stochastic sampling")
use the SAME drafts and the same acceptance comparison, but against
each verify column's counter-keyed SAMPLE instead of its argmax —
rejection sampling with the proposer's tokens as a delta ``q``
(accept prob ``p(draft)``, residual resample on first rejection),
realized via the Gumbel-max coupling so the emitted stream is
byte-identical with speculation on or off.  Draft determinism (the
contract below) matters doubly there: the chaos soak replays
per-step accounting, and drafts must be pure functions of history.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

__all__ = ["DraftSource", "NgramDraft"]


class DraftSource:
    """Interface for draft proposers.

    ``propose(tokens, k)`` receives the request's full token history
    (prompt + everything generated so far, INCLUDING the pending token
    whose K/V the next engine step will write) and returns up to ``k``
    guesses for the tokens that follow.  Returning ``[]`` means "no
    guess" — the request decodes one token normally that iteration.

    Contract notes for implementers:

    - drafts are *hints*, never outputs: a wrong draft is rejected by
      verify and costs only wasted compute, so proposers may guess
      aggressively;
    - ``propose`` runs on the host inside the serve loop, once per
      decoding request per iteration — it must be cheap relative to a
      device step;
    - determinism matters: a request replayed with the same history
      must get the same drafts, or OOM-retry and the chaos soak's
      bit-exact replay would wobble (outputs stay bit-exact either
      way, but per-step accounting would not reproduce).
    """

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError

    def reset(self) -> None:
        """Drop any cross-request state (stateless by default)."""


class NgramDraft(DraftSource):
    """Zero-weight prompt-lookup drafts from the request's own history.

    For ``n`` from ``max_ngram`` down to ``min_ngram``: take the last
    ``n`` tokens of the history, find the most recent EARLIER
    occurrence of that n-gram, and propose the ``k`` tokens that
    followed it.  Longer n-grams are tried first (a longer matched
    context is a stronger predictor); the most recent occurrence wins
    because generation drifts — what followed the suffix lately beats
    what followed it long ago.

    ``history_window`` bounds the scan (the last N tokens of history);
    the proposer is O(window * max_ngram) per call, so the default
    keeps drafting cost trivially small next to a device step even for
    long-context requests.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 history_window: Optional[int] = 512):
        if max_ngram < min_ngram or min_ngram < 1:
            raise ValueError(
                f"need max_ngram >= min_ngram >= 1; got "
                f"max_ngram={max_ngram} min_ngram={min_ngram}")
        if history_window is not None and history_window < 2:
            raise ValueError(
                f"history_window must be >= 2, got {history_window}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.history_window = history_window

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        hist = list(tokens)
        if self.history_window is not None \
                and len(hist) > self.history_window:
            hist = hist[len(hist) - self.history_window:]
        out: List[int] = []
        # extend one token at a time, appending each guess to the
        # working history — a single match only ever pins down the next
        # token, and re-matching the EXTENDED suffix extrapolates
        # periodic tails (the common repetitive-completion shape) to a
        # full k-token draft instead of stopping at the history's edge
        for _ in range(max(0, k)):
            nxt = self._lookup_next(hist)
            if nxt is None:
                break
            out.append(nxt)
            hist.append(nxt)
        return out

    def _lookup_next(self, hist: List[int]) -> Optional[int]:
        """The token that followed the most recent earlier occurrence
        of the longest matching suffix n-gram (None = no occurrence of
        any n-gram down to ``min_ngram``)."""
        n_hist = len(hist)
        for n in range(min(self.max_ngram, n_hist - 1),
                       self.min_ngram - 1, -1):
            suffix = tuple(hist[n_hist - n:])
            # most recent occurrence strictly before the suffix itself
            for i in range(n_hist - n - 1, -1, -1):
                if tuple(hist[i:i + n]) == suffix:
                    return int(hist[i + n])
        return None
