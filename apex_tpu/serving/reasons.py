"""Finish-reason vocabulary — the single source of truth.

Every terminal ``Request.finish_reason`` the stack can assign lives
here as a named constant, together with the frozensets the chaos
invariants gate on.  Scheduler, server, router, and the resilience
layer all import from this module, so a new reason is a one-line
change that the exhaustiveness test (``tests/L0/test_reasons.py``)
and the soak's exactly-one-terminal invariant pick up automatically —
a literal typo'd at an assignment site can no longer silently open a
reason the invariants don't know about.

This module imports NOTHING (stdlib included): it must be importable
from :mod:`apex_tpu.resilience.chaos` while ``apex_tpu.serving``'s
package ``__init__`` is still mid-import (chaos is reachable from the
resilience package ``__init__``, which ``serving.api`` pulls in via
the breaker), so it can carry no imports that re-enter either
package.
"""

# healthy terminals — the request ran to its natural end
EOS = "eos"                      # sampled the eos id
LENGTH = "length"                # hit max_new_tokens

# server-side failure terminals
CAPACITY = "capacity"            # could never fit the KV pool
TIMEOUT = "timeout"              # deadline expired
NONFINITE = "nonfinite"          # non-finite logits isolated
REJECTED = "rejected"            # invalid at submit (bad prompt/params)
SHED = "shed"                    # overload policy dropped it
BREAKER_OPEN = "breaker_open"    # circuit breaker refused submit
DRAINING = "draining"            # submitted into a draining server
CANCELLED = "cancelled"          # client disconnected / cancel(uid)
HANDOFF = "handoff"              # exported to another replica's pool

# router-level terminals
REPLICA_FAILED = "replica_failed"  # replica died mid-stream

#: reasons that end a request without anything having gone wrong
HEALTHY_REASONS = frozenset({EOS, LENGTH})

#: every terminal a single server can assign (the soak's
#: exactly-one-terminal invariant gates membership)
TERMINAL_REASONS = HEALTHY_REASONS | frozenset({
    CAPACITY, TIMEOUT, NONFINITE, REJECTED, SHED, BREAKER_OPEN,
    DRAINING, CANCELLED,
})

#: the router soak's superset: replica failover and cross-replica
#: hand-off add their own terminals
ROUTER_TERMINAL_REASONS = TERMINAL_REASONS | frozenset({
    REPLICA_FAILED, HANDOFF,
})

#: the full vocabulary (what the exhaustiveness test scans source for)
ALL_REASONS = ROUTER_TERMINAL_REASONS
