"""Hierarchical KV offload — host-RAM/disk tiers behind the prefix cache.

The prefix cache (``serving/prefix_cache``) only lives in device pool
bytes, so HBM capacity — not compute — caps how many conversations
stay warm: at million-session scale a returning user almost always
cold-prefills.  This module adds the missing tiers.  An
:class:`OffloadStore` holds exported KV block payloads
(:meth:`DecodeEngine.export_blocks` dicts — host numpy leaves plus
per-leaf crc32s) in a bounded host-RAM LRU, spilling the coldest
entries to an optional disk tier; the prefix cache **demotes** a cold
evictable block into the store at the moment eviction would have
destroyed it, and **promotes** it back through the checksummed
``import_blocks`` path into a fresh device block when a later
admission's radix walk wants it — a cache hit now spans three tiers
(device -> host -> disk) at fixed HBM.

Keys are the radix index's chain hashes (``blake2b`` over
``parent_hash + chunk tokens``): a pure function of token CONTENT, so
they survive block-id reuse, allocator resets, and — for the disk
tier — process restarts.  Payload integrity is defended twice: the
disk tier writes a per-leaf checksum manifest and verifies it on
load (a torn or bit-rotted spill is deleted whole and reads as a
miss), and ``import_blocks`` re-verifies the export-time crc32s
against the bytes it is about to scatter into the pool (a corrupt
host payload is rejected whole).  Either failure falls back to cold
prefill — bit-identical output, just slower — so the offload tier can
NEVER corrupt generation, only decline to accelerate it.

Disk writes follow the ``CheckpointManager`` atomic-publish pattern:
every entry is staged under a ``.tmp-`` sibling, fsynced, and
``os.rename``d into place — a crash mid-spill leaves a stale temp
directory (swept at startup), never a half-readable entry.
"""

from __future__ import annotations

import json
import os
import shutil
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.utils.checkpoint import leaf_checksum
from apex_tpu.utils.meters import CounterMeter

__all__ = ["KV_OFFLOAD_ENV", "OffloadStore", "resolve_kv_offload"]

# fleet-wide enable twin of the ``enable_kv_offload=`` kwarg
# (precedent: APEX_TPU_KV_QUANT) — a provided kwarg wins; the env
# only fills in a None ("not provided") kwarg
KV_OFFLOAD_ENV = "APEX_TPU_KV_OFFLOAD"

MANIFEST_FILE = "manifest.json"
_TMP_PREFIX = ".tmp-"


def resolve_kv_offload(value) -> bool:
    """Normalize an ``enable_kv_offload`` kwarg/env value to a bool.

    ``None``, ``""``, ``"0"``, ``"off"``, ``"none"``, ``"false"`` and
    ``"no"`` disable; ``"1"``, ``"on"``, ``"true"`` and ``"yes"``
    enable; anything else raises (a typo'd env var must not silently
    run the fleet without its offload tier)."""
    if value is None:
        return False
    if isinstance(value, bool):
        return value
    v = str(value).strip().lower()
    if v in ("", "0", "off", "none", "false", "no"):
        return False
    if v in ("1", "on", "true", "yes"):
        return True
    raise ValueError(
        f"unknown KV offload mode {value!r} (from kwarg or "
        f"{KV_OFFLOAD_ENV}): use '1'/'on' or '0'/'off'")


def payload_nbytes(payload: dict) -> int:
    """Host bytes one exported payload occupies (every cache leaf)."""
    return sum(int(np.asarray(a).nbytes)
               for a in payload["leaves"].values())


def verify_payload(payload: dict) -> None:
    """Host-side integrity check of one exported payload against its
    RECORDED per-leaf crc32s — the same test ``import_blocks`` runs,
    hoisted out so the promote walk can reject a torn payload before
    any device or radix state moves.  Raises :class:`ValueError`
    naming the first rotten leaf."""
    import zlib

    for name, arr in payload["leaves"].items():
        got = zlib.crc32(np.ascontiguousarray(arr).tobytes())
        want = payload["crc"].get(name)
        if got != want:
            raise ValueError(
                f"torn offload payload: leaf {name!r} has checksum "
                f"{got} (actual) != {want} (expected); payload "
                f"rejected whole")


def merge_payloads(payloads: List[dict]) -> dict:
    """Concatenate per-block exported payloads into one multi-block
    payload for a single batched ``import_blocks`` launch.  The
    merged crcs are RECOMPUTED from the concatenated bytes — callers
    must have verified each input against its stored checksums first
    (:func:`verify_payload`); this merge is dispatch economy, not an
    integrity step."""
    import zlib

    if len(payloads) == 1:
        return payloads[0]
    leaves = {name: np.concatenate(
        [p["leaves"][name] for p in payloads], axis=1)
        for name in payloads[0]["leaves"]}
    return {
        "num_blocks": sum(p["num_blocks"] for p in payloads),
        "block_size": payloads[0]["block_size"],
        "leaves": leaves,
        "crc": {name: zlib.crc32(np.ascontiguousarray(a).tobytes())
                for name, a in leaves.items()},
    }


def split_payload(payload: dict) -> List[dict]:
    """Slice one batched :meth:`DecodeEngine.export_blocks` payload
    into per-block payloads — the demote path's dual of
    :func:`merge_payloads`: eviction gathers a whole victim batch off
    the device in ONE launch, then stores each block under its own
    content hash.  Each slice carries the crc the ENGINE recorded for
    that block at export time (``block_crc``), so per-block integrity
    survives the batching; a payload without ``block_crc`` (not
    engine-built) falls back to checksumming the slice here."""
    import zlib

    n = payload["num_blocks"]
    if n == 1:
        return [payload]
    bs = payload["block_size"]
    bc = payload.get("block_crc")
    out = []
    for i in range(n):
        leaves = {name: np.ascontiguousarray(
            arr[:, i * bs:(i + 1) * bs])
            for name, arr in payload["leaves"].items()}
        out.append({
            "num_blocks": 1,
            "block_size": bs,
            "leaves": leaves,
            "crc": ({name: bc[name][i] for name in leaves}
                    if bc is not None else
                    {name: zlib.crc32(a.tobytes())
                     for name, a in leaves.items()}),
        })
    return out


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class OffloadStore:
    """Bounded host-RAM tier + optional disk spill tier for exported
    KV block payloads, keyed by the prefix cache's chain hashes.

    - :meth:`put` inserts at the hot end of the host LRU; when the
      tier exceeds ``host_bytes`` the coldest entries spill to
      ``spill_dir`` (atomic write-tmp -> rename, per-leaf checksum
      manifest) or, with no disk tier, are dropped and counted.
    - :meth:`take` pops an entry (host first, then disk) — tiers are
      exclusive, so a promoted payload leaves the store entirely; a
      disk entry failing manifest verification is deleted whole and
      reads as a miss (``disk_torn``).
    - keys are content hashes, so surviving disk entries are adopted
      on construction (a restarted server keeps its cold tier).

    ``counters`` (normally the server's ``serving_offload`` meter)
    accumulates ``spills`` / ``host_dropped`` / ``disk_torn``; the
    demote/promote counts live with the prefix cache, which drives
    this store.
    """

    def __init__(self, host_bytes: int = 64 << 20,
                 spill_dir: Optional[str] = None,
                 counters: Optional[CounterMeter] = None):
        if int(host_bytes) < 0:
            raise ValueError(
                f"host_bytes must be >= 0, got {host_bytes}")
        self.host_bytes = int(host_bytes)
        self.spill_dir = spill_dir
        self.counters = (counters if counters is not None
                         else CounterMeter())
        self._host: "OrderedDict[bytes, dict]" = OrderedDict()
        self._host_used = 0
        self._disk: Dict[bytes, None] = {}
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
            for name in sorted(os.listdir(spill_dir)):
                path = os.path.join(spill_dir, name)
                if name.startswith(_TMP_PREFIX):
                    # a crash mid-spill: never renamed, never valid
                    shutil.rmtree(path, ignore_errors=True)
                    continue
                try:
                    key = bytes.fromhex(name)
                except ValueError:
                    continue    # foreign file; not ours to manage
                if os.path.isfile(os.path.join(path, MANIFEST_FILE)):
                    self._disk[key] = None

    # -- introspection ----------------------------------------------------

    @property
    def host_entries(self) -> int:
        return len(self._host)

    @property
    def host_used_bytes(self) -> int:
        return self._host_used

    @property
    def disk_entries(self) -> int:
        return len(self._disk)

    def __contains__(self, key: bytes) -> bool:
        return key in self._host or key in self._disk

    def __len__(self) -> int:
        return len(self._host) + len(self._disk)

    def stats(self) -> dict:
        return {
            "host_entries": len(self._host),
            "host_bytes": self._host_used,
            "host_bytes_cap": self.host_bytes,
            "disk_entries": len(self._disk),
            "spill_dir": self.spill_dir,
        }

    # -- the store --------------------------------------------------------

    def put(self, key: bytes, payload: dict) -> None:
        """Insert ``payload`` at the hot end of the host tier,
        spilling (or dropping) the coldest entries past the byte
        bound.  Content-addressed: re-putting a present key only
        refreshes its recency."""
        if key in self._host:
            self._host.move_to_end(key)
            return
        if key in self._disk:
            return
        nbytes = payload_nbytes(payload)
        if nbytes > self.host_bytes:
            # would never fit the host tier: straight to disk (or
            # dropped — an oversized payload must not wedge the LRU)
            if not self._spill(key, payload):
                self.counters.incr("host_dropped")
            return
        self._host[key] = payload
        self._host_used += nbytes
        while self._host_used > self.host_bytes and self._host:
            vkey, victim = self._host.popitem(last=False)
            self._host_used -= payload_nbytes(victim)
            if not self._spill(vkey, victim):
                self.counters.incr("host_dropped")

    def take(self, key: bytes) -> Optional[Tuple[dict, str]]:
        """Pop ``key``'s payload and the tier it came from (``"host"``
        / ``"disk"``), or None on miss.  A disk entry that fails its
        manifest verification is deleted and reads as a miss."""
        payload = self._host.pop(key, None)
        if payload is not None:
            self._host_used -= payload_nbytes(payload)
            return payload, "host"
        if key in self._disk:
            payload = self._load(key)
            if payload is not None:
                return payload, "disk"
        return None

    def clear(self) -> None:
        """Drop the host tier (disk entries stay — content-addressed,
        they remain valid across allocator resets)."""
        self._host.clear()
        self._host_used = 0

    # -- disk tier --------------------------------------------------------

    def _spill(self, key: bytes, payload: dict) -> bool:
        """Atomically publish ``payload`` as ``spill_dir/<key.hex()>/``
        (write-tmp -> fsync -> rename, per the CheckpointManager
        pattern) with a per-leaf checksum manifest.  False = no disk
        tier configured (the caller counts the drop)."""
        if self.spill_dir is None:
            return False
        hexkey = key.hex()
        final = os.path.join(self.spill_dir, hexkey)
        if key in self._disk and os.path.isdir(final):
            return True         # content-addressed: already published
        tmp = os.path.join(self.spill_dir, _TMP_PREFIX + hexkey)
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        manifest = {
            "num_blocks": int(payload["num_blocks"]),
            "block_size": int(payload["block_size"]),
            "crc": {name: int(c)
                    for name, c in payload["crc"].items()},
            "leaves": {},
        }
        for i, name in enumerate(sorted(payload["leaves"])):
            arr = np.ascontiguousarray(
                np.asarray(payload["leaves"][name]))
            fname = f"leaf{i}.npy"
            fpath = os.path.join(tmp, fname)
            with open(fpath, "wb") as f:
                np.save(f, arr)
                f.flush()
                os.fsync(f.fileno())
            manifest["leaves"][name] = {
                "file": fname, "checksum": leaf_checksum(arr)}
        mpath = os.path.join(tmp, MANIFEST_FILE)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_path(tmp)
        os.rename(tmp, final)
        _fsync_path(self.spill_dir)
        self._disk[key] = None
        self.counters.incr("spills")
        return True

    def _load(self, key: bytes) -> Optional[dict]:
        """Read one disk entry back, verifying every leaf against the
        MANIFEST-recorded checksum (recorded at write time — the only
        reference that can convict torn bytes).  Any failure deletes
        the entry whole and returns None; success also deletes it
        (tiers are exclusive — the payload is leaving the store)."""
        root = os.path.join(self.spill_dir, key.hex())
        try:
            with open(os.path.join(root, MANIFEST_FILE)) as f:
                manifest = json.load(f)
            leaves = {}
            for name, ent in manifest["leaves"].items():
                arr = np.load(os.path.join(root, ent["file"]))
                got = leaf_checksum(arr)
                if got != ent["checksum"]:
                    raise ValueError(
                        f"offload spill {key.hex()} leaf {name!r}: "
                        f"checksum {got} != recorded "
                        f"{ent['checksum']}")
                leaves[name] = arr
            payload = {
                "num_blocks": int(manifest["num_blocks"]),
                "block_size": int(manifest["block_size"]),
                "leaves": leaves,
                "crc": {name: int(c)
                        for name, c in manifest["crc"].items()},
            }
        except (OSError, ValueError, KeyError) as _:
            self.counters.incr("disk_torn")
            self._disk.pop(key, None)
            shutil.rmtree(root, ignore_errors=True)
            return None
        self._disk.pop(key, None)
        shutil.rmtree(root, ignore_errors=True)
        return payload
