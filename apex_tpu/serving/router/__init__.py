"""apex_tpu.serving.router — the multi-replica front door.

Request-level data parallelism for serving (``docs/serving.md``,
"Multi-replica routing"): N in-process
:class:`~apex_tpu.serving.InferenceServer` replicas behind one
``submit()/step()/drain()/stats()`` surface — the serving analogue of
the survey's ``apex.parallel`` DDP pillar, where the unit replicated
is the whole engine and the unit balanced is the request.

Four modules, bottom-up:

- :mod:`~serving.router.policy` — placement:
  :class:`RouterPolicy` (least-pressure balancing on the PR-5
  ``Scheduler.pressure()`` signal, a spill threshold, seeded-random
  control arm) and :class:`AffinityIndex` (a router-side radix index
  over submitted prompts — hash-chained full-token chunks mapping
  content -> replica — so shared-prefix sessions land on the replica
  whose prefix cache already holds their blocks);
- :mod:`~serving.router.replica` — :class:`Replica`: one wrapped
  server plus its router-side circuit breaker (step failures are the
  in-process "connection refused") and health scrape (in-process or
  over its ops plane's ``GET /healthz``);
- :mod:`~serving.router.router` — :class:`ReplicaRouter` /
  :class:`RouterRequest`: routing, exactly-once failover (queued and
  zero-token work re-enqueues onto survivors bit-identically,
  mid-stream work fails ``replica_failed`` with partial output kept),
  rolling-restart drains, and the pinned ``stats()["router"]`` block;
- :mod:`~serving.router.fleet` — :class:`RouterFleet`: construction
  (incl. Router x TP: per-replica disjoint device meshes), the
  round-robin / threaded step loop, fleet ``generate()`` /
  ``drain()`` / ``close()``, and the aggregate ops plane.

Quick start::

    from apex_tpu.serving.router import RouterFleet
    fleet = RouterFleet(cfg, params, replicas=3, max_batch_size=4)
    outs = fleet.generate(prompts, max_new_tokens=64)

Every replica runs the full single-replica stack (prefix cache,
chunked prefill, speculation, pipelined loop, overload control), and
greedy output through the fleet is bit-identical to a single replica
(``tests/L0/test_router.py``).
"""

from apex_tpu.serving.router.fleet import RouterFleet
from apex_tpu.serving.router.policy import AffinityIndex, RouterPolicy
from apex_tpu.serving.router.replica import Replica
from apex_tpu.serving.router.router import ReplicaRouter, RouterRequest

__all__ = [
    "AffinityIndex",
    "Replica",
    "ReplicaRouter",
    "RouterFleet",
    "RouterPolicy",
    "RouterRequest",
]
