"""`ReplicaRouter` — placement, failover, and re-enqueue over N
replicas.

The router is request-level data parallelism: where the scheduler
decides *which iteration* serves a request and the engine decides
*which device step*, the router decides *which replica* — by pressure
(:class:`~serving.router.policy.RouterPolicy` least-pressure
balancing), by prefix affinity (the router-side radix index steering
shared-prefix sessions at the replica already holding their cached
blocks), and by health (per-replica circuit breakers
— :class:`~serving.router.replica.Replica`).

Callers hold :class:`RouterRequest` proxies, not raw scheduler
``Request`` objects: failover can MOVE a queued request to another
replica (a fresh underlying ``Request``), and the proxy is the stable
handle that follows it.  The failover contract
(``docs/serving.md``, "Multi-replica routing"):

- a replica whose ``step()`` keeps raising trips its router-side
  breaker; the router then **evacuates** it exactly once per open
  transition — queued work and zero-token admissions re-enqueue onto
  healthy replicas (bit-identical restarts: nothing was emitted yet),
  mid-stream requests finish ``finish_reason="replica_failed"`` with
  their partial output intact;
- every request reaches exactly ONE terminal state, on exactly one
  replica (the chaos soak's router invariants —
  :func:`resilience.chaos.run_router_soak`);
- re-enqueued requests keep their priority and their REMAINING
  deadline budget (wall and iteration), so failover never silently
  extends an SLA.

:class:`~serving.router.fleet.RouterFleet` owns construction and the
step loop; this class is the policy/bookkeeping core and is directly
testable with hand-built replicas.
"""

from __future__ import annotations

import itertools
import random
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.observability import NULL_JOURNEY_LOG
from apex_tpu.ops.sampling import SamplingParams
from apex_tpu.serving import reasons
from apex_tpu.serving.router.policy import AffinityIndex, RouterPolicy
from apex_tpu.serving.router.replica import Replica
from apex_tpu.serving.scheduler import Request
from apex_tpu.serving.transport import (
    InProcessTransport,
    TransportError,
    TransportPolicy,
)
from apex_tpu.utils import CounterMeter

__all__ = ["ReplicaRouter", "RouterRequest"]

_rid = itertools.count()


class RouterRequest:
    """The caller's stable handle on one routed request.

    Delegates the read surface (``generated`` / ``finished`` /
    ``finish_reason`` / ``timeline()``) to the CURRENT underlying
    scheduler ``Request`` — which failover may replace when the
    request is re-enqueued onto another replica.  ``rid`` is the
    router-level id (underlying ``uid`` changes on a move);
    ``replica`` is the index currently serving it (None = never
    placed); ``moves`` counts re-enqueues.

    ``rid`` doubles as the fleet-stable JOURNEY id
    (``observability.journey``): when journeys are enabled the router
    draws it up front, opens a :class:`JourneyContext` on it, and the
    context (held here as ``journey``) travels with the request across
    failover and hand-off — the ``uid`` changes on a move, the ``rid``
    never does.  Exactly one ``next(_rid)`` draw per request either
    way, so the rid sequence is byte-identical journeys on or off."""

    __slots__ = ("rid", "inner", "replica", "moves", "journey")

    def __init__(self, inner: Request, replica: Optional[int],
                 rid: Optional[int] = None, journey=None):
        self.rid = next(_rid) if rid is None else rid
        self.inner = inner
        self.replica = replica
        self.moves = 0
        self.journey = journey

    @property
    def prompt(self) -> List[int]:
        return self.inner.prompt

    @property
    def generated(self) -> List[int]:
        return self.inner.generated

    @property
    def finished(self) -> bool:
        return self.inner.finished

    @property
    def finish_reason(self) -> Optional[str]:
        return self.inner.finish_reason

    @property
    def priority(self) -> int:
        return self.inner.priority

    @property
    def max_new_tokens(self) -> int:
        return self.inner.max_new_tokens

    def timeline(self) -> dict:
        return self.inner.timeline()

    def __repr__(self):
        return (f"RouterRequest(rid={self.rid}, "
                f"replica={self.replica}, moves={self.moves}, "
                f"finished={self.finished})")


class ReplicaRouter:
    """Placement + failover core over a fixed replica list.

    Args:
      replicas: the :class:`Replica` wrappers (index order is the
        deterministic tiebreak everywhere).
      policy: the :class:`RouterPolicy` (default: stock affinity).
      clock: the router's monotonic-seconds source (deadline
        re-budgeting on re-enqueue).
      registry: the :class:`~observability.MetricsRegistry` holding
        the router's counters (``router_placements{outcome=}``,
        ``router_events{event=}``).
      tracer: span tracer (``route`` spans, ``router_failover`` /
        ``router_reenqueue`` instants).
    """

    def __init__(self, replicas: Sequence[Replica], *,
                 policy: Optional[RouterPolicy] = None,
                 clock=None, registry=None, tracer=None,
                 journeys=None, transport=None):
        if not replicas:
            raise ValueError("ReplicaRouter needs >= 1 replica")
        self.replicas = list(replicas)
        self.policy = policy if policy is not None else RouterPolicy()
        self.clock = clock if clock is not None \
            else self.replicas[0].server.clock
        # cross-replica KV transport (docs/serving.md, "KV
        # transport"): every hand-off / warm payload rides this
        # backend under the retry/deadline/breaker envelope; the
        # default in-process backend is behavior-identical to the
        # historical direct call
        self.transport = transport if transport is not None \
            else InProcessTransport(
                policy=TransportPolicy(clock=self.clock))
        for rep in self.replicas:
            self._register_transport_peer(rep)
        self.tracer = tracer
        # journey correlation (``observability.journey``): the
        # ROUTER's own hop log — front-door submit/route, failover
        # evacuate/re-enqueue, and hand-off outcomes record here with
        # replica label "router"; per-replica hops land in each
        # server's log and the fleet merges them by rid
        self.journeys = journeys if journeys is not None \
            else NULL_JOURNEY_LOG
        self.affinity = AffinityIndex(self.policy.affinity_block,
                                      self.policy.max_entries)
        self._rng = random.Random(self.policy.seed)
        self.placements = CounterMeter(registry=registry,
                                       name="router_placements",
                                       label="outcome")
        self.events = CounterMeter(registry=registry,
                                   name="router_events", label="event")
        self.requests: List[RouterRequest] = []
        self._by_uid: Dict[int, RouterRequest] = {}

    # -- placement ---------------------------------------------------------

    def place(self, prompt: Sequence[int], *,
              exclude: Optional[Replica] = None,
              role: Optional[str] = None
              ) -> Tuple[Optional[Replica], str]:
        """Pick the replica for ``prompt``: ``(replica, outcome)``
        with ``replica=None`` when nobody can take it.  Outcomes:
        ``affinity_hit`` (the matched replica takes it),
        ``affinity_spill`` (matched but over ``spill_threshold`` —
        least-pressure instead), ``affinity_dead`` (matched but
        dead/draining/probe-exhausted), ``affinity_miss`` (no match),
        ``least_pressure`` / ``random`` (the non-affinity kinds), or
        ``unplaced``.  The chosen replica's breaker ``allow()`` is
        consumed; merely-scanned replicas' are not.

        ``role`` is the phase preference of disaggregated placement
        (``docs/serving.md``, "Disaggregated prefill/decode"):
        ``"prefill"`` prefers prefill-role replicas, ``"decode"``
        prefers decode-capable ones (role ``"any"``/``"decode"``).  A
        preference, never a mandate — when no replica of the preferred
        role can take the request, placement falls back to every
        placeable replica (monolithic placement), so phase awareness
        can only redirect work, never strand it."""
        cands = [rep for rep in self.replicas
                 if rep is not exclude and rep.placeable()]
        if role is not None and cands:
            if role == "prefill":
                preferred = [r for r in cands if r.role == "prefill"
                             and r.alive]
            else:
                preferred = [r for r in cands if r.role != "prefill"
                             and r.alive]
            if preferred:
                cands = preferred
        if not cands:
            return None, "unplaced"
        kind = self.policy.kind
        if kind == "random":
            for rep in self._rng.sample(cands, len(cands)):
                if rep.breaker.allow():
                    return rep, "random"
            return None, "unplaced"
        outcome = "least_pressure"
        if kind == "affinity":
            ridx, _matched = self.affinity.match(list(prompt))
            if ridx is None:
                outcome = "affinity_miss"
            else:
                target = self.replicas[ridx]
                if (target is exclude or target not in cands
                        or not target.placeable()
                        or not target.alive):
                    outcome = "affinity_dead"
                elif target.pressure() >= self.policy.spill_threshold:
                    outcome = "affinity_spill"
                elif target.breaker.allow():
                    return target, "affinity_hit"
                else:
                    outcome = "affinity_dead"   # probe quota spent
        for rep in sorted(cands,
                          key=lambda r: (r.pressure(), r.index)):
            if rep.breaker.allow():
                return rep, outcome
        return None, "unplaced"

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None, *,
               priority: int = 0,
               deadline_iters: Optional[int] = None,
               deadline_s: Optional[float] = None) -> RouterRequest:
        """Route one request through the fleet's front door.  The
        chosen replica's own ``submit`` runs the usual per-replica
        gates (budget validation, bounded queue, its own breaker,
        draining) — a submit-time rejection there comes back through
        the proxy exactly as it would single-replica.  When NO replica
        can accept (all dead/draining), the proxy comes back already
        finished ``finish_reason="breaker_open"`` — the fleet-wide
        fast-fail — without touching any replica."""
        prompt = [int(t) for t in prompt]
        # the journey opens at the FRONT DOOR: the rid is drawn here
        # (the one next(_rid) call this request ever makes — the
        # RouterRequest below is handed the same rid, so the draw
        # count and hence the rid sequence is identical journeys on or
        # off), and the context's hops start before placement so the
        # route decision itself is part of the story
        rid = next(_rid)
        jlog = self.journeys
        ctx = jlog.start(rid) if jlog.enabled else None
        if ctx is not None:
            jlog.hop(ctx, "submit", prompt_tokens=len(prompt),
                     priority=int(priority))
        # phase-aware placement: long prompts prefer a prefill-role
        # replica (whose hand-off ships the KV to a decode replica);
        # short ones always place monolithically
        role = None
        thr = self.policy.disagg_prefill_threshold
        if thr is not None and len(prompt) >= thr:
            role = "prefill"
        tr = self.tracer
        if tr is not None and tr.enabled:
            # rid lands in the span only when journeys are armed, so
            # journey-less traces keep their legacy args
            span = (tr.span("route", tokens=len(prompt), rid=rid)
                    if ctx is not None
                    else tr.span("route", tokens=len(prompt)))
            with span:
                rep, outcome = self.place(prompt, role=role)
        else:
            rep, outcome = self.place(prompt, role=role)
        self.placements.incr(outcome)
        if rep is None:
            now = self.clock()
            inner = Request(prompt=prompt,
                            max_new_tokens=int(max_new_tokens),
                            eos_id=eos_id, priority=int(priority),
                            submitted_at=now)
            inner.finished = True
            inner.finish_reason = reasons.BREAKER_OPEN
            inner.finished_at = now
            if ctx is not None:
                # router-terminal: no server ever saw this request, so
                # the router closes the journey itself
                jlog.hop(ctx, "finish", uid=inner.uid,
                         reason=reasons.BREAKER_OPEN, tokens=0)
            rr = RouterRequest(inner, None, rid=rid, journey=ctx)
            self.requests.append(rr)
            return rr
        if ctx is not None:
            jlog.hop(ctx, "route", to=rep.name, outcome=outcome)
        inner = rep.server.submit(prompt, max_new_tokens, eos_id,
                                  priority=priority,
                                  deadline_iters=deadline_iters,
                                  deadline_s=deadline_s,
                                  journey=ctx)
        rr = RouterRequest(inner, rep.index, rid=rid, journey=ctx)
        self.requests.append(rr)
        self._by_uid[inner.uid] = rr
        if self.policy.kind == "affinity" and not inner.finished:
            self.affinity.record(prompt, rep.index)
        return rr

    # -- stepping (driven by RouterFleet) ----------------------------------

    def try_step(self, rep: Replica):
        """The concurrency-safe half of stepping one replica: run its
        ``step()`` and capture the outcome WITHOUT touching shared
        router state (the fleet's threaded mode calls this from worker
        threads).  Returns ``None`` for a skipped (breaker-open)
        replica, else ``(had_work, produced, exception_or_None)``."""
        if rep.breaker.state == "open":
            return None
        srv = rep.server
        had_work = srv.has_work
        try:
            return had_work, srv.step(), None
        except Exception as e:  # noqa: BLE001 — a replica blowing up
            #                     is exactly the event to contain
            return had_work, 0, e

    def absorb_step(self, rep: Replica, result) -> int:
        """The serial half: breaker bookkeeping over one
        :meth:`try_step` result, firing failover on the
        closed/half-open -> open edge.  An idle step never counts as
        breaker evidence (a dead engine answers empty steps just
        fine), so a sick replica cannot vacuously probe itself
        healthy.  Returns tokens produced."""
        if result is None:
            return 0
        had_work, produced, exc = result
        if exc is not None:
            rep.step_failures += 1
            rep.last_error = repr(exc)
            self.events.incr("step_errors")
            rep.breaker.record_failure()
        else:
            rep.steps += 1
            if had_work:
                rep.breaker.record_success()
        state = rep.breaker.state
        if state == "open" and rep.last_breaker_state != "open":
            self._failover(rep)
        rep.last_breaker_state = state
        return produced

    # -- failover / lifecycle ----------------------------------------------

    def _failover(self, rep: Replica) -> None:
        """The replica's breaker just opened: evacuate it (queued +
        zero-token work re-enqueues, mid-stream work fails
        ``replica_failed`` with partial output kept) and place the
        evacuees on the survivors."""
        self.events.incr("failovers")
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant("router_failover", replica=rep.name)
        moved, failed = rep.server.evacuate(reasons.REPLICA_FAILED)
        if failed:
            self.events.incr(reasons.REPLICA_FAILED, len(failed))
        self.reenqueue(moved, exclude=rep)

    def reenqueue(self, reqs: Sequence[Request], *,
                  exclude: Optional[Replica] = None) -> int:
        """Place withdrawn (never-finished, zero-output) requests on
        replicas other than ``exclude``, rebinding their proxies.
        Deadlines carry their REMAINING budget: wall deadlines shrink
        by the time already spent, iteration deadlines by the
        iterations already burned on the old replica.  A request
        nobody can take finishes ``breaker_open`` at the router.
        Returns the number successfully re-placed."""
        now = self.clock()
        jlog = self.journeys
        placed = 0
        for old in reqs:
            rr = self._by_uid.pop(old.uid, None)
            # the context travels on the inner request; the failover
            # hop PAIR (evacuate -> reenqueue) both record here at the
            # router — consecutive seqs whichever replica dies when
            ctx = getattr(old, "journey", None)
            if jlog.enabled and ctx is not None:
                jlog.hop(ctx, "evacuate", uid=old.uid,
                         src=exclude.name if exclude is not None
                         else None)
            rep, _outcome = self.place(old.prompt, exclude=exclude)
            if rep is None:
                old.finished = True
                old.finish_reason = reasons.BREAKER_OPEN
                old.finished_at = now
                self.events.incr("reenqueue_unplaced")
                if jlog.enabled and ctx is not None:
                    # router-terminal: the old server withdrew the
                    # request unfinished and nobody can take it, so
                    # the router closes the journey
                    jlog.hop(ctx, "finish", uid=old.uid,
                             reason=reasons.BREAKER_OPEN,
                             tokens=len(old.generated))
                if rr is not None:
                    rr.replica = None
                continue
            d_s = d_iters = None
            if old.deadline_s is not None:
                d_s = max(0.0,
                          old.deadline_s - (now - old.submitted_at))
            if old.deadline_iters is not None and exclude is not None:
                burned = exclude.server._iter - old.submit_iter
                d_iters = max(0, old.deadline_iters - burned)
            elif old.deadline_iters is not None:
                d_iters = old.deadline_iters
            if jlog.enabled and ctx is not None:
                jlog.hop(ctx, "reenqueue", to=rep.name)
            new = rep.server.submit(old.prompt, old.max_new_tokens,
                                    old.eos_id,
                                    priority=old.priority,
                                    deadline_iters=d_iters,
                                    deadline_s=d_s,
                                    journey=ctx)
            self.events.incr("reenqueued")
            if self.tracer is not None and self.tracer.enabled:
                self.tracer.instant("router_reenqueue",
                                    to=rep.name, uid=new.uid)
            if rr is not None:
                rr.inner = new
                rr.replica = rep.index
                rr.moves += 1
                self._by_uid[new.uid] = rr
            else:
                self._by_uid[new.uid] = RouterRequest(new, rep.index,
                                                      journey=ctx)
            if self.policy.kind == "affinity" and not new.finished:
                self.affinity.record(old.prompt, rep.index)
            placed += 1
        return placed

    # -- KV transport (docs/serving.md, "KV transport") --------------------

    def _register_transport_peer(self, rep: Replica) -> None:
        """Register ``rep`` as a transport peer.  The handler is the
        RECEIVER half of every cross-replica block movement: it
        dispatches on ``meta["op"]`` — ``"handoff"`` ingests a
        finished prefill's decode half, ``"warm"`` imports donor
        prefix blocks into spare pool capacity.  Meta is plain JSON
        data (the socket backend serializes it); only an in-process
        backend (``carries_objects``) may carry the journey context
        object through."""
        def handle(meta: dict, payload: dict) -> dict:
            op = meta.get("op")
            if op == "handoff":
                s = meta["sampling"]
                sampling = None if s is None else SamplingParams(
                    temperature=s[0], top_k=s[1], top_p=s[2],
                    seed=s[3])
                new = rep.server.ingest_handoff(
                    meta["prompt"], meta["generated"], payload,
                    max_new_tokens=meta["max_new_tokens"],
                    num_cached=meta["num_cached"],
                    eos_id=meta["eos_id"],
                    priority=meta["priority"],
                    deadline_iters=meta["deadline_iters"],
                    deadline_s=meta["deadline_s"],
                    sampling=sampling,
                    submitted_at=meta["submitted_at"],
                    first_token_at=meta["first_token_at"],
                    journey=meta.get("journey"))
                return {"uid": None if new is None else int(new.uid)}
            if op == "warm":
                eng = rep.server.prefill_engine or rep.server.engine
                n = int(payload.get("num_blocks", 0))
                if n <= 0:
                    return {"blocks": None}
                # warm only into genuinely spare capacity: the
                # replica must still admit a full-context request
                # immediately after seeding
                spare = eng.allocator.num_free - eng.blocks_per_seq
                if spare < n:
                    return {"blocks": None}
                dst = eng.allocator.alloc(n)
                if dst is None:
                    return {"blocks": None}
                try:
                    eng.import_blocks(dst, payload)
                except Exception:
                    eng.allocator.free(dst)
                    raise
                return {"blocks": [int(b) for b in dst]}
            raise ValueError(f"unknown transport op {op!r}")
        self.transport.register_peer(rep.name, handle)

    # -- disaggregated prefill -> decode hand-off --------------------------

    def handoff_sink_for(self, rep: Replica):
        """The ``handoff_sink`` callable wired into a prefill-role
        replica's server (``InferenceServer(handoff_sink=...)``): the
        server calls it with ``(request, payload)`` when a prefill
        finishes, and the router places the decode half."""
        def sink(req, payload) -> bool:
            return self._handoff_request(rep, req, payload)
        return sink

    def _handoff_request(self, prefill_rep: Replica, req,
                         payload: dict) -> bool:
        """Place one finished prefill's decode half: ingest the
        checksummed block payload into a decode-capable replica's
        pool, rebinding the caller's proxy to the new underlying
        request.  On any failure — torn payload (checksum mismatch),
        no capacity, no healthy decode replica — the request FALLS
        BACK TO MONOLITHIC PLACEMENT: a fresh submit elsewhere re-runs
        the prefill and regenerates the same stream (greedy /
        counter-keyed sampling is a pure function of the prompt), so
        failover moves work, never tokens.  Returns True when
        ownership moved off the prefill replica (it then finishes the
        local request ``finish_reason="handoff"``); False keeps the
        request on the prefill replica's own decode pool — the last
        resort when no other replica can take it."""
        rr = self._by_uid.pop(req.uid, None)
        now = self.clock()
        jlog = self.journeys
        ctx = getattr(req, "journey", None)
        d_s = d_iters = None
        if req.deadline_s is not None:
            d_s = max(0.0, req.deadline_s - (now - req.submitted_at))
        if req.deadline_iters is not None:
            burned = prefill_rep.server._iter - req.submit_iter
            d_iters = max(0, req.deadline_iters - burned)

        def rebind(new, rep_idx):
            if rr is not None:
                rr.inner = new
                rr.replica = rep_idx
                rr.moves += 1
                self._by_uid[new.uid] = rr
            else:
                self._by_uid[new.uid] = RouterRequest(new, rep_idx,
                                                      journey=ctx)

        target, _outcome = self.place(req.prompt,
                                      exclude=prefill_rep,
                                      role="decode")
        if target is not None:
            if jlog.enabled and ctx is not None:
                # export records at the router (not the prefill
                # replica) so the local-fallback path keeps its single
                # export hop from scheduler.release_handoff
                jlog.hop(ctx, "handoff_export", to=target.name,
                         blocks=int(payload.get("num_blocks", 0)))
            s = req.sampling
            meta = {
                "op": "handoff",
                "prompt": [int(t) for t in req.prompt],
                "generated": [int(t) for t in req.generated],
                "max_new_tokens": int(req.max_new_tokens),
                "num_cached": int(req.num_cached),
                "eos_id": (None if req.eos_id is None
                           else int(req.eos_id)),
                "priority": int(req.priority),
                "deadline_iters": d_iters,
                "deadline_s": d_s,
                "sampling": (None if s is None else
                             [s.temperature, s.top_k, s.top_p,
                              s.seed]),
                "submitted_at": req.submitted_at,
                "first_token_at": req.first_token_at,
            }
            if self.transport.carries_objects and ctx is not None:
                # only an in-process backend may carry the live
                # journey context; over a wire the hand-off keeps
                # its per-replica hops and the fleet merge still
                # correlates by rid
                meta["journey"] = ctx
            new = None
            try:
                ack = self.transport.send(target.name, meta, payload)
            except ValueError:
                # torn payload: detected whole, nothing imported
                self.events.incr("handoff_torn")
                if jlog.enabled and ctx is not None:
                    jlog.hop(ctx, "handoff_torn", to=target.name)
            except TransportError:
                # the envelope gave up (retries exhausted, deadline,
                # or open breaker): exactly-once ingest means nothing
                # half-landed on the target — degrade to monolithic
                self.events.incr("handoff_transport_failed")
                if jlog.enabled and ctx is not None:
                    jlog.hop(ctx, "handoff_transport_failed",
                             to=target.name)
            else:
                if ack.get("uid") is not None:
                    new = target.server._find_request(int(ack["uid"]))
            if new is not None:
                if req.finished:
                    # a cancel() raced the transfer: the prefill side
                    # already terminalized the request, so the
                    # freshly-ingested decode half must not live on —
                    # cancel it on the target (frees its imported
                    # blocks) and report ownership moved
                    target.server.cancel(new.uid)
                    self.events.incr("handoff_cancelled")
                    return True
                self.events.incr("handoffs")
                if self.tracer is not None and self.tracer.enabled:
                    self.tracer.instant("router_handoff",
                                        to=target.name, uid=new.uid)
                rebind(new, target.index)
                if self.policy.kind == "affinity":
                    self.affinity.record(req.prompt, target.index)
                return True
        # monolithic fallback: fresh prefill + decode on whichever
        # replica can take it (bit-identical stream by construction)
        if req.finished:
            # cancelled while placing: nothing to resubmit — the
            # request reached its terminal on the prefill replica
            self.events.incr("handoff_cancelled")
            return True
        rep2, _outcome = self.place(req.prompt, exclude=prefill_rep)
        if rep2 is not None:
            if jlog.enabled and ctx is not None:
                jlog.hop(ctx, "handoff_fallback", to=rep2.name)
            new = rep2.server.submit(req.prompt, req.max_new_tokens,
                                     req.eos_id,
                                     priority=req.priority,
                                     deadline_iters=d_iters,
                                     deadline_s=d_s,
                                     sampling=req.sampling,
                                     journey=ctx)
            self.events.incr("handoff_fallback")
            rebind(new, rep2.index)
            if self.policy.kind == "affinity" and not new.finished:
                self.affinity.record(req.prompt, rep2.index)
            return True
        # nobody else can take it: keep it on the prefill replica's
        # own (small) decode pool
        if rr is not None:
            self._by_uid[req.uid] = rr
        self.events.incr("handoff_kept_local")
        return False

    def drain_replica(self, rep: Replica) -> int:
        """Rolling-restart drain: stop placing on ``rep`` (router-side
        flag + server ``begin_drain``), move its QUEUED work to the
        survivors, and leave its in-flight work to finish in place
        over the fleet's normal stepping — zero healthy-request loss.
        Returns the number of requests moved."""
        rep.draining = True
        rep.server.begin_drain()
        moved = rep.server.withdraw_queued()
        self.events.incr("drains")
        self.reenqueue(moved, exclude=rep)
        return len(moved)

    def revive(self, rep: Replica, server=None) -> None:
        """Return ``rep`` to the rotation — after a drain (rolling
        restart: pass the fresh ``server`` replacing the drained one)
        or to force-close a recovered breaker.  A replaced server's
        affinity entries are dropped (the fresh cache is cold); the
        old server is closed when it is safely drainable."""
        if server is not None:
            old = rep.server
            if not old.closed and not old.has_work:
                old.close()
            rep.server = server
            self.affinity.drop_replica(rep.index)
        elif rep.server.draining and not rep.server.closed:
            # same-server revive (in-place weight rollout): reopen
            # the admissions ``begin_drain()`` closed — the server
            # kept its compiled programs, only its params moved
            rep.server.end_drain()
        rep.draining = False
        rep.breaker.reset()
        rep.last_breaker_state = rep.breaker.state
        self.events.incr("revives")

    # -- elastic membership (serving/elastic) ------------------------------

    def add_replica(self, rep: Replica) -> None:
        """Admit a new replica to the rotation.  Append-at-end ONLY:
        the router holds its own copy of the replica list and the
        affinity index stores positional indices into it, so the new
        replica's ``index`` must equal its position here AND in the
        fleet's list."""
        if rep.index != len(self.replicas):
            raise ValueError(
                f"replica index {rep.index} must equal its position "
                f"{len(self.replicas)} (affinity indices are "
                f"positional)")
        self.replicas.append(rep)
        self._register_transport_peer(rep)
        self.events.incr("scale_ups")

    def remove_replica(self, rep: Replica) -> None:
        """Retire a replica from the rotation — the TAIL one only
        (removing any other position would shift every index the
        affinity map stores).  Its affinity chains are dropped so no
        placement ever resolves to the retired position."""
        if not self.replicas or self.replicas[-1] is not rep:
            raise ValueError(
                f"only the tail replica may be removed (got "
                f"{rep.name}); drain + remove from the end")
        self.replicas.pop()
        self.affinity.drop_replica(rep.index)
        self.events.incr("scale_downs")

    # -- stats -------------------------------------------------------------

    def router_stats(self) -> dict:
        """The pinned ``stats()["router"]`` block (minus the fleet
        driver's own keys — :meth:`RouterFleet.stats` adds those)."""
        p = self.placements
        hit = p.count("affinity_hit")
        miss = p.count("affinity_miss")
        spill = p.count("affinity_spill")
        dead = p.count("affinity_dead")
        looked = hit + miss + spill + dead
        return {
            "replicas": len(self.replicas),
            "alive": sum(1 for r in self.replicas if r.alive),
            "policy": {
                "kind": self.policy.kind,
                "spill_threshold": self.policy.spill_threshold,
                "affinity_block": self.policy.affinity_block,
                "index_entries": len(self.affinity),
            },
            "placements": p.as_dict(),
            "affinity": {
                "hits": hit,
                "misses": miss,
                "spills": spill,
                "dead": dead,
                "hit_rate": round(hit / looked, 3) if looked else 0.0,
            },
            "reenqueued": self.events.count("reenqueued"),
            "failovers": self.events.count("failovers"),
            "replica_failed": self.events.count(reasons.REPLICA_FAILED),
            # disaggregated prefill -> decode hand-offs
            # (docs/serving.md, "Disaggregated prefill/decode")
            "handoffs": self.events.count("handoffs"),
            "handoff_fallback": self.events.count("handoff_fallback"),
            "handoff_torn": self.events.count("handoff_torn"),
            "handoff_kept_local":
                self.events.count("handoff_kept_local"),
            "handoff_transport_failed":
                self.events.count("handoff_transport_failed"),
            "handoff_cancelled":
                self.events.count("handoff_cancelled"),
            "disagg_prefill_threshold":
                self.policy.disagg_prefill_threshold,
            "unplaced": (p.count("unplaced")
                         + self.events.count("reenqueue_unplaced")),
            "per_replica": {rep.name: rep.snapshot()
                            for rep in self.replicas},
        }
