"""Placement policy for the multi-replica router — who serves this
prompt?

Two signals compose (``docs/serving.md``, "Multi-replica routing"):

**Least pressure.**  Every replica already publishes the PR-5 overload
signal — ``Scheduler.pressure()``, the max of queue fill and
(live blocks + queued demand) / usable blocks — so the balanced
default is simply "place on the replica under the least pressure",
ties toward the lowest index (deterministic, so tests and the chaos
replay never depend on dict order).

**Prefix affinity.**  Shared-prefix traffic (system prompts, few-shot
templates, multi-turn sessions) only profits from a replica's prefix
cache if it keeps LANDING on that replica — spraying a session across
the fleet re-prefills the shared blocks N times and caches them N
times.  The router keeps its own radix index over SUBMITTED prompts
(the same hash-chained full-chunk encoding as
:mod:`serving.prefix_cache`, but mapping token content -> replica
instead of -> physical block): a new prompt walks the chain, and the
deepest match votes for the replica that last served that prefix.
Affinity is a hint, never a mandate — it YIELDS to pressure (a match
whose replica sits above ``spill_threshold`` spills to least-pressure
rather than pile onto a hot spot) and to health (dead or draining
replicas are skipped).

The index is bounded (``max_entries``) with LRU eviction cascading
over chain descendants — a dangling parent must take its children
with it, exactly the :class:`~serving.prefix_cache.PrefixCache`
eviction rule, because a child key embeds its parent's node id.

``kind="random"`` (seeded) exists for the bench's control arm
(``tools/serving_bench.py --router``): the A/B that proves affinity
actually concentrates cache hits is affinity-vs-random on identical
shared-prefix traffic.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["AffinityIndex", "RouterPolicy"]

# chain parent of a prompt's first chunk (mirrors prefix_cache.ROOT)
_ROOT = 0


@dataclasses.dataclass
class RouterPolicy:
    """Knobs for :meth:`ReplicaRouter.place` (``serving.router``).

    Args:
      kind: ``"affinity"`` (the default: radix-affinity overriding
        least-pressure), ``"least_pressure"`` (balancing only), or
        ``"random"`` (seeded uniform — the bench control arm).
      spill_threshold: affinity yields when the matched replica's
        ``pressure()`` is at or above this — the point where piling
        more shared-prefix work onto the cache-warm replica costs
        more in queueing than the cache hit saves.  The PR-5 pressure
        signal may exceed 1.0 (queued demand counts), so 0.9 means
        "nearly full, counting what's already queued".
      affinity_block: tokens per index chunk.  Match granularity is
        one chunk; the natural value is the replicas' KV block size
        (the fleet defaults it there) so router-side matches predict
        replica-side cache hits one-to-one.
      max_entries: affinity-index bound; least-recently-touched chains
        evict first (cascading over descendants).
      seed: the ``"random"`` kind's RNG seed (deterministic benches).
      disagg_prefill_threshold: prompts at or above this token count
        route to a PREFILL-role replica when the fleet has one alive
        (``docs/serving.md``, "Disaggregated prefill/decode") — the
        prefill replica runs the prompt and ships the KV blocks to a
        decode replica.  ``None`` (default) disables phase-aware
        placement; short prompts always place monolithically (a
        cross-replica hand-off costs more than a short prefill).
    """

    kind: str = "affinity"
    spill_threshold: float = 0.9
    affinity_block: int = 16
    max_entries: int = 8192
    seed: int = 0
    disagg_prefill_threshold: Optional[int] = None

    def __post_init__(self):
        if self.kind not in ("affinity", "least_pressure", "random"):
            raise ValueError(
                f"unknown placement kind {self.kind!r} (expected "
                f"'affinity', 'least_pressure', or 'random')")
        if self.disagg_prefill_threshold is not None \
                and self.disagg_prefill_threshold < 1:
            raise ValueError(
                f"disagg_prefill_threshold must be >= 1, got "
                f"{self.disagg_prefill_threshold}")
        if self.affinity_block < 1:
            raise ValueError(
                f"affinity_block must be >= 1, got {self.affinity_block}")
        if self.max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {self.max_entries}")


class AffinityIndex:
    """Token content -> replica, hash-chained over full chunks.

    The key of chunk i is ``(parent node id, tuple of its tokens)`` —
    two prompts agreeing on chunks 0..i-1 share the same parent id by
    induction, so the flat dict encodes the radix tree without hashing
    whole prefixes (the :class:`~serving.prefix_cache.PrefixCache`
    trick, host-side only: the router never sees physical blocks).

    Values are mutable replica indices: re-registering an existing
    chain under a different replica REPOINTS it (most recent placement
    wins) — after a failover or drain the next placement heals the
    index instead of chasing a dead replica forever.
    """

    def __init__(self, block: int, max_entries: int = 8192):
        self.block = int(block)
        self.max_entries = int(max_entries)
        self._next_id = 1
        # key -> [node_id, replica]; OrderedDict recency = LRU order
        self._map: "OrderedDict[Tuple[int, tuple], list]" = OrderedDict()
        self._children: Dict[int, Set[Tuple[int, tuple]]] = {}

    def __len__(self) -> int:
        return len(self._map)

    def match(self, tokens: List[int]) -> Tuple[Optional[int], int]:
        """Walk ``tokens``' full chunks down the chain; returns
        ``(replica of the deepest matched chunk, matched tokens)`` —
        ``(None, 0)`` on a cold miss.  Touches matched entries
        (LRU recency)."""
        parent, replica, matched = _ROOT, None, 0
        for i in range(len(tokens) // self.block):
            key = (parent, tuple(tokens[i * self.block:
                                        (i + 1) * self.block]))
            node = self._map.get(key)
            if node is None:
                break
            self._map.move_to_end(key)
            parent, replica = node[0], node[1]
            matched += self.block
        return replica, matched

    def record(self, tokens: List[int], replica: int) -> int:
        """Register every full chunk of ``tokens`` as served by
        ``replica`` (repointing chunks already chained elsewhere);
        returns chunks touched.  Evicts LRU chains past
        ``max_entries``."""
        parent, chunks = _ROOT, 0
        for i in range(len(tokens) // self.block):
            key = (parent, tuple(tokens[i * self.block:
                                        (i + 1) * self.block]))
            node = self._map.get(key)
            if node is None:
                node = [self._next_id, replica]
                self._next_id += 1
                self._map[key] = node
                self._children.setdefault(parent, set()).add(key)
            else:
                node[1] = replica
                self._map.move_to_end(key)
            parent = node[0]
            chunks += 1
        while len(self._map) > self.max_entries:
            oldest = next(iter(self._map))
            self._remove(oldest)
        return chunks

    def drop_replica(self, replica: int) -> int:
        """Remove every entry pointing at ``replica`` (cascading over
        descendants — a surviving child of a dropped parent would
        dangle) — called when a replica is replaced by a FRESH server
        whose cache is cold, so stale affinity stops steering traffic
        at an empty cache.  Returns entries removed."""
        doomed = [k for k, node in self._map.items()
                  if node[1] == replica]
        before = len(self._map)
        for key in doomed:
            if key in self._map:           # cascade may have taken it
                self._remove(key)
        return before - len(self._map)

    def _remove(self, key: Tuple[int, tuple]) -> None:
        node = self._map.pop(key)
        self._children.get(key[0], set()).discard(key)
        for child in list(self._children.pop(node[0], ())):
            if child in self._map:
                self._remove(child)
