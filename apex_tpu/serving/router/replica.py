"""One routed replica — an :class:`~serving.InferenceServer` plus the
router-side state that decides whether to trust it.

The router deliberately does NOT reuse the server's own submit-guard
breaker: that one watches the ENGINE's numerics (non-finite logits,
OOM bursts) from inside a healthy process, while the router's
per-replica breaker watches the replica AS A WHOLE from outside — a
step() that raises is the in-process analogue of a connection refused.
Three states, standard semantics (:class:`resilience.CircuitBreaker`):
closed replicas serve, a failure streak opens the breaker (the router
fails over: queued work re-enqueues onto healthy replicas), and after
the cooldown the half-open probe quota lets a little traffic test the
replica before it rejoins the rotation.

Health comes in two flavors: :meth:`Replica.health` reads the live
server in-process (the default — replicas are in-process objects), or
scrapes its ops plane's ``GET /healthz`` over real HTTP
(``via_http=True``) when one is attached — the one-cheap-endpoint
contract (``pressure`` / ``draining`` / ``live_requests`` are
machine-readable in the body) a cross-process router would live on.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Callable, Optional

from apex_tpu.resilience.breaker import CircuitBreaker

__all__ = ["Replica"]


class Replica:
    """Router-side wrapper around one in-process ``InferenceServer``.

    Args:
      index: position in the fleet (stable — placement and the
        affinity index refer to it).
      server: the wrapped ``InferenceServer``.
      name: display name for stats/logs (default ``replica<index>``).
      breaker: the router-side :class:`CircuitBreaker` for THIS
        replica (default: 3-failure threshold on ``clock``).
      clock: monotonic-seconds source for the default breaker.
    """

    def __init__(self, index: int, server, *,
                 name: Optional[str] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 clock: Optional[Callable[[], float]] = None,
                 role: str = "any"):
        if role not in ("any", "prefill", "decode"):
            raise ValueError(
                f"unknown replica role {role!r} (expected 'any', "
                f"'prefill', or 'decode')")
        self.index = int(index)
        self.name = name or f"replica{index}"
        self.server = server
        # phase role (docs/serving.md, "Disaggregated prefill/
        # decode"): a "prefill" replica runs prefills and ships the
        # KV to a decode-capable replica; "any" (the default) serves
        # monolithically.  Placement prefers matching roles but NEVER
        # strands a request — with no prefill replica alive, long
        # prompts fall back to monolithic placement.
        self.role = role
        self.breaker = breaker if breaker is not None else \
            CircuitBreaker(failure_threshold=3,
                           clock=clock or server.clock)
        # router-side lifecycle: `draining` stops placement while the
        # replica runs its in-flight work off (rolling restart);
        # `last_error` is the most recent step failure, for stats()
        self.draining = False
        self.steps = 0
        self.step_failures = 0
        self.last_error: Optional[str] = None
        # which published weights this replica serves (None until a
        # rollout stamps it — "initial" in stats; serving/elastic)
        self.weights_version: Optional[str] = None
        # breaker-state edge detection: the router fails over exactly
        # once per closed/half_open -> open transition
        self.last_breaker_state = self.breaker.state

    # -- placement signals -------------------------------------------------

    @property
    def alive(self) -> bool:
        """Steppable right now (breaker not open) — NOT the same as
        placeable (:meth:`can_accept` also checks drain/close and the
        half-open probe quota)."""
        return self.breaker.state != "open"

    def pressure(self) -> float:
        """The replica's PR-5 overload signal (queue fill vs pool
        demand, now incl. the remaining-prefill-tokens backlog) — the
        router's balancing key.  Server-level: a disaggregated
        replica's saturated prefill pool reads as pressure even while
        its decode pool idles."""
        return self.server.pressure()

    def live_requests(self) -> int:
        """Waiting + running requests (the ``/healthz`` occupancy
        field, read in-process)."""
        n = 0
        for sched in self.server._schedulers():
            n += len(sched.waiting) + len(sched.running)
        return n

    def placeable(self) -> bool:
        """May this replica receive NEW work, breaker aside?  (The
        breaker's ``allow()`` is consumed separately, only on the
        replica placement actually picks — a half-open probe admission
        must not be burned on replicas that merely got scanned.)"""
        return (not self.draining
                and not self.server.draining
                and not self.server.closed)

    # -- health ------------------------------------------------------------

    def health(self, *, via_http: bool = False,
               timeout: float = 0.5, retries: int = 1) -> dict:
        """The replica's health view — status / pressure / draining /
        live_requests.  In-process reads by default; ``via_http=True``
        scrapes the attached ops plane's ``GET /healthz`` (the wire
        contract a cross-process router uses), raising
        :class:`RuntimeError` when no ops plane is attached.

        The HTTP scrape is BOUNDED: ``timeout`` caps both connect and
        read per attempt and a connect/read failure gets exactly
        ``retries`` more attempts before the probe gives up with
        ``{"status": "unreachable"}`` instead of raising — a wedged
        replica (accepts the socket, never answers) costs the caller
        at most ``timeout * (1 + retries)`` seconds and can never
        stall a fleet ``step()`` loop on an exception path."""
        if via_http:
            ops = getattr(self.server, "ops", None)
            if ops is None:
                raise RuntimeError(
                    f"{self.name} has no ops plane attached "
                    f"(ops_port=) to scrape /healthz from")
            url = f"http://{ops.host}:{ops.port}/healthz"
            last_err = "unknown"
            for _ in range(1 + max(0, int(retries))):
                try:
                    with urllib.request.urlopen(url,
                                                timeout=timeout) as r:
                        return json.loads(r.read())
                except urllib.error.HTTPError as e:  # 503 still has
                    return json.loads(e.read())      # a JSON body
                except (urllib.error.URLError, OSError,
                        ValueError) as e:
                    last_err = str(e) or type(e).__name__
            return {"status": "unreachable", "error": last_err,
                    "pressure": None, "draining": None,
                    "live_requests": None}
        srv = self.server
        if srv.closed:
            status = "closed"
        elif srv.draining or self.draining:
            status = "draining"
        elif not self.alive:
            status = "breaker_open"
        else:
            status = "ok"
        return {
            "status": status,
            "pressure": round(self.pressure(), 4),
            "draining": bool(srv.draining or self.draining),
            "live_requests": self.live_requests(),
        }

    # -- stats -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The per-replica row of ``stats()["router"]`` — cheap direct
        reads, never a full ``server.stats()``."""
        sched = self.server.scheduler
        return {
            "name": self.name,
            "role": self.role,
            "alive": self.alive,
            "draining": bool(self.draining or self.server.draining),
            "pressure": round(self.pressure(), 4),
            "live_requests": self.live_requests(),
            "waiting": len(sched.waiting),
            "running": len(sched.running),
            "finished": len(sched.finished),
            "steps": self.steps,
            "step_failures": self.step_failures,
            "last_error": self.last_error,
            "weights_version": self.weights_version or "initial",
            "breaker": self.breaker.state_snapshot(),
        }
