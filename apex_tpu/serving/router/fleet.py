"""`RouterFleet` — N in-process replicas behind one front door.

The driver half of the router subsystem: builds the replicas (one
:class:`~serving.InferenceServer` each, optionally each carrying its
own ``mesh=``/``tp=`` slice — the replicas-of-shards topology), wires
them into a :class:`~serving.router.router.ReplicaRouter`, and
exposes the same four-call surface as one server:

- ``submit()`` — routed by pressure/affinity/health
  (:mod:`serving.router.policy`);
- ``step()`` — one round-robin pass over the replicas (each replica
  advances one continuous-batching iteration; the rotation point
  moves every fleet step so no replica systematically retires first).
  ``threaded=True`` steps the replicas concurrently on a private
  thread pool — each replica's device step is independent, so on a
  multi-core host (or N real device sets) the fleet step costs ~the
  slowest replica, not the sum.  Breaker bookkeeping and failover
  stay serial either way (``ReplicaRouter.absorb_step``), so the two
  modes make identical routing decisions;
- ``drain()`` — fleet-wide graceful shutdown (every replica stops
  admitting, in-flight work runs to terminal states);
  ``drain_replica()`` / ``revive()`` are the rolling-restart pair;
- ``stats()`` — fleet aggregates plus the pinned ``stats()["router"]``
  block (per-replica pressure/live/finished, affinity
  hit/spill/re-enqueue counters, per-replica breaker snapshots).

Router × TP (``docs/serving.md``, "Multi-replica routing"): pass
``tp=K`` and each replica gets its OWN ``jax.sharding.Mesh`` over a
disjoint ``K``-device slice — ``replicas * tp`` devices total — so
request-level data parallelism composes with tensor-parallel decode
exactly as it would across real hosts.

An optional aggregate ops plane (``ops_port=``) serves the fleet the
same way a single server's does: ``/healthz`` answers for the fleet
(ok / draining / closed) with the router's pressure gauge,
``/statusz`` is the fleet ``stats()``, ``/metrics`` the router
registry, and ``/debug/requests/<uid>`` finds a request on whichever
replica holds it.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence

from apex_tpu.observability import (
    JOURNEYS_ENV,
    NULL_FLIGHT_RECORDER,
    NULL_JOURNEY_LOG,
    NULL_WATCHDOG,
    JourneyLog,
    MetricsRegistry,
    OpsServer,
    dump_journeys,
    fleet_prometheus_text,
    get_tracer,
    journeys_census,
    merge_journeys,
    resolve_journeys,
    write_postmortem,
)
from apex_tpu.resilience.breaker import CircuitBreaker
from apex_tpu.serving import reasons
from apex_tpu.serving.api import InferenceServer
from apex_tpu.serving.elastic import Autoscaler, AutoscalerConfig
from apex_tpu.serving.elastic.rollout import rollout_fleet
from apex_tpu.serving.router.policy import RouterPolicy
from apex_tpu.serving.router.replica import Replica
from apex_tpu.serving.router.router import ReplicaRouter, RouterRequest
from apex_tpu.serving.scheduler import Request
from apex_tpu.serving.streaming import StreamBroker, TokenStream
from apex_tpu.serving.transport import (
    InProcessTransport,
    KVTransport,
    TransportError,
    TransportPolicy,
)
from apex_tpu.utils import GaugeMeter

__all__ = ["RouterFleet"]

_NO_LOCK = contextlib.nullcontext()


class _FleetSchedView:
    """Duck-typed aggregate ``scheduler`` for the ops plane: the
    endpoints only read ``waiting`` / ``running`` / ``finished`` /
    ``has_work``, so the view concatenates the replicas' live state
    on access (``running`` keyed by uid — what ``/debug/requests``
    actually looks up)."""

    def __init__(self, fleet: "RouterFleet"):
        self._fleet = fleet

    @property
    def waiting(self):
        return [r for rep in self._fleet.replicas
                for r in rep.server.scheduler.waiting]

    @property
    def running(self):
        return {r.uid: r for rep in self._fleet.replicas
                for r in rep.server.scheduler.running.values()}

    @property
    def finished(self):
        return [r for rep in self._fleet.replicas
                for r in rep.server.scheduler.finished]

    @property
    def has_work(self):
        return self._fleet.has_work


class RouterFleet:
    """N routed replicas with one ``submit/step/drain/stats`` door.

    Args:
      cfg, params: the model every replica serves (shared host-side;
        each replica holds its own device arrays and compiled
        programs — that is the point of a replica).
      replicas: fleet size (>= 1).
      policy: the :class:`RouterPolicy`; default stock affinity with
        ``affinity_block`` snapped to the replicas' KV block size so
        router-side matches predict replica-side cache hits.
      make_server: optional ``make_server(i) -> InferenceServer``
        factory overriding replica construction entirely (mutually
        exclusive with ``tp=``); the default builds
        ``InferenceServer(cfg, params, clock=clock, **server_kwargs)``
        per replica — each with its OWN private registry, so
        per-replica counters never alias.
      tp: tensor-parallel degree PER REPLICA — each replica gets a
        disjoint ``tp``-device mesh slice (Router × TP; needs
        ``replicas * tp`` visible devices).
      tp_axis: the mesh axis name (default ``"model"``).
      breaker_factory: ``(i) -> CircuitBreaker`` for the router-side
        per-replica breakers (default: 3-failure threshold on
        ``clock``).
      threaded: step replicas concurrently on a private thread pool
        (identical routing decisions either way; see module
        docstring).
      clock / registry / tracer: the fleet's time source, metrics
        registry (router counters + per-replica pressure gauges), and
        span tracer.
      ops_port: serve the aggregate ops plane on this loopback port
        (0 = ephemeral), mirroring ``InferenceServer(ops_port=)``.
      **server_kwargs: passed to every default-built replica
        (``max_batch_size``, ``block_size``, ``cache_dtype``, ...).
    """

    def __init__(self, cfg, params, *, replicas: int = 2,
                 policy: Optional[RouterPolicy] = None,
                 make_server: Optional[Callable] = None,
                 names: Optional[Sequence[str]] = None,
                 tp: Optional[int] = None, tp_axis: str = "model",
                 breaker_factory: Optional[Callable] = None,
                 threaded: bool = False,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None,
                 tracer=None,
                 ops_port: Optional[int] = None,
                 disagg_prefill: int = 0,
                 disagg_prefill_threshold: Optional[int] = None,
                 enable_streaming: bool = True,
                 stream_queue_tokens: int = 256,
                 enable_elastic: bool = False,
                 elastic: Optional[AutoscalerConfig] = None,
                 enable_journeys: Optional[bool] = None,
                 kv_transport: Optional[KVTransport] = None,
                 **server_kwargs):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if make_server is not None and tp:
            raise ValueError(
                "pass either make_server= or tp= — a custom factory "
                "owns its replicas' meshes")
        if disagg_prefill and not 0 < disagg_prefill < replicas:
            raise ValueError(
                f"disagg_prefill={disagg_prefill} must leave at least "
                f"one decode-capable replica (replicas={replicas})")
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self.clock = clock
        # journey correlation plane (docs/observability.md, "Request
        # journeys & exemplars"; OFF by default): the fleet arms one
        # log per replica (replica=<name>) plus a router-level one
        # (replica="router") for route/failover/hand-off hops, and
        # journey(rid) merges them causally by hop seq
        if enable_journeys is None:
            enable_journeys = os.environ.get(JOURNEYS_ENV)
        self._enable_journeys = resolve_journeys(enable_journeys)
        self.journeys = (
            JourneyLog(replica="router",
                       iter_source=lambda: self._iter, clock=clock)
            if self._enable_journeys else NULL_JOURNEY_LOG)
        self._journey_name_next: Optional[str] = None
        # the fleet keeps its construction recipe: scale-up builds
        # new replicas from the same factory/kwargs, and rollout
        # rebinds self.params so post-rollout scale-ups serve the
        # NEW weights (serving/elastic)
        self.cfg = cfg
        self.params = params
        self._server_kwargs = dict(server_kwargs)
        self._breaker_factory = breaker_factory
        self._weights_version: Optional[str] = None
        self._last_rollout: Optional[dict] = None
        self._rollout_active = False
        self.retired_replicas: List[Replica] = []
        meshes: List = [None] * replicas
        if tp:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            devs = jax.devices()
            need = tp * replicas
            if len(devs) < need:
                raise ValueError(
                    f"Router x TP needs replicas*tp = {need} devices "
                    f"for {replicas} replicas of tp={tp}, have "
                    f"{len(devs)}")
            meshes = [Mesh(np.asarray(devs[i * tp:(i + 1) * tp]),
                           (tp_axis,)) for i in range(replicas)]

        def default_server(i: int) -> InferenceServer:
            kw = dict(server_kwargs)
            # scaled-up replicas (i beyond the construction-time
            # fleet) are meshless "any"-role; reading self.params
            # (not the closure arg) keeps them on the rolled-out
            # weight version
            if i < len(meshes) and meshes[i] is not None:
                kw.setdefault("mesh", meshes[i])
                kw.setdefault("tp_axis", tp_axis)
            if i < disagg_prefill:
                # a prefill-role replica runs its server DISAGGREGATED
                # so every prefill lands in the dedicated prefill pool
                # and finished KV ships through the hand-off sink
                # (wired below); its own decode pool stays the
                # last-resort local fallback
                kw.setdefault("enable_disagg", True)
            if self._enable_journeys:
                # each replica's log is labeled with its fleet name so
                # merged journeys read replica0 -> replica2, not
                # server/server (scale-ups pass their serial name via
                # _journey_name_next)
                kw.setdefault("enable_journeys", True)
                kw.setdefault(
                    "journey_replica",
                    self._journey_name_next
                    or (names[i] if names and i < len(names)
                        else f"replica{i}"))
            return InferenceServer(cfg, self.params, clock=clock,
                                   **kw)

        build = make_server or default_server
        self._build = build
        self.replicas: List[Replica] = []
        for i in range(replicas):
            srv = build(i)
            breaker = (breaker_factory(i) if breaker_factory is not None
                       else CircuitBreaker(failure_threshold=3,
                                           clock=clock))
            name = names[i] if names else None
            self.replicas.append(
                Replica(i, srv, name=name, breaker=breaker,
                        role="prefill" if i < disagg_prefill
                        else "any"))
        if policy is None:
            policy = RouterPolicy(
                affinity_block=self.replicas[0].server.engine.block_size,
                disagg_prefill_threshold=(
                    disagg_prefill_threshold if disagg_prefill
                    else None))
        # cross-replica KV transport (docs/serving.md, "KV
        # transport"): hand-off and warm payloads ride this backend;
        # the router registers every replica as a peer (elastic
        # scale-ups included) and the in-process default is
        # behavior-identical to the historical direct calls
        self.kv_transport = kv_transport if kv_transport is not None \
            else InProcessTransport(policy=TransportPolicy(clock=clock))
        self.router = ReplicaRouter(self.replicas, policy=policy,
                                    clock=clock,
                                    registry=self.registry,
                                    tracer=self.tracer,
                                    journeys=self.journeys,
                                    transport=self.kv_transport)
        # wire each prefill-role replica's hand-off sink to the router
        # (the server exports the blocks; the router places the decode
        # half — docs/serving.md, "Disaggregated prefill/decode")
        for rep in self.replicas:
            if rep.role == "prefill" and rep.server.disagg:
                rep.server.handoff_sink = \
                    self.router.handoff_sink_for(rep)
        if disagg_prefill and \
                self.router.policy.disagg_prefill_threshold is None:
            # default: prompts spanning >= 4 KV blocks are worth the
            # cross-replica transfer; shorter ones stay monolithic
            self.router.policy = dataclasses.replace(
                self.router.policy,
                disagg_prefill_threshold=(
                    4 * self.replicas[0].server.engine.block_size))
        self.threaded = bool(threaded)
        self._pool = (ThreadPoolExecutor(
            max_workers=replicas,
            thread_name_prefix="apex-tpu-router")
            if self.threaded and replicas > 1 else None)
        self._iter = 0
        self._draining = False
        self._closed = False
        self._final_stats: Optional[dict] = None
        # fleet-level pressure (max over alive replicas) — the ops
        # plane's /healthz pressure field, and the router's own
        # saturation signal
        self.pressure_gauge = GaugeMeter(registry=self.registry,
                                         name="router_pressure")
        self._replica_pressure = [
            GaugeMeter(registry=self.registry,
                       name="router_replica_pressure",
                       replica=rep.name)
            for rep in self.replicas]
        # ops-plane duck-type surface (the aggregate view): the fleet
        # has no single flight ring / watchdog / submit breaker — the
        # per-replica ones live behind each replica's own ops plane
        self.watchdog = NULL_WATCHDOG
        self.recorder = NULL_FLIGHT_RECORDER
        self.breaker = None
        self.scheduler = _FleetSchedView(self)
        self._postmortem_dir = None
        # fleet-level streaming front door (docs/serving.md,
        # "Streaming & cancellation"): streams key on the STABLE
        # ``rid`` and read through the RouterRequest proxy, so a
        # stream survives failover re-enqueue and hand-off rebinds;
        # the cursor pump republishes from the proxy's token list and
        # the broker's index dedup drops anything already delivered
        self.stream_broker: Optional[StreamBroker] = (
            StreamBroker(queue_tokens=stream_queue_tokens)
            if enable_streaming else None)
        self._stream_reqs: dict = {}     # rid -> RouterRequest
        self._stream_cursors: dict = {}  # rid -> publish high-water
        # elastic control loop (docs/serving.md, "Elastic fleet"):
        # OFF by default — a fleet without it is byte-identical to
        # the pre-elastic fleet.  Scaled-up replicas take serial
        # names (replicaN, N ever-increasing) so a retire + regrow
        # never aliases stats rows.
        self._replica_serial = replicas
        self.autoscaler: Optional[Autoscaler] = (
            Autoscaler(self, elastic, clock=clock)
            if enable_elastic else None)
        self.ops: Optional[OpsServer] = None
        self._ops_lock = None
        if ops_port is not None:
            self.ops = OpsServer(self, port=ops_port)
            self._ops_lock = self.ops.lock
            self.ops.start()

    # -- the one-door surface ----------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None, *,
               priority: int = 0,
               deadline_iters: Optional[int] = None,
               deadline_s: Optional[float] = None) -> RouterRequest:
        """Route one request (see :meth:`ReplicaRouter.submit`)."""
        with (self._ops_lock or _NO_LOCK):
            if self._closed:
                raise RuntimeError(
                    "RouterFleet is closed; no further submissions")
            if self._draining:
                # fleet-level drain: finish at the front door exactly
                # like a draining single server would — without
                # consuming a placement
                now = self.clock()
                inner = Request(prompt=[int(t) for t in prompt],
                                max_new_tokens=int(max_new_tokens),
                                eos_id=eos_id,
                                priority=int(priority),
                                submitted_at=now)
                inner.finished = True
                inner.finish_reason = reasons.DRAINING
                inner.finished_at = now
                rr = RouterRequest(inner, None)
                self.router.requests.append(rr)
                return rr
            return self.router.submit(
                prompt, max_new_tokens, eos_id, priority=priority,
                deadline_iters=deadline_iters, deadline_s=deadline_s)

    def step(self) -> int:
        """One fleet iteration: every non-open replica advances one
        continuous-batching step (rotating the start point for
        fairness), then breaker bookkeeping and any failover run
        serially.  Returns tokens produced across the fleet."""
        with (self._ops_lock or _NO_LOCK):
            return self._step()

    def _step(self) -> int:
        self._iter += 1
        n = len(self.replicas)
        k = self._iter % n
        order = self.replicas[k:] + self.replicas[:k]
        router = self.router
        if self._pool is not None:
            futures = {rep: self._pool.submit(router.try_step, rep)
                       for rep in order}
            results = {rep: f.result() for rep, f in futures.items()}
        else:
            results = {rep: router.try_step(rep) for rep in order}
        produced = 0
        for rep in order:
            produced += router.absorb_step(rep, results[rep])
        peak = 0.0
        for rep, gauge in zip(self.replicas, self._replica_pressure):
            p = rep.pressure()
            gauge.update(p)
            if rep.alive and p > peak:
                peak = p
        self.pressure_gauge.update(peak)
        self._pump_streams()
        # the control loop ticks last, on this step's fresh gauges;
        # it stands down while a drain or rollout owns the replica
        # list (one lifecycle driver at a time)
        if self.autoscaler is not None and not self._draining \
                and not self._rollout_active:
            self.autoscaler.observe()
        return produced

    # -- elastic fleet (docs/serving.md, "Elastic fleet") ------------------

    def shed_debt_tokens(self) -> int:
        """Cumulative SLO debt (shed tokens) across the fleet —
        retired replicas included, so the autoscaler's trend signal
        never jumps backwards on a scale-down."""
        return sum(
            rep.server.slo.as_stats()["debt"]["shed_tokens"]
            for rep in self.replicas + self.retired_replicas)

    def add_replica(self, *, warm_blocks: int = 0) -> Replica:
        """Grow the fleet by one replica built from the construction
        recipe (factory or default kwargs), optionally warming its
        prefix cache from a donor.  Manual actuator — the autoscaler
        calls the unlocked body."""
        with (self._ops_lock or _NO_LOCK):
            rep, _ = self._add_replica(warm_blocks=warm_blocks)
            return rep

    def _add_replica(self, *, warm_blocks: int = 0):
        i = len(self.replicas)
        name = f"replica{self._replica_serial}"
        self._replica_serial += 1
        # the default factory reads the serial name for its journey
        # log label (the positional default would alias a retired
        # replica's rows after a scale-down + regrow)
        self._journey_name_next = name
        try:
            srv = self._build(i)
        finally:
            self._journey_name_next = None
        breaker = (self._breaker_factory(i)
                   if self._breaker_factory is not None
                   else CircuitBreaker(failure_threshold=3,
                                       clock=self.clock))
        rep = Replica(i, srv, name=name, breaker=breaker, role="any")
        rep.weights_version = self._weights_version
        # append-at-end ONLY: the affinity index stores positional
        # replica indices, so any other insertion point would remap
        # every existing entry under the router's feet
        self.replicas.append(rep)
        self.router.add_replica(rep)
        self._replica_pressure.append(
            GaugeMeter(registry=self.registry,
                       name="router_replica_pressure",
                       replica=rep.name))
        warmed = self._warm_replica(rep, warm_blocks) \
            if warm_blocks > 0 else 0
        return rep, warmed

    def _warm_replica(self, rep: Replica, max_blocks: int) -> int:
        """Seed the new replica's prefix cache from the best donor
        over the checksummed block-transfer path.  Best-effort: any
        failure (no donor, no spare blocks, torn payload) leaves the
        replica cold, never broken."""
        dst_srv = rep.server
        dst_pc = dst_srv.prefix_cache
        if dst_pc is None:
            return 0
        donor, best = None, 0
        for cand in self.replicas:
            if cand is rep or not cand.alive or cand.draining:
                continue
            pc = cand.server.prefix_cache
            if pc is not None and pc.num_cached_blocks > best:
                best = pc.num_cached_blocks
                donor = cand
        if donor is None:
            return 0
        src_srv = donor.server
        nodes = src_srv.prefix_cache.export_nodes(max_blocks)
        if not nodes:
            return 0
        # the engines that OWN the prefix pool (the prefill pool
        # under disaggregation)
        src_eng = src_srv.prefill_engine or src_srv.engine
        dst_eng = dst_srv.prefill_engine or dst_srv.engine
        # warm only into genuinely spare capacity: the new replica
        # must still admit a full-context request immediately
        spare = dst_eng.allocator.num_free - dst_eng.blocks_per_seq
        n = min(len(nodes), max(0, spare))
        if n <= 0:
            return 0
        nodes = nodes[:n]
        src_ids = [blk for _, _, blk in nodes]
        try:
            payload = src_eng.export_blocks(src_ids)
        except Exception:
            return 0
        # the bulk KV bytes ride the transport (alloc + import happen
        # in the peer handler — the receiver owns its pool); the
        # control plane (donor choice, spare-capacity read, radix
        # seeding below) stays in-process
        try:
            ack = self.kv_transport.send(rep.name, {"op": "warm"},
                                         payload)
        except (ValueError, MemoryError, TransportError):
            # torn transfer (checksum rejected whole), receiver OOM,
            # or an exhausted envelope: the handler freed its staging
            # blocks — start cold, never broken
            return 0
        dst_ids = ack.get("blocks")
        if not dst_ids:
            return 0
        return dst_pc.seed_nodes(nodes, dict(zip(src_ids, dst_ids)))

    def remove_replica(self) -> Replica:
        """Retire the LAST replica (it must already be drained dry —
        ``drain_replica`` + stepping first).  The server closes; the
        replica moves to ``retired_replicas`` so its finished ledger
        keeps counting in fleet aggregates."""
        with (self._ops_lock or _NO_LOCK):
            return self._remove_replica()

    def _remove_replica(self) -> Replica:
        rep = self.replicas[-1]
        if not (rep.draining and not rep.server.has_work):
            raise RuntimeError(
                f"{rep.name} still has work or is not draining; "
                f"drain it dry before remove_replica()")
        self.replicas.pop()
        self.router.remove_replica(rep)
        gauge = self._replica_pressure.pop()
        gauge.update(0.0)
        rep.server.close()
        self.retired_replicas.append(rep)
        return rep

    def _probe_server(self, params) -> InferenceServer:
        """A standalone (never-routed) server for the rollout parity
        audit — same model kwargs as a default replica, its own
        private registry, NO entry in any fleet ledger, so probe
        traffic can never pollute the soaks' exactly-once
        accounting."""
        return InferenceServer(self.cfg, params, clock=self.clock,
                               **self._server_kwargs)

    def rollout(self, checkpoint_dir: str, **kwargs) -> dict:
        """Zero-downtime weight rollout of the newest checkpoint
        under ``checkpoint_dir`` (``serving/elastic/rollout.py``:
        per-replica drain -> swap -> verify -> revive behind an A/B
        output-parity gate; halt + rollback on any failure).  Runs
        UNLOCKED like :meth:`drain` — every fleet call it makes
        self-locks, and holding the ops lock across a multi-step
        drain would starve the handlers."""
        return rollout_fleet(self, checkpoint_dir, **kwargs)

    # -- streaming & cancellation (docs/serving.md) ------------------------

    def _pump_streams(self) -> None:
        """Fan this fleet step's tokens out to open streams.  Reads go
        through the RouterRequest proxy, so a rebind (failover
        re-enqueue, hand-off, monolithic fallback) is transparent:
        the moved request regenerates its stream bit-identically, the
        publish cursor only ever advances, and the broker's index
        dedup discards the already-delivered prefix."""
        b = self.stream_broker
        if b is None or not self._stream_reqs:
            return
        for rid, rr in list(self._stream_reqs.items()):
            gen = rr.generated
            cur = self._stream_cursors.get(rid, 0)
            for i in range(cur, len(gen)):
                b.publish(rid, i, gen[i])
            if len(gen) > cur:
                self._stream_cursors[rid] = len(gen)
            if rr.finished:
                b.finish(rid, rr.finish_reason or "")
                self._stream_reqs.pop(rid, None)
                self._stream_cursors.pop(rid, None)

    def _resolve_request(self, which) -> Optional[RouterRequest]:
        """The RouterRequest for a proxy or rid (None if unknown)."""
        if isinstance(which, RouterRequest):
            return which
        rid = int(which)
        for rr in self.router.requests:
            if rr.rid == rid:
                return rr
        return None

    def stream(self, req_or_rid, callback: Optional[Callable] = None
               ) -> TokenStream:
        """The per-token stream for a routed request — the fleet
        front door's delivery surface (same contract as
        :meth:`InferenceServer.stream`, keyed by the stable ``rid``).
        Opening late backfills; the stream survives failover and
        hand-off and ends with a terminal event carrying the
        ``finish_reason``."""
        with (self._ops_lock or _NO_LOCK):
            if self.stream_broker is None:
                raise RuntimeError(
                    "streaming is disabled (enable_streaming=False)")
            rr = self._resolve_request(req_or_rid)
            if rr is None:
                raise KeyError(
                    f"no routed request with rid {req_or_rid}")
            s = self.stream_broker.open(rr.rid, rr, callback)
            if not rr.finished:
                self._stream_reqs[rr.rid] = rr
                self._pump_streams()
            return s

    def cancel(self, req_or_rid) -> bool:
        """Cancel a routed request wherever it currently lives (the
        SSE front door's disconnect hook).  Scans the replicas by the
        CURRENT inner uid, so a request that moved since submission is
        still found; idempotent — False for unknown/terminal."""
        with (self._ops_lock or _NO_LOCK):
            rr = self._resolve_request(req_or_rid)
            if rr is None or rr.finished:
                return False
            uid = rr.inner.uid
            for rep in self.replicas:
                if rep.server.cancel(uid):
                    self._pump_streams()
                    return True
            return False

    def _stream_stats(self) -> dict:
        """The fleet ``stats()["streams"]`` block: front-door broker
        counters + fleet-wide cancellation tally."""
        cancelled = sum(
            rep.server.failures.count("requests_failed_cancelled")
            for rep in self.replicas)
        st = {"enabled": self.stream_broker is not None,
              "cancelled": cancelled}
        if self.stream_broker is not None:
            st.update(self.stream_broker.stats())
            # bounded per-stream rows (``ops_probe --streams``)
            st["per_stream"] = self.stream_broker.snapshot()
        return st

    @property
    def has_work(self) -> bool:
        """Any live (non-open) replica still holding queued, running,
        or launched-but-unretired work.  Open replicas never count:
        failover already evacuated them."""
        return any(rep.server.has_work for rep in self.replicas
                   if rep.breaker.state != "open")

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int,
                 eos_id: Optional[int] = None, *,
                 priority: int = 0,
                 return_requests: bool = False):
        """Batch-synchronous front door, fleet edition: route all
        prompts, run the fleet to completion, return the generated
        ids per prompt in input order (or the proxies with
        ``return_requests=True``)."""
        reqs = [self.submit(p, max_new_tokens, eos_id,
                            priority=priority) for p in prompts]
        while self.has_work:
            self.step()
        if return_requests:
            return reqs
        return [list(r.generated) for r in reqs]

    # -- lifecycle ---------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def closed(self) -> bool:
        return self._closed

    def drain_replica(self, which) -> int:
        """Rolling-restart drain of one replica (index or name):
        placement stops, queued work moves to the survivors, in-flight
        work finishes in place over normal stepping.  Returns requests
        moved."""
        with (self._ops_lock or _NO_LOCK):
            return self.router.drain_replica(self._resolve(which))

    def replica_drained(self, which) -> bool:
        """True once a draining replica has run all its work off —
        safe to swap (:meth:`revive`)."""
        rep = self._resolve(which)
        return rep.draining and not rep.server.has_work

    def revive(self, which, server=None) -> None:
        """Return a replica to the rotation, optionally swapping in a
        fresh server (the rolling-restart second half)."""
        with (self._ops_lock or _NO_LOCK):
            self.router.revive(self._resolve(which), server)

    def _resolve(self, which) -> Replica:
        if isinstance(which, Replica):
            return which
        if isinstance(which, str):
            for rep in self.replicas:
                if rep.name == which:
                    return rep
            raise KeyError(f"no replica named {which!r}")
        return self.replicas[int(which)]

    def drain(self) -> dict:
        """Fleet-wide graceful shutdown: every replica stops
        admitting, then the fleet steps until all in-flight work
        reaches terminal states.  Idempotent; returns the final
        :meth:`stats`."""
        # admissions stop atomically w.r.t. concurrent submit()/step()
        # holders of the ops lock (apexlint lock-discipline: the flag
        # write used to race the handler threads)
        with (self._ops_lock or _NO_LOCK):
            self._draining = True
            for rep in self.replicas:
                rep.server.begin_drain()
        # the convergence loop runs unlocked on purpose: step()
        # re-locks per iteration, and holding across it would starve
        # ops handlers; a stale has_work read only costs one extra step
        # apexlint: disable=lock-discipline — convergence loop; step() self-locks per iteration
        while self.has_work:
            self.step()
        return self.stats()

    def close(self) -> dict:
        """Drain, then close every replica, stop the thread pool and
        the ops plane, and refuse further submissions.  Exactly-once;
        repeated calls return the same final stats."""
        with (self._ops_lock or _NO_LOCK):
            if self._closed:
                return self._final_stats
        final = self.drain()
        with (self._ops_lock or _NO_LOCK):
            if self._closed:       # lost a concurrent close(): keep
                return self._final_stats        # the first result
            self._final_stats = final
            self._closed = True
            replicas = list(self.replicas)
            pool, ops = self._pool, self.ops
        for rep in replicas:
            srv = rep.server
            if not srv.closed and not srv.has_work:
                srv.close()
        # teardown after the flag flip, unlocked: joining the ops
        # thread while holding its own lock would deadlock any
        # handler blocked on that lock
        if pool is not None:
            pool.shutdown(wait=True)
        if ops is not None:
            ops.stop()
        # the transport join rides the same unlocked teardown: the
        # _closed flag already fenced new sends, and the socket
        # backend's server thread synchronizes on the TRANSPORT lock,
        # not the fleet ops lock — joining it under _ops_lock would
        # only stall late ops handlers for the join timeout
        # apexlint: disable=lock-discipline
        self.kv_transport.close()
        return final

    # -- observability -----------------------------------------------------

    def _journey_logs(self) -> list:
        """Every journey log in the fleet: the router's own (route /
        failover / hand-off hops) plus each replica's — retired
        replicas included, so a journey that finished on a since-
        removed replica still merges complete."""
        return [self.journeys] + [
            rep.server.journeys
            for rep in self.replicas + self.retired_replicas]

    def journey(self, rid: int) -> Optional[dict]:
        """One request's merged cross-replica journey (None if the
        rid never opened one).  Hops from every replica it touched
        — submit/route at the router, enqueue/admit/first-token/
        finish on the servers, evacuate/reenqueue and hand-off hops
        wherever they fired — causally ordered by the hop sequence
        the traveling context issued, never by wall clock."""
        with (self._ops_lock or _NO_LOCK):
            j = merge_journeys(self._journey_logs(),
                               rid=int(rid)).get(int(rid))
            return j.as_dict() if j is not None else None

    def fleet_metrics_text(self) -> str:
        """Fleet-wide Prometheus exposition: the router registry's
        series as-is plus every replica's private registry with a
        ``replica=<name>`` label — one HELP/TYPE per family across
        the whole fleet (``GET /metrics/fleet``).  Lock-free like
        ``/metrics``: registries serialize internally."""
        sources = [({}, self.registry)]
        sources += [({"replica": rep.name}, rep.server.registry)
                    for rep in self.replicas + self.retired_replicas]
        return fleet_prometheus_text(sources)

    def dump_postmortem(self, path: str, *, reason: str = "on_demand",
                        extra: Optional[dict] = None) -> dict:
        """The aggregate ops plane's postmortem hook: the router
        registry snapshot + trace + a manifest carrying the router
        block (per-replica flight rings live behind each replica's
        own ops plane), plus the merged journeys member when the
        correlation plane is armed."""
        merged = {"iter": self._iter,
                  "router": self.router.router_stats()}
        if extra:
            merged.update(extra)
        return write_postmortem(path, recorder=self.recorder,
                                registry=self.registry,
                                tracer=self.tracer, reason=reason,
                                extra=merged,
                                journeys=(
                                    dump_journeys(self._journey_logs())
                                    if self.journeys.enabled else None))

    def stats(self) -> dict:
        """Fleet aggregates + the pinned ``stats()["router"]`` block
        (``docs/serving.md``, "Multi-replica routing").  Aggregate
        prefix-cache counters sum the replicas' — the fleet-level
        hit rate is what the affinity policy exists to raise
        (``tools/serving_bench.py --router`` floors it vs random
        placement)."""
        with (self._ops_lock or _NO_LOCK):
            return self._stats()

    def _elastic_stats(self) -> dict:
        """The pinned ``stats()["elastic"]`` block: the autoscaler's
        decision table when the control loop is on, the minimal
        shape otherwise — plus the rollout/version fields either
        way (rollout works on non-autoscaled fleets too)."""
        st = (self.autoscaler.stats() if self.autoscaler is not None
              else {"enabled": False})
        census: dict = {}
        for rep in self.replicas:
            v = rep.weights_version or "initial"
            census[v] = census.get(v, 0) + 1
        st["weights_versions"] = census
        st["last_rollout"] = self._last_rollout
        return st

    def _stats(self) -> dict:
        router = self.router.router_stats()
        router["steps"] = self._iter
        router["threaded"] = self.threaded
        hit = miss = finished = tokens = 0
        # retired replicas stay in the ledger: a scale-down must not
        # make finished work or generated tokens vanish from the
        # fleet's aggregates (the soak reconciles on these)
        for rep in self.replicas + self.retired_replicas:
            srv = rep.server
            hit += srv.prefix.count("prefix_hit_tokens")
            miss += srv.prefix.count("prefix_miss_tokens")
            finished += len(srv.scheduler.finished)
            tokens += srv.tokens.total
        return {
            "router": router,
            "requests_finished": finished,
            "requests_unplaced": router["unplaced"],
            "tokens_generated": tokens,
            "prefix_hit_tokens": hit,
            "prefix_miss_tokens": miss,
            "prefix_hit_rate": round(hit / (hit + miss), 3)
            if hit + miss else 0.0,
            "pressure": round(self.pressure_gauge.val, 3),
            "pressure_peak": round(self.pressure_gauge.peak, 3),
            "draining": self._draining,
            "streams": self._stream_stats(),
            "elastic": self._elastic_stats(),
            "journeys": journeys_census(self._journey_logs()),
            # cross-replica KV transport (docs/serving.md, "KV
            # transport"): envelope totals + per-peer counters and
            # breaker state for hand-off / warm transfers
            "transport": self.kv_transport.stats(),
        }
