"""SLO-driven autoscaler — the fleet's size as a control variable.

The controller is deliberately boring: one observation per fleet
step, one score, one hysteresis band, one action in flight at a time.

- **Score**: the windowed average of the fleet pressure gauge (max
  over alive replicas — one saturated replica IS a capacity problem,
  however idle its peers) plus ``debt_weight`` times the SLO-debt
  growth over the same window (``SLOTracker``'s shed-token counters:
  work the fleet already refused).  Pressure says "about to be
  late"; debt growth says "already turning work away" — either alone
  can be noise, together they cross the band exactly when capacity,
  not placement, is the binding constraint.
- **Hysteresis + cooldowns**: scale up at ``score >= up_pressure``,
  down at ``score <= down_pressure``, with the dead band between
  them and per-direction cooldowns (measured on the injected clock)
  absorbing oscillation.  A scale-up also re-arms the DOWN cooldown:
  the fresh replica must get a full window to absorb load before it
  can be judged idle.
- **One action at a time**: a scale-down is a rolling drain — the
  victim (always the LAST replica: the affinity index stores
  positional indices, so only tail removal keeps every stored index
  valid) stops placing, its queued work moves to survivors, and only
  when it runs dry is it retired.  While that drain converges the
  controller takes no other action.

Everything is deterministic for a (schedule, seed) pair: the clock is
injected, the signals are pure functions of fleet state, and there is
no randomness anywhere in the loop — the chaos soak replays the same
scaling trajectory every run.

Scale-up warms the NEW replica's prefix cache from a donor (the alive
replica with the most registered blocks): the donor's radix tree is
exported parent-before-child (``PrefixCache.export_nodes``), the KV
bytes travel over the engine's CHECKSUMMED ``export_blocks`` /
``import_blocks`` path (a torn transfer is rejected whole, exactly
like a decode hand-off), and the imported blocks are registered +
parked as evictable holds — so the first flash-crowd request the new
replica sees can already hit cache.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Optional

__all__ = ["Autoscaler", "AutoscalerConfig"]


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Hysteresis band, cooldowns, and bounds for one fleet.

    ``up_pressure`` / ``down_pressure`` bracket the dead band on the
    score (module docstring); ``debt_weight`` converts shed tokens
    per window into score units (0 = pressure-only scaling);
    ``window`` is the smoothing horizon in fleet steps;
    ``up_cooldown_s`` / ``down_cooldown_s`` are per-direction action
    spacings on the fleet clock; ``warm_blocks`` bounds the donor
    prefix-cache transfer per scale-up (0 = cold start);
    ``max_decisions`` bounds the decision log in ``stats()``."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_pressure: float = 0.85
    down_pressure: float = 0.25
    debt_weight: float = 0.01
    window: int = 8
    up_cooldown_s: float = 20.0
    down_cooldown_s: float = 60.0
    warm_blocks: int = 16
    max_decisions: int = 64

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas={self.max_replicas} must be >= "
                f"min_replicas={self.min_replicas}")
        if not 0.0 <= self.down_pressure < self.up_pressure:
            raise ValueError(
                f"need 0 <= down_pressure < up_pressure (the "
                f"hysteresis dead band), got down={self.down_pressure} "
                f"up={self.up_pressure}")
        if self.debt_weight < 0:
            raise ValueError(
                f"debt_weight must be >= 0, got {self.debt_weight}")
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.warm_blocks < 0:
            raise ValueError(
                f"warm_blocks must be >= 0, got {self.warm_blocks}")


class Autoscaler:
    """The per-fleet controller instance (one per ``RouterFleet``,
    created by ``enable_elastic=True``).  :meth:`observe` runs at the
    END of every fleet step, under the fleet's ops lock — it
    therefore calls the fleet's UNLOCKED actuators (``_add_replica``
    and friends), never the public locking wrappers."""

    def __init__(self, fleet, cfg: Optional[AutoscalerConfig] = None,
                 *, clock: Optional[Callable[[], float]] = None):
        self.fleet = fleet
        self.cfg = cfg if cfg is not None else AutoscalerConfig()
        self.clock = clock if clock is not None else fleet.clock
        self._pressure_win: deque = deque(maxlen=self.cfg.window)
        # one extra slot so [-1] - [0] spans exactly `window` steps
        self._debt_win: deque = deque(maxlen=self.cfg.window + 1)
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0
        self.retiring = None            # Replica mid-rolling-drain
        self.decisions: deque = deque(maxlen=self.cfg.max_decisions)
        self._last_action = "none"
        self._score = 0.0
        self._pressure_avg = 0.0
        self._debt_delta = 0

    # -- the control loop --------------------------------------------------

    def observe(self) -> None:
        """One controller tick (end of ``RouterFleet._step``)."""
        fleet, cfg = self.fleet, self.cfg
        now = self.clock()
        self._pressure_win.append(fleet.pressure_gauge.val)
        self._debt_win.append(fleet.shed_debt_tokens())
        self._pressure_avg = (sum(self._pressure_win)
                              / len(self._pressure_win))
        self._debt_delta = self._debt_win[-1] - self._debt_win[0]
        self._score = (self._pressure_avg
                       + cfg.debt_weight * self._debt_delta)

        # an in-flight scale-down converges before anything else may
        # happen — one actuator at a time keeps the trajectory
        # attributable (and the replica list stable per action)
        if self.retiring is not None:
            if fleet.replica_drained(self.retiring):
                victim = self.retiring
                self.retiring = None
                fleet._remove_replica()
                self._last_down_t = now
                self.scale_downs += 1
                self._decide("scale_down", now,
                             replica=victim.name)
            return

        size = len(fleet.replicas)
        if (self._score >= cfg.up_pressure
                and size < cfg.max_replicas
                and self._ready(self._last_up_t, cfg.up_cooldown_s,
                                now)):
            rep, warmed = fleet._add_replica(
                warm_blocks=cfg.warm_blocks)
            self._last_up_t = now
            self._last_down_t = now     # fresh capacity gets a grace
            self.scale_ups += 1         # window before any cull
            self._decide("scale_up", now, replica=rep.name,
                         warmed_blocks=warmed)
            return

        if (self._score <= cfg.down_pressure
                and size > cfg.min_replicas
                and self._ready(self._last_down_t,
                                cfg.down_cooldown_s, now)):
            victim = fleet.replicas[-1]
            if victim.draining:
                return                  # already leaving the fleet
            fleet.router.drain_replica(victim)
            self.retiring = victim
            self._decide("drain", now, replica=victim.name)

    @staticmethod
    def _ready(last: Optional[float], cooldown: float,
               now: float) -> bool:
        return last is None or now - last >= cooldown

    def _decide(self, action: str, now: float, **signals) -> None:
        """Pin one decision everywhere it is postmortem-visible: the
        bounded decision log (``stats()["elastic"]``), the fleet's
        flight recorder, and a tracer instant."""
        fleet = self.fleet
        rec = {"kind": "elastic", "action": action,
               "iter": fleet._iter, "t": now,
               "pressure_avg": round(self._pressure_avg, 4),
               "debt_delta": int(self._debt_delta),
               "score": round(self._score, 4),
               "replicas": len(fleet.replicas)}
        rec.update(signals)
        self.decisions.append(rec)
        self._last_action = action
        fleet.recorder.record(rec)
        if fleet.tracer.enabled:
            fleet.tracer.instant(f"elastic_{action}", **{
                k: v for k, v in rec.items()
                if isinstance(v, (int, float, str))})

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The pinned ``stats()["elastic"]`` block body."""
        now = self.clock()
        cfg = self.cfg
        return {
            "enabled": True,
            "replicas": len(self.fleet.replicas),
            "retired": len(self.fleet.retired_replicas),
            "min_replicas": cfg.min_replicas,
            "max_replicas": cfg.max_replicas,
            "pressure_avg": round(self._pressure_avg, 4),
            "debt_delta": int(self._debt_delta),
            "score": round(self._score, 4),
            "band": {"up": cfg.up_pressure,
                     "down": cfg.down_pressure},
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "retiring": (self.retiring.name
                         if self.retiring is not None else None),
            "cooldown": {
                "up_ready": self._ready(self._last_up_t,
                                        cfg.up_cooldown_s, now),
                "down_ready": self._ready(self._last_down_t,
                                          cfg.down_cooldown_s, now),
            },
            "last_action": self._last_action,
            "decisions": list(self.decisions),
        }
