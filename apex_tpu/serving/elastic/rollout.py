"""Zero-downtime weight rollout — a published checkpoint, one replica
at a time, behind a parity gate.

Params are an ARGUMENT to every compiled serving program, never a
captured constant (``DecodeEngine.swap_params``), so swapping a
replica's weights recompiles NOTHING — the only thing a rollout has
to manage is WHEN each replica switches and what happens to state
computed under the old weights.  The procedure per replica:

1. **Parity gate** (before anything is drained): the probe prompts
   replay on two STANDALONE servers — one holding the fleet's
   current params, one holding the restored checkpoint — and their
   outputs must match bit-for-bit.  The gate encodes what
   "zero-downtime rollout" is for: output-equivalent re-publishes
   (requantized, defragmented, re-exported weights).  A checkpoint
   that CHANGES behavior must not silently mix versions inside one
   fleet mid-traffic — it fails the gate, the rollout halts, and any
   already-swapped replica rolls back, so the fleet always converges
   to ONE version.  Probe servers are standalone on purpose: probes
   through live replicas would pollute the fleet's finished ledgers
   and break the soak's exactly-once accounting.
2. **Drain**: the replica stops placing, queued work moves to the
   survivors (the existing rolling-drain actuator), and the fleet
   steps until the replica runs dry — in-flight requests ALWAYS
   finish under the weights they started with.
3. **Swap + purge**: ``engine.swap_params`` (both pools under
   disaggregation), then the replica's prefix cache is evicted and
   cleared — cached KV was computed under the old weights and must
   never serve a post-swap request.
4. **Verify + revive**: the swapped tree's per-leaf checksums are
   compared against the checkpoint manifest
   (``utils.checkpoint.tree_checksums``) — a torn swap is caught
   before the replica takes traffic — then ``revive()`` returns it
   to the rotation stamped with the new ``weights_version``.

Any failure (parity mismatch, drain that will not converge, checksum
mismatch) rolls the already-swapped replicas BACK through the same
drain/swap/revive cycle, so partial rollouts are impossible to
observe from outside: the fleet ends on exactly one version either
way, and the report says which.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from apex_tpu.utils import checkpoint as ckpt

__all__ = ["rollout_fleet"]

_PROBE_TOKENS = 8
_STEP_BUDGET = 512


def _default_probes(cfg) -> List[List[int]]:
    """Two deterministic probe prompts drawn from the model's vocab
    (no RNG — the same fleet always probes the same prompts)."""
    vocab = int(getattr(cfg, "vocab_size", 61))
    return [[(3 + 7 * i + j) % vocab for j in range(6)]
            for i in range(2)]


def _probe_outputs(server, prompts: Sequence[Sequence[int]],
                   tokens: int) -> List[List[int]]:
    return server.generate(prompts, max_new_tokens=tokens)


def _swap_replica(fleet, rep, params, version: Optional[str],
                  step_budget: int) -> bool:
    """Drain -> swap -> purge -> revive for one replica.  Returns
    False when the drain did not converge within ``step_budget``
    fleet steps (the replica is revived UNSWAPPED in that case)."""
    fleet.drain_replica(rep)
    for _ in range(step_budget):
        if fleet.replica_drained(rep):
            break
        fleet.step()
    else:
        fleet.revive(rep)       # un-drain; still on its old weights
        return False
    srv = rep.server
    srv.engine.swap_params(params)
    if srv.prefill_engine is not None:
        srv.prefill_engine.swap_params(params)
    pc = srv.prefix_cache
    if pc is not None:
        # every cached block was computed under the OLD weights;
        # drained means they are all ref-0 evictable holds
        pc.evict(pc.num_evictable)
        pc.clear()
        # router-side affinity entries now point at a cold cache
        fleet.router.affinity.drop_replica(rep.index)
    fleet.revive(rep)
    rep.weights_version = version
    return True


def rollout_fleet(fleet, checkpoint_dir: str, *,
                  probe_prompts: Optional[Sequence[Sequence[int]]]
                  = None,
                  probe_tokens: int = _PROBE_TOKENS,
                  step_budget: int = _STEP_BUDGET) -> dict:
    """Roll the newest checkpoint under ``checkpoint_dir`` across
    ``fleet`` (module docstring).  Returns a report dict — never
    raises for an unhealthy rollout; ``status`` says what happened:

    - ``"ok"``: every replica serves the new version.
    - ``"no_checkpoint"``: nothing restorable under the directory.
    - ``"unavailable"``: the fleet is draining or closed.
    - ``"parity_mismatch"`` / ``"drain_stuck"`` /
      ``"swap_corrupt"``: the rollout halted and rolled back; every
      replica serves the OLD version.
    """
    if fleet.draining or fleet.closed:
        return {"status": "unavailable", "step": None,
                "version": None, "replicas_rolled": 0,
                "rolled_back": 0, "detail": "fleet draining/closed"}
    mgr = ckpt.CheckpointManager(checkpoint_dir)
    res = mgr.restore_latest(target=fleet.params)
    if res is None:
        return {"status": "no_checkpoint", "step": None,
                "version": None, "replicas_rolled": 0,
                "rolled_back": 0,
                "detail": f"no restorable checkpoint in "
                          f"{checkpoint_dir}"}
    new_params, step = res
    version = f"step_{int(step)}"
    want_sums = mgr.read_manifest(step)["leaf_checksums"]
    old_params = fleet.params
    prompts = (list(probe_prompts) if probe_prompts is not None
               else _default_probes(fleet.cfg))

    # standalone A/B probe pair — compiled once, replayed before each
    # replica's promotion.  The autoscaler stands down while the
    # rollout owns the replica list (one lifecycle driver at a time).
    fleet._rollout_active = True
    report = {"status": "ok", "step": int(step), "version": version,
              "probes": len(prompts), "replicas_rolled": 0,
              "rolled_back": 0, "detail": ""}
    swapped = []
    prev_version = {rep.name: rep.weights_version
                    for rep in fleet.replicas}
    try:
        old_srv = fleet._probe_server(old_params)
        new_srv = fleet._probe_server(new_params)
        try:
            for rep in list(fleet.replicas):
                old_out = _probe_outputs(old_srv, prompts,
                                         probe_tokens)
                new_out = _probe_outputs(new_srv, prompts,
                                         probe_tokens)
                if old_out != new_out:
                    report["status"] = "parity_mismatch"
                    report["detail"] = (
                        f"probe outputs diverged before promoting "
                        f"{rep.name}; halting")
                    break
                if not _swap_replica(fleet, rep, new_params,
                                     version, step_budget):
                    report["status"] = "drain_stuck"
                    report["detail"] = (
                        f"{rep.name} did not drain within "
                        f"{step_budget} steps")
                    break
                got = ckpt.tree_checksums(rep.server.engine.params)
                if got != want_sums:
                    report["status"] = "swap_corrupt"
                    report["detail"] = (
                        f"{rep.name} post-swap checksums do not "
                        f"match the step {step} manifest")
                    break
                swapped.append(rep)
                report["replicas_rolled"] += 1
                _note(fleet, "rollout_replica", replica=rep.name,
                      version=version)
        finally:
            old_srv.close()
            new_srv.close()

        if report["status"] != "ok":
            # converge DOWN to the old version: re-swap everything
            # that already promoted (same drain discipline — no
            # in-flight request ever crosses a version boundary)
            for rep in swapped:
                _swap_replica(fleet, rep, old_params,
                              prev_version[rep.name], step_budget)
                report["rolled_back"] += 1
        else:
            # future scale-ups must build on the NEW weights, or the
            # fleet would fork versions at the next flash crowd
            fleet.params = new_params
            fleet._weights_version = version
    finally:
        fleet._rollout_active = False
    fleet._last_rollout = {"status": report["status"],
                           "version": report["version"],
                           "replicas_rolled": report["replicas_rolled"],
                           "rolled_back": report["rolled_back"]}
    _note(fleet, "rollout_done", status=report["status"],
          version=report["version"] or "",
          rolled=report["replicas_rolled"])
    return report


def _note(fleet, name: str, **fields) -> None:
    rec = {"kind": "elastic", "action": name,
           "iter": fleet._iter}
    rec.update(fields)
    fleet.recorder.record(rec)
    if fleet.tracer.enabled:
        fleet.tracer.instant(name, **fields)
