"""Elastic fleet — the control loop over ``RouterFleet``'s actuators.

The router subsystem already carries every SIGNAL an operator would
scale on (per-replica pressure with prefill backlog, per-priority SLO
attainment and shed debt, breaker snapshots) and every ACTUATOR a
scale action needs (``drain_replica()``/``revive()`` rolling drain,
checksummed cross-pool block transfer, ``CheckpointManager`` atomic
publish/restore) — this package closes the loop between them:

- :class:`Autoscaler` (``autoscaler.py``): a deterministic,
  injectable-clock controller stepped once per fleet iteration.
  Pressure + SLO-debt trend against a hysteresis band decide
  scale-up (new replica from the fleet's factory, prefix cache
  warmed from a donor over the checksummed block path) and
  scale-down (rolling drain, then retire); cooldowns keep it from
  flapping, and every decision lands in the pinned
  ``stats()["elastic"]`` block + the flight recorder.
- zero-downtime weight rollout (``rollout.py``):
  ``fleet.rollout(checkpoint_dir)`` rolls a published checkpoint
  replica-by-replica through drain -> in-place param swap ->
  revive, gated per replica by an A/B output-parity audit on probe
  prompts; a failed gate halts and rolls back, so a partial rollout
  always converges to ONE weight version.

``docs/serving.md`` ("Elastic fleet") has the control-loop diagram,
the knob tables, and the when-NOT-to-autoscale discussion.
"""

from apex_tpu.serving.elastic.autoscaler import Autoscaler, AutoscalerConfig
from apex_tpu.serving.elastic.rollout import rollout_fleet

__all__ = ["Autoscaler", "AutoscalerConfig", "rollout_fleet"]
