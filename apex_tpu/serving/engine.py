"""Jit-compiled prefill + single-token decode steps over the KV cache.

Compiled programs, all fixed-shape so the continuous-batching loop
never recompiles in steady state:

- **prefill** (one request, prompt padded to a length *bucket*): the
  ordinary causal GPT forward — optionally through the flash kernel
  via ``attention_fn`` — with ``return_kv=True``; the per-layer K/V
  are scattered into the request's blocks in the same program.  One
  trace per bucket length, so the compile count is bounded by
  ``len(prefill_buckets)``, not by the distribution of prompt lengths.
- **chunk prefill** (one request, one fixed-width chunk at a carried
  KV position): the chunked-prefill and prefix-cached-tail workhorse —
  the chunk attends the request's ALREADY-CACHED context through its
  block table (gather + ``ops.chunk_cached_attention``) plus itself
  causally, and its K/V scatter at block-offset slots.  A fixed chunk
  size means ONE trace however long prompts get.
- **decode** (the whole running batch, always ``max_batch_size``
  wide): gather every slot's context through its block table, run the
  model on one token per slot at its own position
  (``ops.cached_attention`` inside), scatter the new K/V, return
  next-token logits.  Compiled exactly once.
- **verify** (the whole batch, ``max_batch_size`` x a fixed token
  width): the speculative-decoding scoring step — every slot feeds its
  pending token plus its drafted guesses at carried positions, attends
  its cached context through its block table plus itself causally
  (``ops.chunk_cached_attention``, the same program shape as chunk
  prefill but batched and returning EVERY row's logits), and scatters
  all fed tokens' K/V.  Greedy acceptance happens on the host
  (``serving.api``); rejected suffix positions hold garbage K/V that
  sits beyond the accepted length — masked by the context bias and
  overwritten before the request ever advances past it.  One trace per
  verify width, so a fixed speculation depth compiles exactly once.
- **block copy** (fixed-width (src, dst) id batch): whole-block
  duplication inside the pool — the device half of the prefix cache's
  copy-on-write.  Compiled exactly once.
- **sampled variants** (``prefill_sampled`` / ``chunk_prefill_sampled``
  / ``decode_sampled`` / ``verify_sampled``): the same programs with
  greedy argmax and the non-finite row guard fused in
  (:func:`ops.greedy_argmax` / :func:`ops.finite_rows`), returning
  token ids + per-row finite flags instead of logits.  The per-step
  device→host transfer shrinks from a ``(B, V)`` float block to a
  ``(B,)`` int32 vector, and — because the host never has to
  materialize logits to sample — the pipelined serve loop
  (``serving.api``, ``enable_pipeline``) can leave the returned arrays
  as futures and let JAX async dispatch run the device a full
  iteration ahead of host scheduling.  Bit-exact against the host
  path by construction: ``jnp.argmax`` and ``np.argmax`` share the
  lowest-index tie rule (pinned by ``tests/L0/test_pipeline.py``).

Empty slots ride along as no-ops by construction: position 0 masks
the whole context, the zeroed block table routes the KV write into
the reserved garbage block, and the caller ignores their logits.

The cache pytree is donated through both steps — on TPU the pool is
the HBM hog and must be updated in place, not double-buffered.  (XLA
on CPU ignores donation; the warning is filtered.)
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.observability import NULL_PROGRAM_ACCOUNTING, NULL_TRACER
from apex_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from apex_tpu.ops.sampling import finite_rows, greedy_argmax, sample_tokens
from apex_tpu.ops.vocab_parallel import (
    vocab_parallel_sample,
    vocab_parallel_sample_tokens,
)
from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    context_bias,
    copy_blocks,
    copy_blocks_across,
    gather_context,
    gather_scales,
    init_kv_cache,
    resolve_kv_quant,
    slot_index,
    write_prefill,
    write_tokens,
)

# CPU backends can't honor donation; the fallback copy is exactly the
# pre-donation behavior, so the warning is noise off-TPU
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def default_prefill_buckets(max_context: int,
                            smallest: int = 16) -> Tuple[int, ...]:
    """Power-of-two bucket ladder capped at ``max_context`` — each
    prompt pads to the next rung, so at most ``log2`` distinct prefill
    shapes ever compile and no prompt pads to more than 2x its
    length."""
    buckets = []
    b = smallest
    while b < max_context:
        buckets.append(b)
        b *= 2
    buckets.append(max_context)
    return tuple(buckets)


def pick_bucket(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``length`` (buckets ascending); raises past
    the largest — one definition shared by ``DecodeEngine.bucket_for``
    and its edge-case tests."""
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(
        f"length {length} exceeds the largest bucket {buckets[-1]}")

# padded width of one copy_blocks launch: COW duplicates arrive one or
# two at a time, so a single fixed shape keeps the program count at 1
_COPY_WIDTH = 8


class DecodeEngine:
    """The device half of the serving stack: owns the cache pool, the
    compiled prefill/decode programs, and nothing else — admission,
    batching composition, and termination live in
    ``serving.scheduler``/``serving.api``.

    Args:
      cfg: the GPT architecture (params must match).
      params: the model's ``{"params": ...}["params"]`` pytree (pass
        amp-cast params to serve in half).
      max_batch_size: decode batch width (running-request slots).
      max_context: per-request token capacity; default
        ``cfg.max_position_embeddings``.
      num_blocks: physical blocks in the pool (incl. the reserved
        garbage block 0); default sizes the pool for
        ``max_batch_size`` full-context requests plus slack.
      block_size: tokens per block.
      cache_dtype: KV COMPUTE dtype; None = amp policy
        (:func:`serving.kv_cache.resolve_cache_dtype`).
      kv_quant: ``"int8"`` stores the pool quantized — int8 payload
        plus a per-slot per-head fp32 scale sidecar sharded with its
        heads — with quantization fused into every write program and
        dequantization fused into every read (``docs/serving.md``,
        "Quantized KV cache").  ``cache_dtype`` keeps naming the
        compute dtype the values widen to.  Default ``None`` (the
        historical full-width pool, byte-identical programs).
      attention_fn: optional fused attention for the PREFILL pass
        (``make_flash_attention(causal=True)`` on TPU); decode always
        takes the ``ops.cached_attention`` path.
      prefill_buckets: ascending prompt-length buckets; None =
        :func:`default_prefill_buckets`.
      tracer: optional :class:`apex_tpu.observability.SpanTracer`;
        when enabled, every first-compile of a prefill/chunk/decode/
        copy program emits a ``compile`` instant event (recompiles in
        steady state are exactly what the trace is for catching).
      programs: optional
        :class:`apex_tpu.observability.ProgramAccounting` — every
        host-API launch is tallied per program key
        (``prefill[<bucket>]`` / ``chunk_prefill[<width>]`` /
        ``decode`` / ``verify[<width>]`` / sampled twins /
        ``copy_blocks``): call count, host wall time, compile count,
        compile time.  Default: the zero-overhead disabled instance
        (``InferenceServer`` passes a registry-backed one).
      mesh: optional :class:`jax.sharding.Mesh` — tensor-parallel
        serving (``docs/serving.md``, "Tensor-parallel serving").
        Params place per ``tp_rules`` (Megatron column/row split), the
        KV pool shards its HEADS dim over ``tp_axis`` (each device
        holds ``num_heads/tp`` heads of EVERY block, so block tables,
        the allocator, and the whole scheduler stay replicated
        host-side state), and all compiled programs lower through
        GSPMD with sharded in/out placements — XLA inserts the
        attention all-reduce and the lm-head all-gather; the sampled
        twins take the fused :func:`ops.vocab_parallel_sample` path
        (per-shard argmax, one (B,)-shaped cross-shard reduction)
        instead of ever gathering logits.  Greedy token streams are
        bit-exact vs the unsharded engine
        (``tests/L0/test_serving_tp.py``).
      tp_rules: the ``(regex, PartitionSpec)`` param-sharding rules
        for ``mesh`` (default :func:`parallel.gpt_tp_rules` on
        ``tp_axis``).
      tp_axis: the mesh axis tensor parallelism shards over
        (default ``"model"``).
    """

    def __init__(self, cfg: GPTConfig, params, *,
                 max_batch_size: int = 8,
                 max_context: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 block_size: int = 16,
                 cache_dtype=None,
                 kv_quant: Optional[str] = None,
                 attention_fn=None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 tracer=None,
                 programs=None,
                 mesh=None,
                 tp_rules=None,
                 tp_axis: str = "model"):
        self.cfg = cfg
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.programs = (programs if programs is not None
                         else NULL_PROGRAM_ACCOUNTING)
        self.mesh = mesh
        self.tp_axis = tp_axis if mesh is not None else None
        self.tp = 1
        self.kv_quant = resolve_kv_quant(kv_quant)
        self.quantized = self.kv_quant is not None
        self._repl = None         # replicated placement for launch args
        self._pool_shard = None   # the pool's head-sharded placement
        self._scale_shard = None  # the scale sidecar's (heads last)
        if mesh is not None:
            if tp_axis not in mesh.shape:
                raise ValueError(
                    f"tp_axis {tp_axis!r} is not an axis of the mesh "
                    f"(axes: {tuple(mesh.shape)})")
            self.tp = int(mesh.shape[tp_axis])
            if cfg.num_attention_heads % self.tp:
                raise ValueError(
                    f"num_attention_heads={cfg.num_attention_heads} "
                    f"must divide the {tp_axis!r} axis ({self.tp}) — "
                    "the KV pool shards its heads dim, so every "
                    "device must hold a whole number of heads")
            from apex_tpu.parallel.tensor_parallel import (
                gpt_tp_rules,
                shard_params,
            )
            if tp_rules is None:
                tp_rules = gpt_tp_rules(tp_axis)
            params = shard_params(params, mesh, tp_rules)
            self._tp_rules = tp_rules
            self._repl = NamedSharding(mesh, P())
            self._pool_shard = NamedSharding(
                mesh, P(None, None, tp_axis, None))
            # scale sidecar (L, num_slots, H): heads are its LAST
            # dim, so it shards alongside the heads it dequantizes
            self._scale_shard = NamedSharding(
                mesh, P(None, None, tp_axis))
        self.params = params
        self.max_batch_size = int(max_batch_size)
        self.max_context = int(max_context
                               or cfg.max_position_embeddings)
        if self.max_context > cfg.max_position_embeddings:
            raise ValueError(
                f"max_context={self.max_context} exceeds the model's "
                f"max_position_embeddings={cfg.max_position_embeddings}")
        self.block_size = int(block_size)
        self.blocks_per_seq = -(-self.max_context // self.block_size)
        if num_blocks is None:
            # every slot can hold a full-context request, +1 garbage
            num_blocks = self.max_batch_size * self.blocks_per_seq + 1
        self.cache_cfg = KVCacheConfig(
            num_layers=cfg.num_hidden_layers,
            num_heads=cfg.num_attention_heads,
            head_dim=cfg.hidden_size // cfg.num_attention_heads,
            num_blocks=int(num_blocks),
            block_size=self.block_size,
            dtype=cache_dtype,
            quantize=self.kv_quant)
        self.allocator = BlockAllocator(self.cache_cfg)
        self.cache = init_kv_cache(self.cache_cfg,
                                   sharding=self._pool_shard,
                                   scale_sharding=self._scale_shard)
        self.model = GPTLMHeadModel(cfg, attention_fn=attention_fn)
        if prefill_buckets is None:
            prefill_buckets = default_prefill_buckets(self.max_context)
        self.prefill_buckets = tuple(sorted(int(b)
                                            for b in prefill_buckets))
        if self.prefill_buckets[-1] < self.max_context:
            raise ValueError(
                f"largest prefill bucket {self.prefill_buckets[-1]} "
                f"< max_context {self.max_context}")

        # under a mesh every program pins its output placements so
        # GSPMD keeps the (donated) pool head-sharded and replicates
        # exactly what the host consumes (logits / token ids / flags);
        # without one the jits are byte-identical to the single-chip
        # engine
        def _jit(fn, donate, outs):
            if self.mesh is None:
                return jax.jit(fn, donate_argnums=donate)
            return jax.jit(fn, donate_argnums=donate,
                           out_shardings=outs)

        cache_sh = None
        if self.mesh is not None:
            cache_sh = {"k": self._pool_shard, "v": self._pool_shard}
            if self.quantized:
                cache_sh["k_scale"] = self._scale_shard
                cache_sh["v_scale"] = self._scale_shard
        repl = self._repl
        self._prefill_jit = _jit(self._prefill_impl, (1,),
                                 (cache_sh, repl))
        self._decode_jit = _jit(self._decode_impl, (1,),
                                (cache_sh, repl))
        self._chunk_jit = _jit(self._chunk_impl, (1,),
                               (cache_sh, repl))
        self._verify_jit = _jit(self._verify_impl, (1,),
                                (cache_sh, repl))
        self._copy_jit = _jit(self._copy_impl, (0,), cache_sh)
        # the cross-pool hand-off programs (docs/serving.md,
        # "Disaggregated prefill/decode").  Donation policy mirrors
        # the sampled twins: the hand-off copy sits in the decode
        # pool's step path, and a donated call executes synchronously
        # on the CPU backend (BENCH_NOTES r8) — which would stall the
        # very decode launch disaggregation exists to protect.
        xfer_donate = (0,) if jax.default_backend() != "cpu" else ()
        self._xfer_jit = _jit(self._xfer_impl, xfer_donate, cache_sh)
        self._import_jit = _jit(self._import_impl, xfer_donate,
                                cache_sh)
        # the fused on-device-sampling twins (docs/serving.md,
        # "Pipelined serve loop"): same bodies + argmax/finite-guard,
        # so a greedy server transfers token ids, never logits.
        # Donation policy differs from the logits programs: on TPU the
        # pool is the HBM hog and must be updated in place, but on the
        # CPU backend a donated call executes SYNCHRONOUSLY — which
        # would serialize host and device again and defeat the
        # pipelined loop's dispatch-ahead.  CPU pools are test-scale,
        # so trading the (already-copied-anyway) in-place update for
        # an async launch is the right side of the bargain there.
        sampled_cache = (1,) if jax.default_backend() != "cpu" else ()
        self._prefill_sampled_jit = _jit(self._prefill_sampled_impl,
                                         sampled_cache,
                                         (cache_sh, repl, repl))
        self._chunk_sampled_jit = _jit(self._chunk_sampled_impl,
                                       sampled_cache,
                                       (cache_sh, repl, repl))
        self._decode_sampled_jit = _jit(self._decode_sampled_impl,
                                        sampled_cache,
                                        (cache_sh, repl, repl))
        self._verify_sampled_jit = _jit(self._verify_sampled_impl,
                                        sampled_cache,
                                        (cache_sh, repl, repl))
        # the STOCHASTIC twins (docs/serving.md, "Stochastic
        # sampling"): the same bodies + in-trace temperature/top-k/
        # top-p sampling with per-request counter-based keys
        # (ops.sample_tokens; the vocab-parallel no-gather path under
        # a mesh).  Distinct traces from the greedy twins on purpose:
        # an all-greedy step keeps launching the argmax-only program
        # — zero sort/noise cost for the default traffic — and the
        # stochastic program only compiles once the first stochastic
        # request is actually batched.  Greedy rows INSIDE a
        # stochastic launch still take the bit-exact argmax lane.
        self._prefill_stoch_jit = _jit(self._prefill_stoch_impl,
                                       sampled_cache,
                                       (cache_sh, repl, repl))
        self._chunk_stoch_jit = _jit(self._chunk_stoch_impl,
                                     sampled_cache,
                                     (cache_sh, repl, repl))
        self._decode_stoch_jit = _jit(self._decode_stoch_impl,
                                      sampled_cache,
                                      (cache_sh, repl, repl))
        self._verify_stoch_jit = _jit(self._verify_stoch_impl,
                                      sampled_cache,
                                      (cache_sh, repl, repl))

    # -- compiled bodies --------------------------------------------------

    def _cache_views(self, cache, tables, bias):
        """The model's ``cache_views`` struct for one gathered
        context: (k, v, bias) plain, plus the per-layer scale sidecar
        legs under quantization (int8 payload + fp32 scales — the
        attention ops widen at read)."""
        k_ctx, v_ctx = gather_context(cache, tables, self.block_size)
        if not self.quantized:
            return (k_ctx, v_ctx, bias)
        ks_ctx, vs_ctx = gather_scales(cache, tables, self.block_size)
        return (k_ctx, v_ctx, bias, ks_ctx, vs_ctx)

    def _stack_kvs(self, kvs):
        """Stack the model's per-layer fresh K/V into the scatter
        layout ``write_prefill``/``write_tokens`` expect: plain
        (k, v) arrays, or the quantized
        ``((k_q, k_scale), (v_q, v_scale))`` quadruple."""
        if self.quantized:
            return ((jnp.stack([kv[0][0] for kv in kvs]),
                     jnp.stack([kv[0][1] for kv in kvs])),
                    (jnp.stack([kv[1][0] for kv in kvs]),
                     jnp.stack([kv[1][1] for kv in kvs])))
        return (jnp.stack([kv[0] for kv in kvs]),
                jnp.stack([kv[1] for kv in kvs]))

    def _prefill_impl(self, params, cache, ids, length, table):
        """ids (1, Sb) zero-padded prompt; length (1,) true length;
        table (1, blocks_per_seq).  Returns (cache, last-token logits
        (1, V))."""
        sb = ids.shape[1]
        pos = jnp.arange(sb, dtype=jnp.int32)[None, :]
        mask = (pos < length[:, None]).astype(jnp.int32)
        logits, kvs = self.model.apply(
            {"params": params}, ids, attention_mask=mask,
            deterministic=True, return_kv=True,
            kv_quant=self.quantized)
        kv_new = self._stack_kvs(kvs)                 # (L, 1, Sb, H, D)
        # padded positions scatter into the garbage block (slot 0)
        slots = jnp.where(mask > 0,
                          slot_index(table, pos, self.block_size), 0)
        cache = write_prefill(cache, kv_new, slots)
        last = jnp.take_along_axis(
            logits, (length[:, None, None] - 1).astype(jnp.int32),
            axis=1)[:, 0]                             # (1, V)
        return cache, last

    def _chunk_impl(self, params, cache, ids, start, length, table):
        """One prefill CHUNK at a carried KV position: ids (1, Cb)
        zero-padded chunk tokens; start (1,) absolute position of
        ``ids[0]`` (== tokens already materialized through ``table``);
        length (1,) valid tokens in the chunk; table (1,
        blocks_per_seq).  Gathers the request's full cached context,
        runs the chunk through the model's chunked ``cache_views``
        path (context masked to slots < start, causal within the
        chunk), scatters the chunk's K/V at its block-offset slots,
        and returns (cache, last-valid-token logits (1, V)) — the
        logits only matter on the final chunk."""
        cb = ids.shape[1]
        off = jnp.arange(cb, dtype=jnp.int32)[None, :]
        pos = start[:, None].astype(jnp.int32) + off       # (1, Cb)
        t_ctx = self.blocks_per_seq * self.block_size
        bias = context_bias(start, t_ctx)                  # slots < start
        views = self._cache_views(cache, table, bias)
        # padded tail positions can run past the embedding table; clamp
        # them (their logits and K/V writes are discarded/garbage-sunk)
        pos_emb = jnp.minimum(pos, self.cfg.max_position_embeddings - 1)
        logits, kvs = self.model.apply(
            {"params": params}, ids, positions=pos_emb,
            deterministic=True, cache_views=views,
            return_kv=True, kv_quant=self.quantized)
        kv_new = self._stack_kvs(kvs)                      # (L, 1, Cb, H, D)
        valid = off < length[:, None]
        slots = jnp.where(valid,
                          slot_index(table, pos, self.block_size), 0)
        cache = write_prefill(cache, kv_new, slots)
        last = jnp.take_along_axis(
            logits, (length[:, None, None] - 1).astype(jnp.int32),
            axis=1)[:, 0]                                  # (1, V)
        return cache, last

    def _verify_impl(self, params, cache, ids, start, length, tables):
        """The speculative verify step: ids (B, K) — each slot's
        pending token followed by its drafted guesses, zero-padded;
        start (B,) absolute position of ``ids[:, 0]`` (== tokens
        already materialized through that slot's table); length (B,)
        valid tokens per slot (0 = idle slot); tables (B,
        blocks_per_seq).

        Each slot's K tokens attend its full cached context (masked to
        slots < start) plus themselves causally — the batched
        generalization of ``_chunk_impl`` — and their K/V scatter at
        block-offset slots (invalid columns sink into the garbage
        block).  Returns (cache, logits (B, K, V)): EVERY row's
        logits, because greedy acceptance needs the model's argmax at
        each drafted position, not just the last."""
        kw = ids.shape[1]
        off = jnp.arange(kw, dtype=jnp.int32)[None, :]
        pos = start[:, None].astype(jnp.int32) + off       # (B, K)
        t_ctx = self.blocks_per_seq * self.block_size
        bias = context_bias(start, t_ctx)                  # slots < start
        views = self._cache_views(cache, tables, bias)
        # padded columns can run past the embedding table; clamp (their
        # logits are ignored and their K/V writes garbage-sunk)
        pos_emb = jnp.minimum(pos, self.cfg.max_position_embeddings - 1)
        logits, kvs = self.model.apply(
            {"params": params}, ids, positions=pos_emb,
            deterministic=True, cache_views=views,
            return_kv=True, kv_quant=self.quantized)
        kv_new = self._stack_kvs(kvs)                      # (L, B, K, H, D)
        valid = off < length[:, None]
        slots = jnp.where(valid,
                          slot_index(tables, pos, self.block_size), 0)
        cache = write_prefill(cache, kv_new, slots)
        return cache, logits                               # (B, K, V)

    def _copy_impl(self, cache, src, dst):
        """(_COPY_WIDTH,) src/dst block ids, (0, 0)-padded — the COW
        block duplication (``kv_cache.copy_blocks``)."""
        return copy_blocks(cache, src, dst, self.block_size)

    def _xfer_impl(self, dst_cache, src_cache, src, dst):
        """(_COPY_WIDTH,) src/dst block ids, (0, 0)-padded — the
        CROSS-POOL hand-off copy (``kv_cache.copy_blocks_across``):
        ``src`` indexes another engine's pool of identical geometry,
        ``dst`` this one's."""
        return copy_blocks_across(dst_cache, src_cache, src, dst,
                                  self.block_size)

    def _import_impl(self, cache, slots, leaves):
        """Scatter a host-shipped block payload into the pool:
        ``slots`` (W * block_size,) flat slot indices (padding rows
        point at the garbage block), ``leaves`` a dict matching the
        cache's leaf names with per-slot rows along axis 1."""
        return {name: arr.at[:, slots].set(leaves[name])
                for name, arr in cache.items()}

    def _decode_impl(self, params, cache, tokens, positions, tables):
        """tokens (B,) current input token per slot; positions (B,)
        its position (== cached context length); tables (B,
        blocks_per_seq).  Returns (cache, logits (B, V))."""
        t_ctx = self.blocks_per_seq * self.block_size
        bias = context_bias(positions, t_ctx)
        views = self._cache_views(cache, tables, bias)
        logits, kvs = self.model.apply(
            {"params": params}, tokens[:, None],
            positions=positions[:, None].astype(jnp.int32),
            deterministic=True,
            cache_views=views, return_kv=True,
            kv_quant=self.quantized)
        kv_new = self._stack_kvs(kvs)                 # (L, B, 1, H, D)
        slots = slot_index(tables, positions, self.block_size)
        cache = write_tokens(cache, kv_new, slots)
        return cache, logits[:, 0]                    # (B, V)

    # -- fused on-device-sampling bodies ----------------------------------
    # Each composes its logits twin with greedy argmax + the finite-row
    # guard INSIDE the trace, so the (B, V) logits block never leaves
    # the device — only (B,) int32 ids and (B,) bool flags transfer,
    # and only when the caller eventually materializes them.

    def _sample(self, logits):
        """The fused argmax + finite guard: plain on one chip; under a
        mesh the :func:`ops.vocab_parallel_sample` path — per-shard
        argmax over the lm-head's OWN vocab slice and one (B,)-shaped
        cross-shard reduction (documented lowest-global-id tie rule),
        so the vocab-sharded logits are never all-gathered just to be
        argmaxed."""
        if self.mesh is not None:
            return vocab_parallel_sample(logits, self.mesh,
                                         self.tp_axis)
        return greedy_argmax(logits), finite_rows(logits)

    def _prefill_sampled_impl(self, params, cache, ids, length, table):
        cache, last = self._prefill_impl(params, cache, ids, length,
                                         table)
        return (cache,) + self._sample(last)                   # (1,)

    def _chunk_sampled_impl(self, params, cache, ids, start, length,
                            table):
        cache, last = self._chunk_impl(params, cache, ids, start,
                                       length, table)
        return (cache,) + self._sample(last)                   # (1,)

    def _decode_sampled_impl(self, params, cache, tokens, positions,
                             tables):
        cache, logits = self._decode_impl(params, cache, tokens,
                                          positions, tables)
        return (cache,) + self._sample(logits)                 # (B,)

    def _verify_sampled_impl(self, params, cache, ids, start, length,
                             tables):
        cache, logits = self._verify_impl(params, cache, ids, start,
                                          length, tables)
        return (cache,) + self._sample(logits)                 # (B, K)

    # -- stochastic twins (docs/serving.md, "Stochastic sampling") ---------
    # Same bodies, but the fused sampler is ops.sample_tokens with the
    # per-slot SamplingParams arrays and the COUNTER position of each
    # sampled token (the sequence index of the token being drawn —
    # what makes replay/preemption/speculation deterministic).  Rows
    # whose temperature is 0 (greedy requests, idle slots) take the
    # bit-exact argmax lane inside the same trace.

    def _sample_stoch(self, logits, counters, temp, tk, tp_, seed):
        """The fused stochastic sampler: plain
        :func:`ops.sample_tokens` on one chip; the no-gather
        :func:`ops.vocab_parallel_sample_tokens` under a mesh, so the
        vocab-sharded logits are never gathered for stochastic
        traffic either."""
        b = logits.shape[:-1]
        extra = logits.ndim - 1 - temp.ndim     # 1 on verify's (B, K)

        def bc(x):
            return jnp.broadcast_to(x.reshape(x.shape + (1,) * extra),
                                    b)

        args = (bc(temp), bc(tk), bc(tp_), bc(seed))
        if self.mesh is not None:
            return vocab_parallel_sample_tokens(
                logits, *args, counters, self.mesh, self.tp_axis)
        return sample_tokens(logits, *args, counters)

    def _prefill_stoch_impl(self, params, cache, ids, length, table,
                            temp, tk, tp_, seed):
        cache, last = self._prefill_impl(params, cache, ids, length,
                                         table)
        # the prefill-sampled token's sequence index == prompt length
        ids_out, fin = self._sample_stoch(last, length, temp, tk,
                                          tp_, seed)
        return cache, ids_out, fin                             # (1,)

    def _chunk_stoch_impl(self, params, cache, ids, start, length,
                          table, temp, tk, tp_, seed):
        cache, last = self._chunk_impl(params, cache, ids, start,
                                       length, table)
        # final chunk: start + length == the full context length
        ids_out, fin = self._sample_stoch(last, start + length, temp,
                                          tk, tp_, seed)
        return cache, ids_out, fin                             # (1,)

    def _decode_stoch_impl(self, params, cache, tokens, positions,
                           tables, temp, tk, tp_, seed):
        cache, logits = self._decode_impl(params, cache, tokens,
                                          positions, tables)
        # the input token sits at `positions`; the drawn token is the
        # next sequence index
        ids_out, fin = self._sample_stoch(logits, positions + 1, temp,
                                          tk, tp_, seed)
        return cache, ids_out, fin                             # (B,)

    def _verify_stoch_impl(self, params, cache, ids, start, length,
                           tables, temp, tk, tp_, seed):
        cache, logits = self._verify_impl(params, cache, ids, start,
                                          length, tables)
        # column j's logits predict the token at index start + j + 1;
        # sampling EVERY column with its own positional key is the
        # whole speculation story: the host accepts a draft iff it
        # equals the column's sample (the Gumbel-max coupling of
        # ops.sample_tokens — rejection sampling's exact accept/
        # residual probabilities, with a draft-independent stream)
        kw = ids.shape[1]
        counters = (start[:, None].astype(jnp.int32) + 1
                    + jnp.arange(kw, dtype=jnp.int32)[None, :])
        ids_out, fin = self._sample_stoch(logits, counters, temp, tk,
                                          tp_, seed)
        return cache, ids_out, fin                             # (B, K)

    # -- host API ---------------------------------------------------------

    def _mark(self, jit_fn):
        """Pre-call ``(t0, trace count)`` for the tracer's compile
        instants and the per-program accounting — ``(0.0, 0)`` when
        both are off, so the disabled path skips even the clock
        read."""
        acct = self.programs.enabled
        if not acct and not self.tracer.enabled:
            return 0.0, 0
        return ((self.programs.begin() if acct else 0.0),
                jit_fn._cache_size())

    def _account(self, jit_fn, mark, program: str, key=None,
                 **trace_args) -> None:
        """Post-call bookkeeping for one launch: a ``compile``
        instant if the call traced a new program, and a
        :class:`ProgramAccounting` tally under
        ``program[key]`` (wall time attributed to compile when the
        jit cache grew)."""
        acct, traced = self.programs.enabled, self.tracer.enabled
        if not (acct or traced):
            return
        t0, before = mark
        compiled = jit_fn._cache_size() > before
        if traced and compiled:
            self.tracer.instant("compile", program=program,
                                **trace_args)
        if acct:
            self.programs.note(
                program if key is None else f"{program}[{key}]",
                t0, compiled)

    def _qkey(self, key=None):
        """The :class:`ProgramAccounting` bucket/width key for one
        launch, grown a ``q8`` tag under quantization — quant-on
        traces account under distinct keys (``prefill[64q8]``,
        ``decode[q8]``) so compile-count and wall-time audits can
        bound the quantized program variants separately
        (``tools/ops_probe.py --programs``)."""
        if not self.quantized:
            return key
        return "q8" if key is None else f"{key}q8"

    def bucket_for(self, length: int) -> int:
        try:
            return pick_bucket(length, self.prefill_buckets)
        except ValueError:
            raise ValueError(
                f"prompt length {length} exceeds max_context "
                f"{self.max_context}") from None

    def _put(self, *arrays):
        """ONE host→device handoff for a launch's whole argument
        struct (the per-step host-overhead fix): the prepared numpy
        arrays ship as a single ``jax.device_put`` pytree instead of
        one ``jnp.asarray`` dispatch per array.  Compile counts are
        untouched — shapes/dtypes are identical to the per-array
        path.  Under a mesh the struct commits REPLICATED: token ids,
        positions, and block tables are host-side scheduler state that
        every shard consumes whole (docs/serving.md, "Tensor-parallel
        serving")."""
        if self._repl is not None:
            return jax.device_put(arrays, self._repl)
        return jax.device_put(arrays)

    def _prefill_args(self, prompt, block_table, sampling=None):
        """The prefill launch struct: (ids, length, table[, sampling
        params]) on device in one transfer, plus the bucket the
        prompt padded to."""
        n = len(prompt)
        sb = self.bucket_for(n)
        ids = np.zeros((1, sb), np.int32)
        ids[0, :n] = prompt
        table = np.zeros((1, self.blocks_per_seq), np.int32)
        table[0, :len(block_table)] = block_table
        extra = tuple(sampling) if sampling is not None else ()
        return self._put(ids, np.asarray([n], np.int32), table,
                         *extra), sb

    def _chunk_args(self, tokens, start, block_table, pad_to,
                    sampling=None):
        """The chunk launch struct: (ids, start, length, table[,
        sampling params]) on device in one transfer, plus the
        compiled chunk width."""
        n = len(tokens)
        cb = pad_to if pad_to is not None else self.bucket_for(n)
        if n > cb:
            raise ValueError(
                f"chunk of {n} tokens exceeds pad_to={cb}")
        ids = np.zeros((1, cb), np.int32)
        ids[0, :n] = tokens
        table = np.zeros((1, self.blocks_per_seq), np.int32)
        table[0, :len(block_table)] = block_table
        extra = tuple(sampling) if sampling is not None else ()
        return self._put(ids, np.asarray([start], np.int32),
                         np.asarray([n], np.int32), table, *extra), cb

    def prefill(self, prompt, block_table) -> jax.Array:
        """Run one prompt through the bucketed prefill, writing its
        K/V into ``block_table``'s blocks.  Returns the last-token
        logits (V,)."""
        args, sb = self._prefill_args(prompt, block_table)
        mark = self._mark(self._prefill_jit)
        self.cache, last = self._prefill_jit(self.params, self.cache,
                                             *args)
        self._account(self._prefill_jit, mark, "prefill",
                      key=self._qkey(sb), bucket=sb)
        return last[0]

    def prefill_sampled(self, prompt, block_table, sampling=None):
        """The fused-sampling twin of :meth:`prefill`: returns
        ``(token_ids (1,) int32, finite (1,) bool)`` device arrays —
        the prompt's next token and its non-finite guard — without
        materializing logits on the host.  ``sampling=None`` (the
        default) launches the greedy argmax program; a
        ``(temperature, top_k, top_p, seed)`` tuple of ``(1,)``
        arrays launches the stochastic twin (``docs/serving.md``,
        "Stochastic sampling"; a 0-temperature row inside it is still
        bit-exact argmax)."""
        args, sb = self._prefill_args(prompt, block_table,
                                      sampling=sampling)
        if sampling is None:
            jit_fn, name = self._prefill_sampled_jit, "prefill_sampled"
        else:
            jit_fn, name = self._prefill_stoch_jit, "prefill_stoch"
        mark = self._mark(jit_fn)
        self.cache, ids, fin = jit_fn(self.params, self.cache, *args)
        self._account(jit_fn, mark, name, key=self._qkey(sb),
                      bucket=sb)
        return ids, fin

    def swap_params(self, params) -> None:
        """In-place weight swap: rebind ``self.params`` to a new
        pytree WITHOUT touching any compiled program.  Params are an
        ARGUMENT to every jitted call here (never a captured
        constant), so as long as the new tree has the same structure,
        shapes, and dtypes, the next launch simply traces nothing and
        runs the existing executable with the new weights — this is
        what makes a zero-downtime rollout (``serving/elastic``)
        possible.  Under a mesh the new tree is resharded through the
        same ``shard_params`` rules as construction, so placement is
        identical too."""
        if self.mesh is not None:
            from apex_tpu.parallel.tensor_parallel import shard_params
            params = shard_params(params, self.mesh, self._tp_rules)
        self.params = params

    def chunk_prefill(self, tokens, start: int, block_table,
                      pad_to: Optional[int] = None) -> jax.Array:
        """Run one prefill chunk — ``tokens`` at absolute positions
        ``start..start+len-1`` — writing its K/V through
        ``block_table``; K/V for positions < start must already be
        materialized (earlier chunks or shared prefix-cache blocks).
        Returns the chunk's last-token logits (V,).

        ``pad_to`` is the compiled chunk width (default: the prompt
        bucket for ``len(tokens)``); a steady chunked-prefill loop
        passes its fixed chunk size so exactly one chunk program ever
        compiles."""
        args, cb = self._chunk_args(tokens, start, block_table, pad_to)
        mark = self._mark(self._chunk_jit)
        self.cache, last = self._chunk_jit(self.params, self.cache,
                                           *args)
        self._account(self._chunk_jit, mark, "chunk_prefill",
                      key=self._qkey(cb), width=cb)
        return last[0]

    def chunk_prefill_sampled(self, tokens, start: int, block_table,
                              pad_to: Optional[int] = None,
                              sampling=None):
        """The fused-sampling twin of :meth:`chunk_prefill`: returns
        ``(token_ids (1,) int32, finite (1,) bool)`` device arrays for
        the chunk's last valid token (only meaningful on the final
        chunk, exactly like the logits twin).  ``sampling`` as in
        :meth:`prefill_sampled`."""
        args, cb = self._chunk_args(tokens, start, block_table,
                                    pad_to, sampling=sampling)
        if sampling is None:
            jit_fn, name = (self._chunk_sampled_jit,
                            "chunk_prefill_sampled")
        else:
            jit_fn, name = self._chunk_stoch_jit, "chunk_prefill_stoch"
        mark = self._mark(jit_fn)
        self.cache, ids, fin = jit_fn(self.params, self.cache, *args)
        self._account(jit_fn, mark, name, key=self._qkey(cb),
                      width=cb)
        return ids, fin

    def copy_blocks(self, pairs) -> None:
        """Duplicate physical blocks ``[(src, dst), ...]`` inside the
        pool (copy-on-write).  Launches in fixed-width batches of
        ``_COPY_WIDTH`` padded with (0, 0) no-op pairs, so the copy
        program compiles once."""
        for i in range(0, len(pairs), _COPY_WIDTH):
            batch = pairs[i:i + _COPY_WIDTH]
            src = np.zeros((_COPY_WIDTH,), np.int32)
            dst = np.zeros((_COPY_WIDTH,), np.int32)
            for j, (s, d) in enumerate(batch):
                src[j], dst[j] = s, d
            args = self._put(src, dst)
            mark = self._mark(self._copy_jit)
            self.cache = self._copy_jit(self.cache, *args)
            self._account(self._copy_jit, mark, "copy_blocks",
                          key=self._qkey())

    # -- disaggregated hand-off (docs/serving.md) --------------------------

    def copy_blocks_from(self, src_engine, pairs) -> None:
        """Copy physical blocks ``[(src, dst), ...]`` from ANOTHER
        engine's pool into this one — the same-host disaggregated
        hand-off: a finished prefill's KV moves from the prefill pool
        into the decode pool without either pool's programs ever
        sharing an array.  Both pools must share geometry (layers,
        heads, block size, quantization mode — the server constructs
        them that way).  Fixed-width ``_COPY_WIDTH`` launches, exactly
        like :meth:`copy_blocks`, so one program serves every
        hand-off."""
        for i in range(0, len(pairs), _COPY_WIDTH):
            batch = pairs[i:i + _COPY_WIDTH]
            src = np.zeros((_COPY_WIDTH,), np.int32)
            dst = np.zeros((_COPY_WIDTH,), np.int32)
            for j, (s, d) in enumerate(batch):
                src[j], dst[j] = s, d
            args = self._put(src, dst)
            mark = self._mark(self._xfer_jit)
            self.cache = self._xfer_jit(self.cache, src_engine.cache,
                                        *args)
            self._account(self._xfer_jit, mark, "handoff_copy",
                          key=self._qkey())

    def _block_slots(self, block_ids, pad_to: int) -> np.ndarray:
        """Flat pool slots of ``block_ids``' token rows, padded with
        the garbage block's slots to ``pad_to`` blocks."""
        bs = self.block_size
        ids = np.zeros((pad_to,), np.int64)
        ids[:len(block_ids)] = block_ids
        return (ids[:, None] * bs + np.arange(bs)[None, :]).reshape(-1)

    def export_blocks(self, block_ids, *,
                      per_block_crc: bool = False) -> dict:
        """Materialize ``block_ids``' contents as a host payload — the
        CROSS-REPLICA hand-off transfer unit (``docs/serving.md``,
        "Disaggregated prefill/decode"): every cache leaf's rows for
        those blocks (scale sidecars included under quantization) plus
        a per-leaf crc32, so a torn transfer is DETECTED at import
        instead of silently decoding garbage.

        ``per_block_crc=True`` additionally records a crc32 PER BLOCK
        per leaf: the offload tier demotes blocks in one batched
        export and re-verifies each block against these at promote
        time (``offload.split_payload``), so rot between demote and
        promote is still caught per block even though the device
        gather ran once.  The hot hand-off path leaves it off — the
        whole-leaf crc already covers a one-shot transfer."""
        import zlib

        slots = self._block_slots(block_ids, len(block_ids))
        leaves = {name: np.ascontiguousarray(np.asarray(arr[:, slots]))
                  for name, arr in self.cache.items()}
        bs = self.block_size
        payload = {
            "num_blocks": len(block_ids),
            "block_size": bs,
            "leaves": leaves,
            "crc": {name: zlib.crc32(a.tobytes())
                    for name, a in leaves.items()},
        }
        if per_block_crc:
            payload["block_crc"] = {
                name: [zlib.crc32(np.ascontiguousarray(
                    a[:, i * bs:(i + 1) * bs]).tobytes())
                    for i in range(len(block_ids))]
                for name, a in leaves.items()}
        return payload

    def import_blocks(self, block_ids, payload) -> None:
        """Scatter an :meth:`export_blocks` payload into THIS pool's
        ``block_ids`` (same count, same geometry).  Verifies the
        per-leaf checksums first and raises :class:`ValueError` on any
        mismatch — a torn hand-off must be rejected whole (the caller
        falls back to a fresh monolithic prefill, which is
        bit-identical), never half-imported."""
        import zlib

        if payload.get("block_size") != self.block_size \
                or payload.get("num_blocks") != len(block_ids):
            raise ValueError(
                f"hand-off payload geometry mismatch: payload holds "
                f"{payload.get('num_blocks')} blocks of "
                f"{payload.get('block_size')} slots, importing "
                f"{len(block_ids)} blocks of {self.block_size}")
        leaves = payload["leaves"]
        if set(leaves) != set(self.cache):
            raise ValueError(
                f"hand-off payload leaves {sorted(leaves)} != pool "
                f"leaves {sorted(self.cache)} (quantization modes "
                f"must match across replicas)")
        for name, arr in leaves.items():
            got = zlib.crc32(np.ascontiguousarray(arr).tobytes())
            want = payload["crc"].get(name)
            if got != want:
                # name the culprit: which leaf, which destination
                # blocks, and both crcs — a torn payload in a
                # postmortem must not read as "rejected whole, no
                # idea where" (the offload promote path and the
                # cross-replica hand-off both route through here)
                raise ValueError(
                    f"torn hand-off payload: leaf {name!r} for "
                    f"block(s) {list(map(int, block_ids))} has "
                    f"checksum {got} (actual) != {want} (expected); "
                    f"payload rejected whole")
        if not len(block_ids):
            # an empty (but geometry-consistent) transfer is a no-op:
            # launching the scatter anyway would pad the id list with
            # zeros and overwrite block 0's slots with zero bytes
            return
        w = self.blocks_per_seq
        slots = self._block_slots(block_ids, w).astype(np.int32)
        padded = {}
        for name, arr in leaves.items():
            full = np.zeros((arr.shape[0], w * self.block_size)
                            + arr.shape[2:], arr.dtype)
            full[:, :arr.shape[1]] = arr
            padded[name] = full
        args = self._put(slots, padded)
        mark = self._mark(self._import_jit)
        self.cache = self._import_jit(self.cache, *args)
        self._account(self._import_jit, mark, "import_blocks",
                      key=self._qkey())

    def _decode_args(self, tokens, positions, tables, sampling=None):
        extra = tuple(sampling) if sampling is not None else ()
        return self._put(np.asarray(tokens, np.int32),
                         np.asarray(positions, np.int32),
                         np.asarray(tables, np.int32), *extra)

    def decode(self, tokens, positions, tables) -> jax.Array:
        """One iteration-level decode step over all slots.  Arrays are
        (B,), (B,), (B, blocks_per_seq) with inactive slots zeroed.
        Returns next-token logits (B, V)."""
        args = self._decode_args(tokens, positions, tables)
        mark = self._mark(self._decode_jit)
        self.cache, logits = self._decode_jit(self.params, self.cache,
                                              *args)
        self._account(self._decode_jit, mark, "decode",
                      key=self._qkey())
        return logits

    def decode_sampled(self, tokens, positions, tables, sampling=None):
        """The fused-sampling twin of :meth:`decode`: returns
        ``(token_ids (B,) int32, finite (B,) bool)`` DEVICE arrays.
        Nothing is materialized — the pipelined serve loop stashes the
        handles and consumes them next iteration, so the device runs
        this step while the host plans the next one.

        ``sampling=None`` launches the greedy argmax program; a
        ``(temperature, top_k, top_p, seed)`` tuple of per-slot
        ``(B,)`` arrays launches the stochastic twin — greedy/idle
        slots (temperature 0) stay bit-exact argmax inside it
        (``docs/serving.md``, "Stochastic sampling")."""
        args = self._decode_args(tokens, positions, tables,
                                 sampling=sampling)
        if sampling is None:
            jit_fn, name = self._decode_sampled_jit, "decode_sampled"
        else:
            jit_fn, name = self._decode_stoch_jit, "decode_stoch"
        mark = self._mark(jit_fn)
        self.cache, ids, fin = jit_fn(self.params, self.cache, *args)
        self._account(jit_fn, mark, name, key=self._qkey())
        return ids, fin

    def _verify_args(self, tokens, lengths, positions, tables,
                     sampling=None):
        extra = tuple(sampling) if sampling is not None else ()
        return self._put(np.asarray(tokens, np.int32),
                         np.asarray(positions, np.int32),
                         np.asarray(lengths, np.int32),
                         np.asarray(tables, np.int32), *extra)

    def verify(self, tokens, lengths, positions, tables) -> jax.Array:
        """One speculative verify step over all slots: tokens (B, K)
        — pending token + drafts per slot, zero-padded; lengths (B,)
        valid tokens per slot (0 = idle); positions (B,) each slot's
        cached context length; tables (B, blocks_per_seq).  Writes all
        valid tokens' K/V and returns per-column logits (B, K, V); the
        caller (``serving.api``) runs greedy acceptance and rolls back
        rejected suffix blocks.  One trace per distinct K — a server
        with a fixed speculation depth compiles this exactly once."""
        args = self._verify_args(tokens, lengths, positions, tables)
        kw = int(np.asarray(tokens).shape[1])
        mark = self._mark(self._verify_jit)
        self.cache, logits = self._verify_jit(self.params, self.cache,
                                              *args)
        self._account(self._verify_jit, mark, "verify",
                      key=self._qkey(kw), width=kw)
        return logits

    def verify_sampled(self, tokens, lengths, positions, tables,
                       sampling=None):
        """The fused-sampling twin of :meth:`verify`: returns
        ``(token_ids (B, K) int32, finite (B, K) bool)`` device
        arrays — every row's sampled token and finite flag, the exact
        inputs acceptance needs — without materializing the
        ``(B, K, V)`` logits block.  Same one-trace-per-width compile
        discipline as :meth:`verify`.

        ``sampling=None``: every row is argmax (greedy acceptance
        compares drafts to argmax).  With per-slot params, each column
        is sampled with its own positional counter key — acceptance
        then compares drafts to the column's SAMPLE, which realizes
        rejection sampling's accept/residual probabilities exactly
        while keeping the emitted stream draft-independent
        (``ops.sample_tokens``, the Gumbel-max coupling)."""
        args = self._verify_args(tokens, lengths, positions, tables,
                                 sampling=sampling)
        kw = int(np.asarray(tokens).shape[1])
        if sampling is None:
            jit_fn, name = self._verify_sampled_jit, "verify_sampled"
        else:
            jit_fn, name = self._verify_stoch_jit, "verify_stoch"
        mark = self._mark(jit_fn)
        self.cache, ids, fin = jit_fn(self.params, self.cache, *args)
        self._account(jit_fn, mark, name, key=self._qkey(kw),
                      width=kw)
        return ids, fin

    # -- introspection ----------------------------------------------------

    def compile_counts(self):
        """(prefill traces, decode traces) — the recompile audit the
        scheduler tests pin: prefill (monolithic buckets + chunk
        widths) <= len(prefill_buckets), decode == 1 regardless of
        traffic.  A fixed-chunk loop contributes exactly one chunk
        trace (``chunk_prefill(pad_to=...)``).  Logits, sampled, and
        stochastic twins count together: greedy-only traffic runs
        exactly one path per program (the historical bounds hold
        unchanged), and the first stochastic request adds at most one
        extra trace per program family — still O(1) per shape key,
        never per request."""
        return (self._prefill_jit._cache_size()
                + self._chunk_jit._cache_size()
                + self._prefill_sampled_jit._cache_size()
                + self._chunk_sampled_jit._cache_size()
                + self._prefill_stoch_jit._cache_size()
                + self._chunk_stoch_jit._cache_size(),
                self._decode_jit._cache_size()
                + self._decode_sampled_jit._cache_size()
                + self._decode_stoch_jit._cache_size())

    def verify_compiles(self) -> int:
        """Verify-program traces (logits + sampled + stochastic
        twins) — the speculation half of the compile audit: a
        greedy-only server with a fixed speculation depth must show
        exactly 1 (0 with speculation off/idle) no matter how drafts
        and batch composition vary; stochastic traffic adds at most
        one more trace per width."""
        return (self._verify_jit._cache_size()
                + self._verify_sampled_jit._cache_size()
                + self._verify_stoch_jit._cache_size())

    def collective_programs(self) -> int:
        """Compiled traces currently lowered THROUGH the mesh (all
        program families, logits + sampled + stochastic twins + block
        copy) — the ``stats()["sharding"]`` audit that sharded serving
        compiled one program per logical (program, shape) key, not per
        shard.  0 on an unsharded engine: nothing it compiles carries
        a collective."""
        if self.mesh is None:
            return 0
        return sum(j._cache_size() for j in (
            self._prefill_jit, self._chunk_jit, self._decode_jit,
            self._verify_jit, self._copy_jit,
            self._prefill_sampled_jit, self._chunk_sampled_jit,
            self._decode_sampled_jit, self._verify_sampled_jit,
            self._prefill_stoch_jit, self._chunk_stoch_jit,
            self._decode_stoch_jit, self._verify_stoch_jit))

    def memory_info(self) -> dict:
        """Static pool geometry for ``stats()["memory"]`` and
        postmortem manifests: usable blocks, tokens per block, the
        pool's LOGICAL footprint (both K and V, all shards), and —
        what per-chip HBM budgeting must use — the ACTUAL per-device
        bytes, read off the live arrays' shard shape and dtype (under
        tensor parallelism each device holds ``num_heads/tp`` heads of
        the pool, so the logical size overstates per-chip HBM by
        tp×).  Under quantization every count includes the scale
        sidecar — summed over ALL live cache leaves' shard shapes, so
        ``pool_bytes_per_device`` is what the int8 pool plus its fp32
        scales actually pin on each chip, and ``bytes_per_block`` is
        the true per-block HBM price headroom math divides by."""
        cfg = self.cache_cfg
        k = self.cache["k"]
        per_device = sum(
            int(np.prod(arr.sharding.shard_shape(arr.shape)))
            * jnp.dtype(arr.dtype).itemsize
            for arr in self.cache.values())
        return {
            "blocks_usable": cfg.num_blocks - 1,
            "block_size": cfg.block_size,
            "pool_tokens": cfg.usable_tokens,
            "pool_bytes": cfg.bytes(),
            "pool_bytes_per_device": per_device,
            "bytes_per_block": cfg.bytes_per_block,
            "cache_dtype": str(jnp.dtype(k.dtype)),
            "quantize": cfg.quantize,
            "compute_dtype": str(cfg.resolved_dtype()),
        }

    def sharding_info(self) -> dict:
        """The pinned ``stats()["sharding"]`` block: tensor-parallel
        degree and axis, mesh geometry, per-shard KV bytes, and the
        mesh-lowered program count (``docs/serving.md``,
        "Tensor-parallel serving")."""
        return {
            "enabled": self.mesh is not None,
            "tp": self.tp,
            "axis": self.tp_axis,
            "devices": (int(self.mesh.size)
                        if self.mesh is not None else 1),
            "mesh": ({name: int(n)
                      for name, n in self.mesh.shape.items()}
                     if self.mesh is not None else None),
            "kv_pool_bytes_per_device":
                self.memory_info()["pool_bytes_per_device"],
            "collective_programs": self.collective_programs(),
        }

    def reset_cache(self):
        """Zero the pool and refill the allocator in place (between
        workloads; schedulers holding the allocator stay wired)."""
        self.cache = init_kv_cache(self.cache_cfg,
                                   sharding=self._pool_shard,
                                   scale_sharding=self._scale_shard)
        self.allocator.reset()
