"""Jit-compiled prefill + single-token decode steps over the KV cache.

Two compiled programs, both fixed-shape so the continuous-batching
loop never recompiles in steady state:

- **prefill** (one request, prompt padded to a length *bucket*): the
  ordinary causal GPT forward — optionally through the flash kernel
  via ``attention_fn`` — with ``return_kv=True``; the per-layer K/V
  are scattered into the request's blocks in the same program.  One
  trace per bucket length, so the compile count is bounded by
  ``len(prefill_buckets)``, not by the distribution of prompt lengths.
- **decode** (the whole running batch, always ``max_batch_size``
  wide): gather every slot's context through its block table, run the
  model on one token per slot at its own position
  (``ops.cached_attention`` inside), scatter the new K/V, return
  next-token logits.  Compiled exactly once.

Empty slots ride along as no-ops by construction: position 0 masks
the whole context, the zeroed block table routes the KV write into
the reserved garbage block, and the caller ignores their logits.

The cache pytree is donated through both steps — on TPU the pool is
the HBM hog and must be updated in place, not double-buffered.  (XLA
on CPU ignores donation; the warning is filtered.)
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    context_bias,
    gather_context,
    init_kv_cache,
    slot_index,
    write_prefill,
    write_tokens,
)

# CPU backends can't honor donation; the fallback copy is exactly the
# pre-donation behavior, so the warning is noise off-TPU
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def default_prefill_buckets(max_context: int,
                            smallest: int = 16) -> Tuple[int, ...]:
    """Power-of-two bucket ladder capped at ``max_context`` — each
    prompt pads to the next rung, so at most ``log2`` distinct prefill
    shapes ever compile and no prompt pads to more than 2x its
    length."""
    buckets = []
    b = smallest
    while b < max_context:
        buckets.append(b)
        b *= 2
    buckets.append(max_context)
    return tuple(buckets)


class DecodeEngine:
    """The device half of the serving stack: owns the cache pool, the
    compiled prefill/decode programs, and nothing else — admission,
    batching composition, and termination live in
    ``serving.scheduler``/``serving.api``.

    Args:
      cfg: the GPT architecture (params must match).
      params: the model's ``{"params": ...}["params"]`` pytree (pass
        amp-cast params to serve in half).
      max_batch_size: decode batch width (running-request slots).
      max_context: per-request token capacity; default
        ``cfg.max_position_embeddings``.
      num_blocks: physical blocks in the pool (incl. the reserved
        garbage block 0); default sizes the pool for
        ``max_batch_size`` full-context requests plus slack.
      block_size: tokens per block.
      cache_dtype: KV dtype; None = amp policy
        (:func:`serving.kv_cache.resolve_cache_dtype`).
      attention_fn: optional fused attention for the PREFILL pass
        (``make_flash_attention(causal=True)`` on TPU); decode always
        takes the ``ops.cached_attention`` path.
      prefill_buckets: ascending prompt-length buckets; None =
        :func:`default_prefill_buckets`.
    """

    def __init__(self, cfg: GPTConfig, params, *,
                 max_batch_size: int = 8,
                 max_context: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 block_size: int = 16,
                 cache_dtype=None,
                 attention_fn=None,
                 prefill_buckets: Optional[Sequence[int]] = None):
        self.cfg = cfg
        self.params = params
        self.max_batch_size = int(max_batch_size)
        self.max_context = int(max_context
                               or cfg.max_position_embeddings)
        if self.max_context > cfg.max_position_embeddings:
            raise ValueError(
                f"max_context={self.max_context} exceeds the model's "
                f"max_position_embeddings={cfg.max_position_embeddings}")
        self.block_size = int(block_size)
        self.blocks_per_seq = -(-self.max_context // self.block_size)
        if num_blocks is None:
            # every slot can hold a full-context request, +1 garbage
            num_blocks = self.max_batch_size * self.blocks_per_seq + 1
        self.cache_cfg = KVCacheConfig(
            num_layers=cfg.num_hidden_layers,
            num_heads=cfg.num_attention_heads,
            head_dim=cfg.hidden_size // cfg.num_attention_heads,
            num_blocks=int(num_blocks),
            block_size=self.block_size,
            dtype=cache_dtype)
        self.allocator = BlockAllocator(self.cache_cfg)
        self.cache = init_kv_cache(self.cache_cfg)
        self.model = GPTLMHeadModel(cfg, attention_fn=attention_fn)
        if prefill_buckets is None:
            prefill_buckets = default_prefill_buckets(self.max_context)
        self.prefill_buckets = tuple(sorted(int(b)
                                            for b in prefill_buckets))
        if self.prefill_buckets[-1] < self.max_context:
            raise ValueError(
                f"largest prefill bucket {self.prefill_buckets[-1]} "
                f"< max_context {self.max_context}")

        self._prefill_jit = jax.jit(self._prefill_impl,
                                    donate_argnums=(1,))
        self._decode_jit = jax.jit(self._decode_impl,
                                   donate_argnums=(1,))

    # -- compiled bodies --------------------------------------------------

    def _prefill_impl(self, params, cache, ids, length, table):
        """ids (1, Sb) zero-padded prompt; length (1,) true length;
        table (1, blocks_per_seq).  Returns (cache, last-token logits
        (1, V))."""
        sb = ids.shape[1]
        pos = jnp.arange(sb, dtype=jnp.int32)[None, :]
        mask = (pos < length[:, None]).astype(jnp.int32)
        logits, kvs = self.model.apply(
            {"params": params}, ids, attention_mask=mask,
            deterministic=True, return_kv=True)
        k = jnp.stack([kv[0] for kv in kvs])          # (L, 1, Sb, H, D)
        v = jnp.stack([kv[1] for kv in kvs])
        # padded positions scatter into the garbage block (slot 0)
        slots = jnp.where(mask > 0,
                          slot_index(table, pos, self.block_size), 0)
        cache = write_prefill(cache, (k, v), slots)
        last = jnp.take_along_axis(
            logits, (length[:, None, None] - 1).astype(jnp.int32),
            axis=1)[:, 0]                             # (1, V)
        return cache, last

    def _decode_impl(self, params, cache, tokens, positions, tables):
        """tokens (B,) current input token per slot; positions (B,)
        its position (== cached context length); tables (B,
        blocks_per_seq).  Returns (cache, logits (B, V))."""
        t_ctx = self.blocks_per_seq * self.block_size
        k_ctx, v_ctx = gather_context(cache, tables, self.block_size)
        bias = context_bias(positions, t_ctx)
        logits, kvs = self.model.apply(
            {"params": params}, tokens[:, None],
            positions=positions[:, None].astype(jnp.int32),
            deterministic=True,
            cache_views=(k_ctx, v_ctx, bias), return_kv=True)
        k = jnp.stack([kv[0] for kv in kvs])          # (L, B, 1, H, D)
        v = jnp.stack([kv[1] for kv in kvs])
        slots = slot_index(tables, positions, self.block_size)
        cache = write_tokens(cache, (k, v), slots)
        return cache, logits[:, 0]                    # (B, V)

    # -- host API ---------------------------------------------------------

    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if length <= b:
                return b
        raise ValueError(
            f"prompt length {length} exceeds max_context "
            f"{self.max_context}")

    def prefill(self, prompt, block_table) -> jax.Array:
        """Run one prompt through the bucketed prefill, writing its
        K/V into ``block_table``'s blocks.  Returns the last-token
        logits (V,)."""
        import numpy as np

        n = len(prompt)
        sb = self.bucket_for(n)
        ids = np.zeros((1, sb), np.int32)
        ids[0, :n] = prompt
        table = np.zeros((1, self.blocks_per_seq), np.int32)
        table[0, :len(block_table)] = block_table
        self.cache, last = self._prefill_jit(
            self.params, self.cache, jnp.asarray(ids),
            jnp.asarray([n], jnp.int32), jnp.asarray(table))
        return last[0]

    def decode(self, tokens, positions, tables) -> jax.Array:
        """One iteration-level decode step over all slots.  Arrays are
        (B,), (B,), (B, blocks_per_seq) with inactive slots zeroed.
        Returns next-token logits (B, V)."""
        self.cache, logits = self._decode_jit(
            self.params, self.cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(tables, jnp.int32))
        return logits

    # -- introspection ----------------------------------------------------

    def compile_counts(self):
        """(prefill traces, decode traces) — the recompile audit the
        scheduler tests pin: prefill <= len(prefill_buckets), decode
        == 1 regardless of traffic."""
        return (self._prefill_jit._cache_size(),
                self._decode_jit._cache_size())

    def reset_cache(self):
        """Zero the pool and refill the allocator in place (between
        workloads; schedulers holding the allocator stay wired)."""
        self.cache = init_kv_cache(self.cache_cfg)
        self.allocator.reset()
