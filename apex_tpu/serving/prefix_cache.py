"""Block-level prefix cache — RadixAttention's sharing, hash-chained.

Shared-prefix traffic (system prompts, few-shot templates, multi-turn
chat) re-prefills the same tokens for every request; SGLang's
RadixAttention observation is that a block-granular KV cache already
holds everything needed to skip that work — the only missing piece is
an INDEX from token content to physical blocks.  This module is that
index:

- the unit of sharing is one FULL block (``block_size`` tokens): a
  partial block is still being written and can never be shared;
- the key of block i is ``(parent physical block, tuple of its
  block_size tokens)`` — chaining on the parent's physical id makes
  the key cover the entire prefix without hashing it (two prefixes
  agreeing on blocks 0..i-1 share the same parent id by induction),
  which is a flat-dict encoding of the radix tree;
- :meth:`match` walks a new request's context down the chain and
  returns the longest cached run of full blocks with one refcount
  taken per block (``BlockAllocator.incref`` / ``adopt``);
- a block whose refcount drops to zero is NOT freed if registered
  here: the allocator's ``release_hook`` parks it in an LRU of
  evictable holds, so a finished request's prefix keeps serving
  matches until the pool actually needs the space;
- :meth:`evict` reclaims LRU holds for the allocator, cascading over
  registered descendants (their chain keys dangle once the parent id
  is reusable — a reused id plus equal tokens would alias a stale
  entry onto garbage).

The cache never touches device memory: like the scheduler it is pure
host bookkeeping over block ids; the KV bytes themselves were written
by whichever request prefilled them first and are bit-identical to
what any later request would have written (same tokens, same absolute
positions, same jitted program).

Quantized pools (``docs/serving.md``, "Quantized KV cache") need no
special handling here: the int8 payload and its fp32 scale sidecar
are both indexed by the SAME flat slot (block * block_size + offset),
so a block id in this index names its scales too — registration,
LRU holds, adoption, eviction, and COW duplication
(``kv_cache.copy_blocks`` copies every cache leaf) all carry scales
with their blocks by construction.  Quantization is elementwise and
deterministic, so the first-writer-wins sharing argument above holds
byte-for-byte for quantized blocks as well.
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from apex_tpu.serving.kv_cache import BlockAllocator
from apex_tpu.serving.transport.base import TransportError
from apex_tpu.serving.offload import (
    merge_payloads,
    split_payload,
    verify_payload,
)
from apex_tpu.utils.meters import CounterMeter

# chain parent of a sequence's first block — the reserved garbage
# block's id, which is never allocated and so never collides
ROOT = 0

# chain hash of ROOT — the seed of every sequence's content-hash
# chain (serving/offload): block i's hash covers its whole prefix by
# induction, like the (parent id, chunk) key covers it by id chaining
_ROOT_HASH = b"\x00" * 16


def _chunk_hash(parent_hash: bytes, chunk) -> bytes:
    """Content hash of a chain node: ``blake2b(parent_hash || chunk
    tokens)`` — a pure function of token content (NOT block ids), so
    it stays valid across block-id reuse and process restarts, which
    is what lets it key the offload store's host/disk tiers."""
    h = hashlib.blake2b(parent_hash, digest_size=16)
    for t in chunk:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


class PrefixCache:
    """Content -> physical-block index over a :class:`BlockAllocator`.

    Wires itself into the allocator on construction: ``release_hook``
    parks registered ref-0 blocks in the evictable LRU instead of
    freeing them, and a reset hook drops the whole index when the
    allocator resets (the ids it stored are dangling after that).

    ``counters`` (a :class:`CounterMeter`) accumulates
    ``prefix_hit_tokens`` / ``prefix_miss_tokens`` /
    ``prefix_hit_requests`` / ``prefix_miss_requests`` /
    ``prefix_evicted_blocks`` / ``prefix_cow_blocks`` — surfaced by
    ``InferenceServer.stats``.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int,
                 counters: Optional[CounterMeter] = None):
        self.allocator = allocator
        self.block_size = block_size
        self.counters = counters if counters is not None else CounterMeter()
        self._map: Dict[Tuple[int, tuple], int] = {}   # key -> block
        self._key_of: Dict[int, Tuple[int, tuple]] = {}
        self._children: Dict[int, Set[int]] = {}       # block -> blocks
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # evictable
        self.evictable_peak = 0     # high-watermark of LRU holds
        # hierarchical offload (serving/offload; attached by the
        # server when enable_kv_offload= is on): chain content hashes
        # per registered block, the store, and the engine's
        # export/import closures — all None when offload is off, and
        # every offload branch below guards on the store
        self._hash_of: Dict[int, bytes] = {}
        self._demote_pending: List[Tuple[int, bytes]] = []
        self._offload = None
        self._exporter = None
        self._importer = None
        self._off_counters: Optional[CounterMeter] = None
        self._promote_hist = None
        self._clock = time.monotonic
        allocator.release_hook = self._on_release
        allocator.reset_hooks.append(self.clear)

    def attach_offload(self, store, exporter, importer, *,
                       counters: Optional[CounterMeter] = None,
                       promote_hist=None, clock=None) -> None:
        """Wire the host/disk offload tiers in (docs/serving.md,
        "Hierarchical KV offload").  ``exporter`` / ``importer`` are
        the cache-home engine's ``export_blocks`` / ``import_blocks``
        (as closures, so chaos wrappers installed later still
        intercept); must be attached before any block registers —
        chain hashes are computed at registration time."""
        if self._key_of:
            raise RuntimeError(
                "attach_offload must run before any block registers "
                "(chain hashes are computed at registration)")
        self._offload = store
        self._exporter = exporter
        self._importer = importer
        self._off_counters = (counters if counters is not None
                              else CounterMeter())
        self._promote_hist = promote_hist
        if clock is not None:
            self._clock = clock

    # -- allocator hooks --------------------------------------------------

    def _on_release(self, blk: int) -> bool:
        """Refcount hit zero: keep registered blocks as evictable LRU
        holds (newest at the back); unregistered blocks go free."""
        if blk in self._key_of:
            self._lru[blk] = None
            if len(self._lru) > self.evictable_peak:
                self.evictable_peak = len(self._lru)
            return True
        return False

    def clear(self):
        """Drop the whole index (allocator reset — every stored id is
        dangling)."""
        self._map.clear()
        self._key_of.clear()
        self._children.clear()
        self._lru.clear()
        self._hash_of.clear()
        # dropped, not demoted: a reset means every stored id is
        # dangling, so there is nothing coherent left to export
        self._demote_pending.clear()
        self.evictable_peak = 0

    # -- introspection ----------------------------------------------------

    @property
    def num_cached_blocks(self) -> int:
        """Registered blocks (shared-or-shareable index size)."""
        return len(self._key_of)

    @property
    def num_evictable(self) -> int:
        """Ref-0 holds reclaimable by :meth:`evict`."""
        return len(self._lru)

    def held_blocks(self) -> Set[int]:
        return set(self._lru)

    def is_registered(self, blk: int) -> bool:
        return blk in self._key_of

    # -- the index --------------------------------------------------------

    def match(self, tokens: List[int]) -> List[int]:
        """Longest cached run of ``tokens``' full-block chunks, as
        physical block ids with one ref taken per block (LRU holds are
        reactivated out of the evictable set).  The caller either
        commits the blocks into a table or returns them via
        :meth:`cancel` — never both."""
        bs = self.block_size
        out: List[int] = []
        parent = ROOT
        for i in range(len(tokens) // bs):
            blk = self._map.get((parent, tuple(tokens[i * bs:(i + 1) * bs])))
            if blk is None:
                break
            if blk in self._lru:
                del self._lru[blk]
                self.allocator.adopt(blk)
            else:
                self.allocator.incref([blk])
            out.append(blk)
            parent = blk
        return out

    def cancel(self, blocks: List[int]):
        """Undo :meth:`match`'s refs for an admission that didn't go
        through (registered blocks drop back into the LRU via the
        release hook)."""
        self.allocator.free(blocks)

    def register(self, parent: int, chunk: Tuple[int, ...],
                 blk: int) -> bool:
        """Index the full block ``blk`` holding ``chunk`` under its
        chain ``parent``.  First registration wins: if the key already
        maps to ANOTHER block (two requests prefilled the same content
        independently) the existing entry stays and this block remains
        private — the caller must then stop registering descendants,
        whose chain would dangle off an unindexed id.  Returns whether
        ``blk`` is the indexed block for this key."""
        if len(chunk) != self.block_size:
            raise ValueError(
                f"register needs a full block of {self.block_size} "
                f"tokens; got {len(chunk)}")
        key = (parent, tuple(chunk))
        cur = self._map.get(key)
        if cur is not None:
            return cur == blk
        if blk in self._key_of:
            # same block under two keys would corrupt eviction; keep
            # the first registration
            return False
        self._map[key] = blk
        self._key_of[blk] = key
        self._children.setdefault(parent, set()).add(blk)
        if self._offload is not None:
            ph = (_ROOT_HASH if parent == ROOT
                  else self._hash_of.get(parent))
            if ph is not None:
                self._hash_of[blk] = _chunk_hash(ph, key[1])
        return True

    # -- cross-replica warm-up (serving/elastic) ---------------------------

    def export_nodes(self, max_blocks: Optional[int] = None
                     ) -> List[Tuple[int, Tuple[int, ...], int]]:
        """The registered radix tree as ``(parent, chunk, block)``
        rows in parent-before-child order (BFS from ``ROOT``,
        children sorted by chunk tokens — deterministic for a given
        index state).  A scale-up warms a NEW replica's cache from a
        donor with this: rows bound by ``max_blocks`` always form a
        valid tree prefix, so the importer can remap parent ids
        row-by-row and never dangles a chain."""
        budget = (len(self._key_of) if max_blocks is None
                  else max(0, int(max_blocks)))
        out: List[Tuple[int, Tuple[int, ...], int]] = []
        frontier = [ROOT]
        while frontier and len(out) < budget:
            nxt: List[int] = []
            for parent in frontier:
                for blk in sorted(
                        self._children.get(parent, ()),
                        key=lambda b: self._key_of[b][1]):
                    if len(out) >= budget:
                        return out
                    out.append((parent, self._key_of[blk][1], blk))
                    nxt.append(blk)
            frontier = nxt
        return out

    def seed_nodes(self, nodes, id_map: Dict[int, int]) -> int:
        """Register imported donor nodes under THIS cache's block ids
        and park them as evictable LRU holds.  ``nodes`` is a donor
        :meth:`export_nodes` listing; ``id_map`` maps donor block id
        -> local block id (freshly allocated, refcount 1, KV bytes
        already imported via the checksummed ``import_blocks`` path).
        A node whose key is already taken (or whose parent failed to
        seed) frees its local block back to the pool.  Returns how
        many blocks were seeded."""
        seeded = 0
        for parent, chunk, src_blk in nodes:
            dst = id_map[src_blk]
            dst_parent = ROOT if parent == ROOT \
                else id_map.get(parent, -1)
            ok = False
            if dst_parent != -1 and (dst_parent == ROOT
                                     or dst_parent in self._key_of):
                ok = self.register(dst_parent, tuple(chunk), dst)
            if ok:
                seeded += 1
                # drop our alloc ref: the release hook parks the
                # registered block in the evictable LRU — warm, free
                # to reclaim, exactly like a finished request's prefix
                self.allocator.free([dst])
            else:
                del id_map[src_blk]     # descendants must not chain
                self.allocator.free([dst])  # unregistered -> free list
        return seeded

    # -- promotion (serving/offload) ---------------------------------------

    def promote(self, tokens: List[int], matched: List[int],
                alloc_fn) -> int:
        """Extend a :meth:`match` run with blocks re-materialized
        from the offload store — the host/disk -> device tier
        crossing, called by the scheduler at admission time right
        after the device-tier walk stops.  Continues the radix walk
        by CONTENT hash: each missing chunk's chain hash is probed in
        the store, imported through the checksummed ``import_blocks``
        path into a fresh device block (``alloc_fn``, the scheduler's
        evicting allocator — colder LRU holds may demote to make
        room), registered, and appended to ``matched`` with the same
        one-ref-per-block contract :meth:`match` gives.

        Every failure mode degrades to cold prefill, never to wrong
        output: a store miss or full pool stops the walk; a checksum
        reject discards the corrupt payload whole (``crc_rejects``);
        a transient import OOM puts every payload back for next time
        (``capacity_skips``).  Returns how many blocks promoted.

        The walk is two-staged for dispatch economy: stage 1 probes /
        integrity-checks / allocates per chunk host-side (crc32 over a
        few KB each — the torn-spill reject happens HERE, before any
        device or radix state moves), stage 2 scatters the whole
        collected run through ONE batched ``import_blocks`` launch —
        a 20-block promote costs one device dispatch, not 20."""
        if self._offload is None:
            return 0
        bs = self.block_size
        total = len(tokens) // bs
        if len(matched) >= total:
            return 0
        parent = matched[-1] if matched else ROOT
        ph = (_ROOT_HASH if parent == ROOT
              else self._hash_of.get(parent))
        if ph is None:
            return 0
        t0 = self._clock()
        # -- stage 1: walk the chain, collect verified payloads ------
        pending = []            # (hash, chunk, payload, tier)
        for i in range(len(matched), total):
            chunk = tuple(tokens[i * bs:(i + 1) * bs])
            h = _chunk_hash(ph, chunk)
            hit = self._offload.take(h)
            if hit is None:
                break
            payload, tier = hit
            try:
                verify_payload(payload)
            except ValueError:
                # checksum reject: the payload is corrupt — discard
                # it WHOLE (re-storing it would re-fail forever) and
                # fall back to cold prefill, bit-identically
                self._off_counters.incr("crc_rejects")
                break
            pending.append((h, chunk, payload, tier))
            ph = h
        if not pending:
            return 0
        # -- stage 2: one bulk alloc (one batched demote-eviction on
        # the way, when the pool is tight), one batched import ------
        fresh = alloc_fn(len(pending))
        if fresh is None:
            # pool dry even after eviction: keep the payloads warm
            # for a later admission, cold-prefill this one
            for h, _, payload, _ in pending:
                self._offload.put(h, payload)
            self._off_counters.incr("capacity_skips")
            return 0
        try:
            self._importer(fresh, merge_payloads(
                [p[2] for p in pending]))
        except MemoryError:
            # transient device OOM mid-import: the payloads are still
            # good — put them all back and retry next admission
            self.allocator.free(fresh)
            for h, _, payload, _ in pending:
                self._offload.put(h, payload)
            self._off_counters.incr("capacity_skips")
            return 0
        except ValueError:
            # belt-and-braces: stage 1 already verified the stored
            # checksums, so a reject here means the bytes rotted
            # in-flight — discard, cold-prefill
            self.allocator.free(fresh)
            self._off_counters.incr("crc_rejects")
            return 0
        except TransportError:
            # the transport exhausted its envelope (retries, deadline,
            # or an open breaker): the payloads are still good — put
            # them back for a later admission and cold-prefill this
            # one, exactly like the capacity path
            self.allocator.free(fresh)
            for h, _, payload, _ in pending:
                self._offload.put(h, payload)
            self._off_counters.incr("transport_skips")
            return 0
        promoted = 0
        parent = matched[-1] if matched else ROOT
        for j, (_, chunk, _, tier) in enumerate(pending):
            blk = fresh[j]
            if not self.register(parent, chunk, blk):
                # cannot happen on a single-threaded walk (the chain
                # was missing moments ago), but never leak: free this
                # block and every unregistered one behind it
                self.allocator.free(fresh[j:])
                break
            matched.append(blk)
            self._off_counters.incr(
                "promotes_host" if tier == "host" else "promotes_disk")
            promoted += 1
            parent = blk
        if promoted and self._promote_hist is not None:
            self._promote_hist.record(self._clock() - t0)
        return promoted

    # -- eviction ---------------------------------------------------------

    def evict(self, n: int = 1) -> int:
        """Reclaim at least ``n`` blocks from the evictable LRU
        (oldest first) back to the allocator's free list, cascading
        each victim's registered subtree.  Returns how many blocks
        actually freed (0 = nothing evictable)."""
        freed = 0
        while freed < n and self._lru:
            blk = next(iter(self._lru))
            freed += self._evict_subtree(blk)
        self._flush_demotes()
        if freed:
            self.counters.incr("prefix_evicted_blocks", freed)
        return freed

    def _evict_subtree(self, blk: int) -> int:
        """Unregister ``blk`` and every registered descendant; free the
        ones sitting in the LRU (a descendant still referenced by a
        live table merely loses shareability)."""
        freed = 0
        for child in list(self._children.get(blk, ())):
            freed += self._evict_subtree(child)
        h = self._hash_of.get(blk)    # before _unregister drops it
        self._unregister(blk)
        if blk in self._lru:
            del self._lru[blk]
            if self._offload is not None and h is not None:
                self._demote_pending.append((blk, h))
            self.allocator.release_to_free(blk)
            freed += 1
        return freed

    def _flush_demotes(self) -> None:
        """Export every block the eviction pass just victimized into
        the offload store in ONE batched device gather — the device
        -> host tier crossing (docs/serving.md, "Hierarchical KV
        offload").  Safe after ``release_to_free``: freed slots'
        KV bytes stay untouched until an engine call re-writes them,
        and the flush runs before :meth:`evict` returns the ids to
        the allocator's caller.  Each block is stored under its own
        content hash with the crc the engine recorded for it
        (``offload.split_payload``).  A transient export OOM drops
        the whole batch (the blocks die exactly as they did before
        offload existed — never an error path)."""
        pending, self._demote_pending = self._demote_pending, []
        if not pending:
            return
        try:
            payload = self._exporter([blk for blk, _ in pending])
        except MemoryError:
            self._off_counters.incr("demote_failed", len(pending))
            return
        for (_, h), sub in zip(pending, split_payload(payload)):
            self._offload.put(h, sub)
        self._off_counters.incr("demotes", len(pending))

    def _unregister(self, blk: int):
        self._hash_of.pop(blk, None)
        key = self._key_of.pop(blk, None)
        if key is None:
            return
        del self._map[key]
        kids = self._children.get(key[0])
        if kids is not None:
            kids.discard(blk)
            if not kids:
                del self._children[key[0]]
        self._children.pop(blk, None)

    # -- invariants (tests + bench) ---------------------------------------

    def audit(self):
        """Index consistency: map/key_of are inverse bijections, chain
        parents are indexed (or ROOT), LRU holds are registered and
        ref-0, and no registered block is on the free list."""
        assert len(self._map) == len(self._key_of)
        for key, blk in self._map.items():
            assert self._key_of.get(blk) == key
            parent = key[0]
            assert parent == ROOT or parent in self._key_of, \
                f"block {blk} chained to unindexed parent {parent}"
            assert blk in self._children.get(parent, ()), \
                f"block {blk} missing from parent {parent}'s children"
        for blk in self._lru:
            assert blk in self._key_of, f"unregistered LRU hold {blk}"
            assert self.allocator.refs(blk) == 0, \
                f"LRU hold {blk} has refs {self.allocator.refs(blk)}"
        for blk in self._key_of:
            assert blk not in self.allocator._free_set, \
                f"registered block {blk} is on the free list"
        for blk in self._hash_of:
            assert blk in self._key_of, \
                f"chain hash held for unregistered block {blk}"
        assert not self._demote_pending, \
            "demote batch not flushed by the evict pass"
