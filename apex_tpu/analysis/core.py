"""apexlint core — AST-level invariant analysis for the serving stack.

Every guarantee the serving stack makes is enforced *dynamically*
today: the chaos soak's bit-exact-replay oracle, the compile-count
audits, the pinned-stats tests.  A soak only catches the instance a
seed happens to exercise; the invariants themselves — counter-keyed
determinism, zero host syncs between LAUNCH and RETIRE, one trace per
bucket, RLock-guarded ops access — are *statically checkable
properties of the source*.  This package checks them at the AST
level, the same move the reference Apex makes for mixed precision
(``amp.lists`` is a static whitelist/blacklist classification pass
deciding casts before execution — PAPER.md): classify the code, not
the execution.

This module is the rule-agnostic substrate (``docs/analysis.md``):

- :class:`SourceModule` — one parsed file: the AST, an import-alias
  map (so ``np.asarray`` / ``numpy.asarray`` / ``from numpy import
  asarray`` all resolve to ``numpy.asarray``), and the inline-pragma
  index (``# apexlint: disable=RULE`` on a line, a ``def``/``class``
  header, or the comment line above one; ``disable-file=RULE`` for
  the whole file).
- :class:`Finding` — one diagnostic: ``path:line [rule] message``.
- :class:`Baseline` — the accepted-findings file
  (``apex_tpu/analysis/baseline.json``): every entry carries a
  written ``justification``; matching is count-aware on
  (rule, path, message) so line drift never churns it.
- :class:`AnalysisConfig` / :func:`load_config` — the
  ``[tool.apexlint]`` block of ``pyproject.toml`` (rule
  enable/disable, path excludes, per-rule options), parsed by a
  dependency-free TOML-subset reader (this interpreter predates
  ``tomllib``), so CI and local runs read one source of truth.
- :func:`run` — walk files, run every enabled rule in scope, apply
  pragma suppression, return sorted findings.

Deliberately **stdlib-only** (``ast`` + ``json``): the linter must
run in any environment that can read the source, without importing
jax or the package under analysis.  Intra-package imports are
relative so ``tools/apexlint.py`` can load it standalone.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# the rule id every file-parse failure is reported under (always
# enabled: an unparseable file silently skipped would un-lint itself)
PARSE_RULE = "parse-error"

DEFAULT_BASELINE = "apex_tpu/analysis/baseline.json"

_PRAGMA_RE = re.compile(
    r"#\s*apexlint:\s*(disable-file|disable)\s*=\s*"
    r"([A-Za-z0-9_,\- ]+?)(?=\s*(?:—|--|#|$))")

_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``key()`` deliberately omits the line number:
    baseline matching survives unrelated edits shifting code."""

    rule: str
    path: str                      # repo-relative, posix separators
    line: int
    message: str
    col: int = 0

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "col": self.col,
                "message": self.message}


class SourceModule:
    """One parsed source file plus the resolution context rules need:
    import aliases, pragma suppression spans, and the raw lines."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace("\\", "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        self.aliases: Dict[str, str] = {}
        self._file_rules: Set[str] = set()
        self._line_rules: Dict[int, Set[str]] = {}
        self._span_rules: List[Tuple[int, int, Set[str]]] = []
        self._build_aliases()
        self._build_pragmas()

    @classmethod
    def from_file(cls, path: Path, root: Path) -> "SourceModule":
        return cls(relpath_under(path, root), path.read_text())

    @classmethod
    def from_source(cls, text: str, relpath: str) -> "SourceModule":
        """Test fixture entry: analyze an inline snippet as if it
        lived at ``relpath`` (rule path scoping keys on it)."""
        return cls(relpath, text)

    # -- alias resolution --------------------------------------------------

    def _build_aliases(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}")

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, resolved
        through the module's import aliases (``np.asarray`` →
        ``numpy.asarray``); None when the chain is not rooted at a
        plain name (``self.x``, calls, subscripts)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        return ".".join([base] + parts[::-1])

    # -- pragma suppression ------------------------------------------------

    def _def_spans(self) -> Dict[int, Tuple[int, int]]:
        spans: Dict[int, Tuple[int, int]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                spans[node.lineno] = (node.lineno, node.end_lineno
                                      or node.lineno)
        return spans

    def _build_pragmas(self) -> None:
        spans = self._def_spans()
        for i, raw in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(raw)
            if not m:
                continue
            kind = m.group(1)
            # comma-separated rule ids; anything after whitespace in
            # a segment is justification text, not a rule name
            rules = {r.split()[0] for r in m.group(2).split(",")
                     if r.split()}
            if kind == "disable-file":
                self._file_rules |= rules
                continue
            target = i
            if _COMMENT_ONLY_RE.match(raw):
                target = i + 1        # comment line governs the next
            span = spans.get(target)
            if span is not None:
                self._span_rules.append((span[0], span[1], rules))
            self._line_rules.setdefault(i, set()).update(rules)
            self._line_rules.setdefault(target, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_rules or "all" in self._file_rules:
            return True
        at = self._line_rules.get(line, ())
        if rule in at or "all" in at:
            return True
        for lo, hi, rules in self._span_rules:
            if lo <= line <= hi and (rule in rules or "all" in rules):
                return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       message=message)


# -- configuration (the [tool.apexlint] block) ----------------------------


@dataclass
class AnalysisConfig:
    """What to run, where, and what's accepted — one object shared by
    the CLI, the build-matrix axis, and the L0 clean-repo test."""

    root: Path
    enable: Optional[List[str]] = None     # None = every registered rule
    exclude: List[str] = field(default_factory=list)
    baseline: str = DEFAULT_BASELINE
    rule_options: Dict[str, dict] = field(default_factory=dict)

    def enabled_rules(self, registry: Dict[str, object],
                      only: Optional[Sequence[str]] = None) -> List[str]:
        names = list(self.enable) if self.enable is not None \
            else sorted(registry)
        unknown = [n for n in names if n not in registry]
        if unknown:
            raise KeyError(f"unknown rule(s) in config: {unknown}; "
                           f"known: {sorted(registry)}")
        if only:
            bad = [n for n in only if n not in registry]
            if bad:
                raise KeyError(f"unknown rule(s): {bad}; "
                               f"known: {sorted(registry)}")
            names = [n for n in names if n in set(only)]
        return names

    def options_for(self, rule) -> dict:
        merged = dict(rule.default_options)
        merged.update(self.rule_options.get(rule.name, {}))
        return merged


def _strip_comment(line: str) -> str:
    out, quote = [], None
    for ch in line:
        if quote:
            out.append(ch)
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
    return "".join(out)


def _parse_scalar(text: str):
    text = text.strip()
    if text.startswith(("\"", "'")):
        return text[1:-1]
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text


def _split_array_items(inner: str) -> List[str]:
    items, depth, quote, cur = [], 0, None, []
    for ch in inner:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch == "[":
            depth += 1
            cur.append(ch)
        elif ch == "]":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        items.append("".join(cur))
    return [i.strip() for i in items if i.strip()]


def _parse_value(text: str):
    text = text.strip()
    if text.startswith("["):
        return [_parse_value(i)
                for i in _split_array_items(text[1:-1])]
    return _parse_scalar(text)


def _header_parts(header: str) -> List[str]:
    parts, cur, quote = [], [], None
    for ch in header:
        if quote:
            if ch == quote:
                quote = None
            else:
                cur.append(ch)
        elif ch in "\"'":
            quote = ch
        elif ch == ".":
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur).strip())
    return [p for p in parts if p]


def parse_toml_tables(text: str) -> Dict[str, dict]:
    """A TOML-subset reader for ``pyproject.toml``'s apexlint block:
    ``[dotted."quoted".headers]`` + ``key = scalar-or-string-array``
    (arrays may span lines).  Not a general TOML parser — just enough
    for configuration this repo writes, with zero dependencies on an
    interpreter that predates ``tomllib``."""
    tables: Dict[str, dict] = {}
    current: Optional[dict] = None
    pending_key, pending_val = None, None
    for raw in text.splitlines():
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if pending_key is not None:
            pending_val += " " + line
            if pending_val.count("[") == pending_val.count("]"):
                current[pending_key] = _parse_value(pending_val)
                pending_key = pending_val = None
            continue
        if line.startswith("["):
            name = ".".join(_header_parts(line.strip("[]")))
            current = tables.setdefault(name, {})
            continue
        if current is None or "=" not in line:
            continue
        key, val = line.split("=", 1)
        key = key.strip().strip("\"'")
        val = val.strip()
        if val.startswith("[") and val.count("[") != val.count("]"):
            pending_key, pending_val = key, val
            continue
        current[key] = _parse_value(val)
    return tables


def load_config(root: Path,
                pyproject: Optional[Path] = None) -> AnalysisConfig:
    """The shared config entry: ``[tool.apexlint]`` (+ per-rule
    ``[tool.apexlint."<rule>"]`` sub-tables) from the repo's
    pyproject.  A missing file or block yields defaults."""
    root = Path(root)
    path = pyproject if pyproject is not None else root / "pyproject.toml"
    cfg = AnalysisConfig(root=root)
    if not Path(path).exists():
        return cfg
    tables = parse_toml_tables(Path(path).read_text())
    top = tables.get("tool.apexlint", {})
    if "enable" in top:
        cfg.enable = list(top["enable"])
    if "exclude" in top:
        cfg.exclude = list(top["exclude"])
    if "baseline" in top:
        cfg.baseline = str(top["baseline"])
    prefix = "tool.apexlint."
    for name, table in tables.items():
        if name.startswith(prefix):
            cfg.rule_options[name[len(prefix):]] = dict(table)
    return cfg


# -- baseline -------------------------------------------------------------


class Baseline:
    """The accepted-findings ledger.  Every entry must carry a
    human-written ``justification`` (the L0 tier asserts it); matching
    is count-aware on (rule, path, message) so identical findings on
    N lines need N entries, while pure line drift costs nothing."""

    def __init__(self, entries: Optional[List[dict]] = None,
                 path: Optional[Path] = None):
        self.entries = list(entries or [])
        self.path = path

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        path = Path(path)
        if not path.exists():
            return cls([], path=path)
        data = json.loads(path.read_text())
        return cls(list(data.get("findings", [])), path=path)

    def match(self, findings: Sequence[Finding]):
        """Split ``findings`` into (new, accepted) and report stale
        baseline entries that matched nothing (fixed code whose
        suppression should be deleted)."""
        budget: Dict[Tuple[str, str, str], int] = {}
        for e in self.entries:
            k = (e.get("rule", ""), e.get("path", ""),
                 e.get("message", ""))
            budget[k] = budget.get(k, 0) + 1
        new, accepted = [], []
        for f in findings:
            if budget.get(f.key(), 0) > 0:
                budget[f.key()] -= 1
                accepted.append(f)
            else:
                new.append(f)
        stale = [k for k, n in budget.items() if n > 0
                 for _ in range(n)]
        return new, accepted, stale

    def write(self, findings: Sequence[Finding], path: Path) -> None:
        """``--update-baseline``: rewrite with the current findings,
        keeping existing justifications for entries that still match
        and stamping ``TODO: justify`` on new ones (the L0 baseline
        test fails until a human replaces it)."""
        just: Dict[Tuple[str, str, str], List[str]] = {}
        for e in self.entries:
            k = (e.get("rule", ""), e.get("path", ""),
                 e.get("message", ""))
            just.setdefault(k, []).append(
                e.get("justification", ""))
        out = []
        for f in sorted(findings,
                        key=lambda f: (f.path, f.line, f.rule)):
            pool = just.get(f.key(), [])
            j = pool.pop(0) if pool else "TODO: justify"
            out.append({"rule": f.rule, "path": f.path,
                        "line": f.line, "message": f.message,
                        "justification": j})
        payload = {"version": 1, "findings": out}
        Path(path).write_text(json.dumps(payload, indent=2,
                                         sort_keys=True) + "\n")


# -- driving --------------------------------------------------------------


def relpath_under(path: Path, root: Path) -> str:
    """Repo-relative posix path, or the absolute posix path for files
    outside the root (scratch fixtures still analyze; rule scoping
    then matches on any path component via fnmatch patterns or the
    suffix-matching in :func:`in_scope`)."""
    try:
        return Path(path).resolve().relative_to(
            Path(root).resolve()).as_posix()
    except ValueError:
        return Path(path).resolve().as_posix()


def in_scope(relpath: str, prefixes: Sequence[str]) -> bool:
    """Path-scope check shared by every rule: ``prefixes`` entries are
    repo-relative file paths, directory prefixes, or fnmatch
    patterns."""
    rooted = "/" + relpath
    for p in prefixes:
        p = p.rstrip("/")
        if relpath == p or relpath.startswith(p + "/") \
                or fnmatch.fnmatch(relpath, p):
            return True
        # absolute scratch paths (test fixtures under /tmp) match the
        # scope as a path infix/suffix
        if rooted.endswith("/" + p) or ("/" + p + "/") in rooted:
            return True
    return False


def iter_source_files(paths: Sequence[Path],
                      config: AnalysisConfig) -> Iterable[Path]:
    seen = set()
    for p in paths:
        p = Path(p)
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts or f.suffix != ".py":
                continue
            rel = relpath_under(f, config.root)
            if any(fnmatch.fnmatch(rel, pat) or in_scope(rel, [pat])
                   for pat in config.exclude):
                continue
            if f not in seen:
                seen.add(f)
                yield f


def run(paths: Sequence[Path], config: AnalysisConfig,
        registry: Dict[str, object],
        rule_names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Analyze every file under ``paths`` with the enabled rules whose
    path scope matches; pragma-suppressed findings are dropped and the
    rest deduplicated per (rule, path, line) and sorted."""
    names = config.enabled_rules(registry, rule_names)
    findings: List[Finding] = []
    for f in iter_source_files(paths, config):
        try:
            mod = SourceModule.from_file(f, config.root)
        except SyntaxError as e:
            findings.append(Finding(
                rule=PARSE_RULE,
                path=relpath_under(f, config.root),
                line=e.lineno or 0,
                message=f"file does not parse: {e.msg}"))
            continue
        for name in names:
            rule = registry[name]
            opts = config.options_for(rule)
            if not in_scope(mod.relpath, opts.get("paths", ["."])):
                continue
            for finding in rule.check(mod, opts):
                if not mod.suppressed(finding.rule, finding.line):
                    findings.append(finding)
    deduped: Dict[Tuple[str, str, int], Finding] = {}
    for f in findings:
        deduped.setdefault((f.rule, f.path, f.line), f)
    return sorted(deduped.values(),
                  key=lambda f: (f.path, f.line, f.rule))
