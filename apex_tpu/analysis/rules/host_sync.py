"""``host-sync`` — no hidden device→host syncs in PLAN/LAUNCH code.

The invariant (PR 8, docs/serving.md "Pipelined serve loop"): between
LAUNCH and the next RETIRE the host must make every scheduling
decision *without materializing a device value*.  One stray
``np.asarray(ids)`` / ``.item()`` / ``float(x)`` on a traced value
blocks the host on the device step it just dispatched — the loop is
silently synchronous again and the ~17% overlap win evaporates, with
no test failing (output is bit-identical either way; only the chaos
soak's wall clock notices, and only if someone reads it).

Two tiers:

1. Inside the **hot functions** (the PLAN/LAUNCH body of
   ``InferenceServer._step`` and the launch helpers, plus every
   jitted program body — ``*_impl`` — where a host-numpy call means a
   concretization during trace): flag ``.item()`` / ``.tolist()`` /
   ``.block_until_ready()``, host-numpy materializers
   (``np.asarray`` / ``np.array`` / ``np.all`` / ``np.any`` /
   ``np.isfinite`` / ``np.argmax``), and ``float()/int()/bool()``
   over non-literal expressions (implicit scalar materialization —
   the same class as implicit array truthiness).
2. Anywhere in the scoped modules: ``jax.device_get`` /
   ``jax.block_until_ready`` — unconditional syncs that belong only
   in the documented RETIRE path (``allow_functions``).

Legitimate sync points carry ``# apexlint: disable=host-sync`` with a
justification (e.g. the prefill token that gates same-iteration
decode admission is synchronous *by design*).
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, SourceModule, in_scope

name = "host-sync"
summary = ("device→host syncs reachable from PLAN/LAUNCH re-serialize "
           "the pipelined serve loop")

default_options = {
    "paths": ["apex_tpu/serving/api.py", "apex_tpu/serving/engine.py"],
    # PLAN/LAUNCH bodies; every *_impl function (the jitted program
    # bodies) is hot implicitly via impl_suffix
    "hot_functions": ["_step", "_launch_decode", "_launch_verify",
                      "_decode_inputs", "_verify_inputs"],
    "impl_suffix": "_impl",
    # the documented RETIRE/materialization points, exempt from the
    # module-wide device_get/block_until_ready tier
    "allow_functions": ["_flush_window"],
}

_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_MATERIALIZERS = {"asarray", "array", "all", "any", "isfinite",
                        "argmax"}
_SCALAR_BUILTINS = {"float", "int", "bool"}


def _is_host_literalish(node: ast.AST) -> bool:
    """Expressions that cannot hold a device value: literals, len(),
    pure arithmetic over those, and attribute reads of shapes."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("len", "min", "max", "round"):
        return True
    if isinstance(node, ast.BinOp):
        return (_is_host_literalish(node.left)
                and _is_host_literalish(node.right))
    if isinstance(node, ast.UnaryOp):
        return _is_host_literalish(node.operand)
    if isinstance(node, ast.Attribute) and node.attr in ("shape",
                                                         "ndim", "size"):
        return True
    if isinstance(node, ast.Subscript):
        return _is_host_literalish(node.value)
    return False


def check(mod: SourceModule, options: dict) -> List[Finding]:
    findings: List[Finding] = []
    hot = set(options.get("hot_functions", ()))
    impl_suffix = options.get("impl_suffix", "_impl")
    allow = set(options.get("allow_functions", ()))

    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        is_hot = fn.name in hot or (impl_suffix
                                    and fn.name.endswith(impl_suffix))
        in_impl = bool(impl_suffix) and fn.name.endswith(impl_suffix)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func)
            if resolved in _SYNC_CALLS and fn.name not in allow:
                findings.append(mod.finding(
                    name, node,
                    f"{resolved} is an unconditional device sync; "
                    f"only the RETIRE path "
                    f"({', '.join(sorted(allow)) or 'none'}) may "
                    f"materialize launched results"))
                continue
            if not is_hot:
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_METHODS \
                    and not node.args:
                findings.append(mod.finding(
                    name, node,
                    f".{node.func.attr}() materializes a device "
                    f"value inside a PLAN/LAUNCH section; move it to "
                    f"RETIRE or justify with a pragma"))
                continue
            if resolved and resolved.startswith("numpy.") \
                    and resolved.split(".", 1)[1] in \
                    _NUMPY_MATERIALIZERS:
                where = ("inside a jitted program body (a "
                         "concretization error waiting for a traced "
                         "input)" if in_impl
                         else "inside a PLAN/LAUNCH section (blocks "
                         "the host on the in-flight device step)")
                findings.append(mod.finding(
                    name, node,
                    f"{resolved} on a potentially traced value "
                    f"{where}"))
                continue
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _SCALAR_BUILTINS \
                    and len(node.args) == 1 \
                    and not _is_host_literalish(node.args[0]):
                findings.append(mod.finding(
                    name, node,
                    f"{node.func.id}(...) over a non-literal in a "
                    f"PLAN/LAUNCH section is an implicit scalar "
                    f"materialization (same class as array "
                    f"truthiness); keep decisions on host state or "
                    f"move to RETIRE"))
    return findings


def applies(relpath: str, options: dict) -> bool:
    return in_scope(relpath, options.get("paths", []))
