"""``lock-discipline`` — cross-thread state goes through the RLock.

The invariant (PR 9, docs/observability.md "Ops plane & watchdog"):
while an ops plane is attached, ``step()`` / ``submit()`` / ``stats()``
serialize through ``OpsServer.lock``, and the handler threads reach
server state only under that lock — except the two *documented*
lock-free paths (``/healthz``, ``/metrics``), which must stay
answerable while the serve loop is wedged holding it.  The same
contract covers the watchdog thread's stall handler and the router
fleet's front-door/ops methods (``RouterFleet`` takes the fleet ops
lock around placement and stats).

The rule builds an attribute-access map per configured class: inside
each **thread method** (a method that runs on a foreign thread —
HTTP handler, watchdog, client caller), every attribute read/write
rooted at the class's **state expression** (``self`` for the servers,
``self.server`` for the ops plane, followed through local aliases
like ``srv = self.server`` and ``sched = srv.scheduler``) must be
lexically inside ``with self.<lock>`` (the ``with (self._ops_lock or
_NO_LOCK)`` spelling counts).  Documented lock-free paths carry
``# apexlint: disable=lock-discipline`` with the justification.

Class specs are configurable (``[tool.apexlint."lock-discipline"]``
``classes`` as ``"Class:lock:state:method,method"`` strings) so new
threaded surfaces opt in as they land.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, SourceModule, in_scope

name = "lock-discipline"
summary = ("cross-thread attribute access outside the documented "
           "RLock path races the step loop")

# "Class:lock_attr:state_expr:method,method,..." — state_expr is
# "self" or "self.<attr>" (the object whose attributes are the
# cross-thread state)
DEFAULT_CLASSES = [
    "OpsServer:lock:self.server:"
    "_handle,_healthz,_flight,_request,_drain,_postmortem",
    "InferenceServer:_ops_lock:self:_on_watchdog_stall",
    "RouterFleet:_ops_lock:self:"
    "submit,stats,drain,drain_replica,replica_drained,revive,close",
    "ReplicaRouter:_ops_lock:self:",
]

default_options = {
    "paths": ["apex_tpu/serving", "apex_tpu/observability"],
    "classes": DEFAULT_CLASSES,
}


def _parse_specs(specs) -> Dict[str, dict]:
    out = {}
    for s in specs:
        cls, lock, state, methods = (s.split(":") + ["", "", ""])[:4]
        out[cls] = {
            "lock": lock,
            "state": state or "self",
            "methods": [m for m in methods.split(",") if m],
        }
    return out


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['self', 'server', 'scheduler'] for ``self.server.scheduler``;
    None when not a pure name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class _MethodChecker(ast.NodeVisitor):
    """Walk one thread method tracking (a) whether the lexical
    position is under ``with self.<lock>`` and (b) local aliases of
    the state expression, flagging unguarded state access."""

    def __init__(self, mod: SourceModule, spec: dict, cls_name: str,
                 method: ast.FunctionDef, findings: List[Finding]):
        self.mod = mod
        self.spec = spec
        self.cls = cls_name
        self.method = method
        self.findings = findings
        self.locked = 0
        # names aliasing the guarded object (or sub-objects of it)
        self.state_aliases: Set[str] = set()
        state = spec["state"].split(".")
        self.state_chain = state          # ["self"] or ["self","server"]

    # -- state rooting ------------------------------------------------------

    def _is_state_rooted(self, chain: Optional[List[str]]) -> bool:
        if not chain:
            return False
        if chain[:2] == ["self", self.spec["lock"]]:
            return False              # the lock itself is not state
        if chain[0] in self.state_aliases:
            return True
        n = len(self.state_chain)
        return chain[:n] == self.state_chain and len(chain) > n

    def _is_lock_expr(self, node: ast.AST) -> bool:
        """``self.<lock>`` — possibly wrapped in the ``(self._ops_lock
        or _NO_LOCK)`` BoolOp spelling."""
        if isinstance(node, ast.BoolOp):
            return any(self._is_lock_expr(v) for v in node.values)
        chain = _attr_chain(node)
        return bool(chain) and len(chain) == 2 \
            and chain[0] == "self" and chain[1] == self.spec["lock"]

    # -- visitors -----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        holds = any(self._is_lock_expr(item.context_expr)
                    for item in node.items)
        for item in node.items:
            if not self._is_lock_expr(item.context_expr):
                self.visit(item.context_expr)
        if holds:
            self.locked += 1
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self.locked -= 1

    def visit_Assign(self, node: ast.Assign) -> None:
        chain = _attr_chain(node.value)
        # aliasing the state root itself (``srv = self.server``) or a
        # sub-object of it (``sched = srv.scheduler``) taints the name
        if chain and (chain == self.state_chain
                      or self._is_state_rooted(chain)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.state_aliases.add(tgt.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # ``self.other_method(...)`` is delegation, not state access:
        # the callee is auditable on its own (and self-locks when it
        # must).  Only same-object single-hop calls qualify — a call
        # THROUGH guarded state (``self.server.stats()``) is still a
        # state read of the receiver chain.
        if (self.state_chain == ["self"]
                and isinstance(node.func, ast.Attribute)
                and _attr_chain(node.func) == ["self",
                                               node.func.attr]):
            for arg in node.args:
                self.visit(arg)
            for kw in node.keywords:
                self.visit(kw.value)
            return
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        chain = _attr_chain(node)
        if self.locked == 0 and self._is_state_rooted(chain):
            verb = ("write" if isinstance(node.ctx,
                                          (ast.Store, ast.Del))
                    else "read")
            self.findings.append(self.mod.finding(
                name, node,
                f"{self.cls}.{self.method.name}() runs on a foreign "
                f"thread but {verb}s {'.'.join(chain)} outside "
                f"'with self.{self.spec['lock']}': races the step "
                f"loop — take the lock, or document the lock-free "
                f"contract with a pragma"))
            return                     # one finding per chain root
        self.generic_visit(node)


def check(mod: SourceModule, options: dict) -> List[Finding]:
    findings: List[Finding] = []
    specs = _parse_specs(options.get("classes", DEFAULT_CLASSES))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef) or node.name not in specs:
            continue
        spec = specs[node.name]
        methods = {m.name: m for m in node.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        for mname in spec["methods"]:
            m = methods.get(mname)
            if m is None:
                continue
            checker = _MethodChecker(mod, spec, node.name, m,
                                     findings)
            for stmt in m.body:
                checker.visit(stmt)
    return findings


def applies(relpath: str, options: dict) -> bool:
    return in_scope(relpath, options.get("paths", []))
