"""``retrace`` — one trace per program/bucket, enforced at the source.

The invariant (PR 1's compile-count audit, hardened every PR since):
each engine program compiles once per (bucket, width) key, and
``stats()["programs"]`` + ``verify_compiles()`` audit the *count*
after the fact.  The audits catch a retrace storm only once a test
happens to drive the offending shape twice; the hazards themselves
are visible in the source:

- **Scalar arguments outside static_argnums.**  A Python
  scalar/``len(...)`` passed in a *dynamic* position traces as a
  weak-typed constant: drift between ``3`` and ``3.0`` (or an
  occasional ``np.int32``) silently forks the jit cache, and marking
  it static instead retraces per *value*.  The repo convention is to
  ship everything through one committed ``device_put`` struct
  (``DecodeEngine._put``) — flag literal/``len()`` args at non-static
  positions of known-jitted callables.
- **f-string-shaped arguments** — a string built per call
  (``JoinedStr``) in a jit argument is a new static value per
  formatting, a guaranteed per-call retrace.
- **Inline-jitted lambdas with free variables** — ``jax.jit(lambda
  x: x * scale)`` closes over ``scale`` at trace time; rebinding the
  name never retraces (stale constant) and an unhashable capture
  makes the cache miss every call.  Hoist to a named function taking
  the state as an argument.
- **Jitted functions reading module-level mutable state** — a dict /
  list / set global read inside a ``@jax.jit`` body is captured at
  trace time; later mutation is silently ignored (the PR-8 donation
  finding's cousin: invisible until someone diffs outputs).
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, SourceModule, in_scope

name = "retrace"
summary = ("jit call-site and closure patterns that fork or stale the "
           "trace cache behind the compile-count audits' back")

default_options = {
    "paths": ["apex_tpu/serving", "apex_tpu/ops"],
}

_MUTABLE_CTORS = {"dict", "list", "set", "collections.defaultdict",
                  "collections.deque", "collections.OrderedDict"}


def _is_jax_jit(node: ast.AST, mod: SourceModule) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` call inside ``node`` when node is
    ``jax.jit(...)`` itself or ``functools.partial(jax.jit, ...)``;
    None otherwise."""
    if not isinstance(node, ast.Call):
        return None
    fn = mod.resolve(node.func)
    if fn in ("jax.jit", "jit"):
        return node
    if fn in ("functools.partial", "partial") and node.args \
            and mod.resolve(node.args[0]) in ("jax.jit", "jit"):
        return node
    return None


def _static_spec(jit_call: ast.Call) -> Tuple[Set[int], Set[str], bool]:
    """(static positions, static names, fully_known): literal
    static_argnums/static_argnames pulled off the jit call.  Non-
    literal specs return fully_known=False and disable the call-site
    scalar check (conservative silence)."""
    nums: Set[int] = set()
    names: Set[str] = set()
    known = True
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, int):
                    nums.add(v.value)
                else:
                    known = False
        elif kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    names.add(v.value)
                else:
                    known = False
    return nums, names, known


def _scalar_arg(node: ast.AST) -> Optional[str]:
    """A description when ``node`` is a retrace-hazard argument —
    a bare Python numeric literal, a ``len(...)`` host int, or an
    f-string; None for anything else."""
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return f"Python scalar literal {node.value!r}"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "len":
        return "host int from len(...)"
    if isinstance(node, ast.JoinedStr):
        return "f-string (new static value per formatting)"
    return None


_BUILTIN_NAMES = set(dir(builtins))


def _lambda_free_names(lam: ast.Lambda, mod: SourceModule) -> List[str]:
    bound = {a.arg for a in (lam.args.args + lam.args.kwonlyargs
                             + lam.args.posonlyargs)}
    if lam.args.vararg:
        bound.add(lam.args.vararg.arg)
    if lam.args.kwarg:
        bound.add(lam.args.kwarg.arg)
    free = []
    for n in ast.walk(lam.body):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id not in bound and n.id not in mod.aliases \
                and n.id not in _BUILTIN_NAMES:
            free.append(n.id)
    return free


class _JitIndex:
    """Module-wide map of jitted callables: plain names (module defs
    and module-level assignments) and ``self.<attr>`` slots, each with
    its literal static spec and, when resolvable, the wrapped
    function's positional parameter names."""

    def __init__(self, mod: SourceModule):
        self.mod = mod
        self.by_name: Dict[str, dict] = {}
        self.by_attr: Dict[str, dict] = {}
        self.defs: Dict[str, ast.FunctionDef] = {
            n.name: n for n in ast.walk(mod.tree)
            if isinstance(n, ast.FunctionDef)}
        self.jit_bodies: List[ast.FunctionDef] = []
        self._build()

    def _spec_for(self, jit_call: ast.Call,
                  fn_node: Optional[ast.AST]) -> dict:
        nums, names, known = _static_spec(jit_call)
        params: Optional[List[str]] = None
        if isinstance(fn_node, ast.Name) \
                and fn_node.id in self.defs:
            fd = self.defs[fn_node.id]
            params = [a.arg for a in fd.args.args]
        elif isinstance(fn_node, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
            params = [a.arg for a in fn_node.args.args]
        if names and params is not None:
            nums |= {params.index(n) for n in names if n in params}
        elif names and params is None:
            known = False        # static-by-name at unknown positions
        return {"static_nums": nums, "static_names": names,
                "known": known, "params": params}

    def _build(self) -> None:
        for node in ast.walk(self.mod.tree):
            # X = jax.jit(f, ...) / X = partial(jax.jit, ...) and
            # self._x = jax.jit(...)
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                jit_call = _is_jax_jit(node.value, self.mod)
                if jit_call is None:
                    continue
                if self.mod.resolve(jit_call.func) in (
                        "functools.partial", "partial"):
                    wrapped = (jit_call.args[1]
                               if len(jit_call.args) > 1 else None)
                else:
                    wrapped = (jit_call.args[0]
                               if jit_call.args else None)
                spec = self._spec_for(jit_call, wrapped)
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    self.by_name[tgt.id] = spec
                elif isinstance(tgt, ast.Attribute) and isinstance(
                        tgt.value, ast.Name) and tgt.value.id == "self":
                    self.by_attr[tgt.attr] = spec
            # @jax.jit / @functools.partial(jax.jit, ...) decorators
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    jit_call = _is_jax_jit(dec, self.mod)
                    is_bare = self.mod.resolve(dec) in ("jax.jit",
                                                        "jit")
                    if jit_call is None and not is_bare:
                        continue
                    if jit_call is None:
                        spec = {"static_nums": set(),
                                "static_names": set(),
                                "known": True,
                                "params": [a.arg
                                           for a in node.args.args]}
                    else:
                        spec = self._spec_for(jit_call, node)
                    self.by_name[node.name] = spec
                    self.jit_bodies.append(node)
                    break

    def lookup(self, call: ast.Call) -> Optional[dict]:
        fn = call.func
        if isinstance(fn, ast.Name):
            return self.by_name.get(fn.id)
        if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name) and fn.value.id == "self":
            return self.by_attr.get(fn.attr)
        return None


def _mutable_globals(mod: SourceModule) -> Set[str]:
    out: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets
                       if isinstance(t, ast.Name)]
            v = node.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp,
                                     ast.SetComp))
            if isinstance(v, ast.Call) \
                    and mod.resolve(v.func) in _MUTABLE_CTORS:
                mutable = True
            if mutable:
                out.update(t.id for t in targets)
    return out


def check(mod: SourceModule, options: dict) -> List[Finding]:
    findings: List[Finding] = []
    index = _JitIndex(mod)
    mutables = _mutable_globals(mod)

    # (a) inline-jitted lambdas with free variables
    for node in ast.walk(mod.tree):
        jit_call = _is_jax_jit(node, mod)
        if jit_call is None:
            continue
        target = jit_call.args[0] if jit_call.args else None
        if mod.resolve(jit_call.func) in ("functools.partial",
                                          "partial"):
            target = jit_call.args[1] if len(jit_call.args) > 1 \
                else None
        if isinstance(target, ast.Lambda):
            free = _lambda_free_names(target, mod)
            if free:
                findings.append(mod.finding(
                    name, node,
                    f"inline-jitted lambda closes over "
                    f"{sorted(set(free))}: captured at trace time, "
                    f"never retraced on rebind (stale constant) — "
                    f"hoist to a named function and pass state as "
                    f"arguments"))

    # (b) jitted bodies reading module-level mutable state
    for body in index.jit_bodies:
        params = {a.arg for a in body.args.args}
        for n in ast.walk(body):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in mutables and n.id not in params:
                findings.append(mod.finding(
                    name, n,
                    f"jitted function {body.name}() reads module-"
                    f"level mutable {n.id!r}: captured once at trace "
                    f"time, later mutation silently ignored — pass "
                    f"it as an argument or freeze it"))

    # (c) call-site scalars outside static positions
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        spec = index.lookup(node)
        if spec is None or not spec["known"]:
            continue
        for i, arg in enumerate(node.args):
            desc = _scalar_arg(arg)
            if desc is None or i in spec["static_nums"]:
                continue
            findings.append(mod.finding(
                name, arg,
                f"{desc} passed at dynamic position {i} of a jitted "
                f"callable: weak-type/dtype drift forks the trace "
                f"cache behind the compile-count audit — ship a "
                f"committed device array (engine._put) or mark the "
                f"position static"))
        for kw in node.keywords:
            if kw.arg is None or kw.arg in spec["static_names"]:
                continue
            if spec["params"] is not None \
                    and kw.arg in spec["params"] \
                    and spec["params"].index(kw.arg) \
                    in spec["static_nums"]:
                continue
            desc = _scalar_arg(kw.value)
            if desc is not None:
                findings.append(mod.finding(
                    name, kw.value,
                    f"{desc} passed as dynamic keyword "
                    f"{kw.arg!r} of a jitted callable: weak-type/"
                    f"dtype drift forks the trace cache — ship a "
                    f"device array or mark it static"))
    return findings


def applies(relpath: str, options: dict) -> bool:
    return in_scope(relpath, options.get("paths", []))
