"""``donation`` — buffer donation must be backend-gated.

The invariant (PR 8, BENCH_NOTES r8): on the CPU backend a donated
jit call executes **synchronously** (measured 10.2ms call / 0.06ms
wait donated vs 0.08 / 10.5 plain) — donation re-serializes exactly
the dispatch-ahead overlap the pipelined loop exists for.  On TPU the
KV pool is the HBM hog and *must* donate for the in-place update.
The shipped pattern (``DecodeEngine.__init__``):

    donate = (1,) if jax.default_backend() != "cpu" else ()
    jax.jit(fn, donate_argnums=donate)

This rule flags ``jax.jit(..., donate_argnums=<literal>)`` — an
*unconditional* donation — unless the enclosing function (or the
module top level, for module-scope jits) visibly consults the
backend (``jax.default_backend()`` or a ``.platform`` attribute).
A donation spec that arrives as a name/expression is presumed
computed from such a gate and stays silent; ``donate_argnums=()``
is donation turned off and always fine.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, SourceModule, in_scope

name = "donation"
summary = ("unconditional donate_argnums serializes the CPU backend "
           "and defeats the pipelined loop's dispatch-ahead")

default_options = {
    "paths": ["apex_tpu"],
}


def _literal_donation(node: ast.AST) -> Optional[str]:
    """Repr of a literal, *non-empty* donate spec; None when the spec
    is computed (presumed gated) or empty (donation off)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return repr(node.value)
    if isinstance(node, (ast.Tuple, ast.List)):
        if not node.elts:
            return None                      # () — donation off
        if all(isinstance(e, ast.Constant) for e in node.elts):
            return ast.unparse(node)
    return None


def _has_backend_gate(scope: ast.AST, mod: SourceModule) -> bool:
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) \
                and mod.resolve(n.func) == "jax.default_backend":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "platform":
            return True
    return False


def check(mod: SourceModule, options: dict) -> List[Finding]:
    findings: List[Finding] = []
    # enclosing-function map: lineno spans -> function node
    funcs = [n for n in ast.walk(mod.tree)
             if isinstance(n, (ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = mod.resolve(node.func)
        if fn not in ("jax.jit", "jit") \
                and not (fn in ("functools.partial", "partial")
                         and node.args
                         and mod.resolve(node.args[0]) in ("jax.jit",
                                                           "jit")):
            continue
        for kw in node.keywords:
            if kw.arg != "donate_argnums":
                continue
            lit = _literal_donation(kw.value)
            if lit is None:
                continue
            enclosing = [f for f in funcs
                         if f.lineno <= node.lineno
                         <= (f.end_lineno or f.lineno)]
            scope: ast.AST = min(
                enclosing,
                key=lambda f: (f.end_lineno or f.lineno) - f.lineno,
            ) if enclosing else mod.tree
            if _has_backend_gate(scope, mod):
                continue
            findings.append(mod.finding(
                name, node,
                f"unconditional donate_argnums={lit}: a donated jit "
                f"call executes synchronously on the CPU backend "
                f"(BENCH_NOTES r8) and re-serializes the pipelined "
                f"loop — gate on jax.default_backend() like "
                f"DecodeEngine._jit"))
    return findings


def applies(relpath: str, options: dict) -> bool:
    return in_scope(relpath, options.get("paths", []))
