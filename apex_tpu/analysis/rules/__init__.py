"""apexlint rule registry (``docs/analysis.md``, "Adding a rule").

Each rule is a module exporting ``name`` (the pragma/CLI id),
``summary`` (one line for ``--list-rules``), ``default_options``
(must include ``paths`` — the repo-relative scope the rule runs
over; overridable per rule from ``[tool.apexlint."<name>"]``), and
``check(SourceModule, options) -> list[Finding]``.  Registering is
importing + listing here.
"""

from . import determinism, donation, host_sync, locks, retrace

_MODULES = (host_sync, determinism, retrace, locks, donation)

RULES = {m.name: m for m in _MODULES}

__all__ = ["RULES", "determinism", "donation", "host_sync", "locks",
           "retrace"]
