"""``determinism`` — decision-making code must be replayable.

The invariant (PR 5, ``resilience/chaos.py``): the chaos soak's
strongest oracle replays every healthy request against a fresh server
and demands *bit-exact* output, and PR 13 extended it to stochastic
sampling by keying every stream on ``(prompt, params, seed)`` counters.
Both collapse the moment any scheduling/failure decision under
``serving/`` or ``resilience/`` reads an unseeded RNG, the wall
clock, or hash-randomized iteration order:

- ``random.*`` module-level calls draw from the process-global RNG —
  seeded by whoever ran first, perturbed by any library; a decision
  made on it replays differently.  Use an owned, seeded
  ``random.Random(seed)`` (the ``ChaosSchedule``/``retry`` pattern).
- ``np.random.*`` legacy global calls, and *seedless*
  ``default_rng()`` / ``RandomState()`` constructions, same class.
- ``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()``
  called directly in decision code: deadlines and breaker windows
  must flow through the injectable-clock pattern (a ``clock=``
  parameter / ``self._clock`` attribute — every server, breaker,
  watchdog, and meter in this repo takes one) or fake-clock tests
  and replay can't pin them.  *References* (``clock=time.monotonic``
  as a default) are the pattern itself and are not flagged.
- Iterating a ``set`` (literal, ``set()``/``frozenset()`` call, set
  comprehension, or a local assigned from one — including through
  ``list()``/``tuple()``/``iter()``/``reversed()``) makes the visit
  order hash-randomized across processes (PYTHONHASHSEED): eviction
  scans, victim selection, and failover sweeps silently diverge
  between the soak and its replay.  ``sorted(...)`` restores a total
  order and is the sanctioned spelling.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..core import Finding, SourceModule, in_scope

name = "determinism"
summary = ("unseeded RNGs, direct wall-clock reads, and set-order "
           "iteration silently break the bit-exact replay oracle")

default_options = {
    "paths": ["apex_tpu/serving", "apex_tpu/resilience"],
}

_ALLOWED_RANDOM = {"random.Random", "random.SystemRandom",
                   "random.getstate", "random.setstate"}
_SEEDED_NP_CTORS = {"numpy.random.default_rng",
                    "numpy.random.RandomState",
                    "numpy.random.Generator"}
_TIME_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
               "time.time_ns", "time.monotonic_ns",
               "time.perf_counter_ns"}
_SET_CALLS = {"set", "frozenset"}
_ORDER_PRESERVERS = {"list", "tuple", "iter", "reversed"}


def _set_valued(node: ast.AST, local_sets: Dict[str, ast.AST],
                mod: SourceModule, depth: int = 0) -> bool:
    """Whether ``node`` evaluates to a set (or an order-preserving
    view of one).  ``sorted(...)`` breaks the chain — a sorted set is
    deterministic."""
    if depth > 6:
        return False
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = mod.resolve(node.func)
        if fn in _SET_CALLS:
            return True
        if fn in _ORDER_PRESERVERS and node.args:
            return _set_valued(node.args[0], local_sets, mod,
                               depth + 1)
        return False
    if isinstance(node, ast.Name):
        src = local_sets.get(node.id)
        if src is not None:
            return _set_valued(src, local_sets, mod, depth + 1)
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return (_set_valued(node.left, local_sets, mod, depth + 1)
                or _set_valued(node.right, local_sets, mod,
                               depth + 1))
    return False


def _walk_scope(scope: ast.AST):
    """Walk ``scope`` without descending into nested function scopes
    (their locals are theirs; each gets its own pass)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _local_set_assignments(scope: ast.AST,
                           mod: SourceModule) -> Dict[str, ast.AST]:
    """name -> value for simple assignments whose value is (possibly)
    a set; one level of scope-local dataflow."""
    out: Dict[str, ast.AST] = {}
    for node in _walk_scope(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.value
    return out


def _check_iteration(scope: ast.AST, mod: SourceModule,
                     findings: List[Finding]) -> None:
    local_sets = _local_set_assignments(scope, mod)
    iters: List[ast.AST] = []
    for node in _walk_scope(scope):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters.extend(g.iter for g in node.generators)
    for it in iters:
        if _set_valued(it, local_sets, mod):
            findings.append(mod.finding(
                name, it,
                "iteration over a set is hash-order-randomized "
                "across processes (PYTHONHASHSEED): a decision made "
                "in this order diverges between the soak and its "
                "bit-exact replay; wrap in sorted(...)"))


def check(mod: SourceModule, options: dict) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = mod.resolve(node.func)
        if resolved is None:
            continue
        if resolved.startswith("random.") \
                and resolved not in _ALLOWED_RANDOM:
            findings.append(mod.finding(
                name, node,
                f"{resolved}() draws from the process-global RNG; "
                f"replay cannot reproduce it — use an owned seeded "
                f"random.Random(seed) (the ChaosSchedule pattern)"))
        elif resolved.startswith("numpy.random."):
            if resolved in _SEEDED_NP_CTORS:
                if not node.args and not node.keywords:
                    findings.append(mod.finding(
                        name, node,
                        f"{resolved}() without a seed is entropy-"
                        f"seeded; pass an explicit seed so the "
                        f"replay oracle holds"))
            else:
                findings.append(mod.finding(
                    name, node,
                    f"{resolved}() uses numpy's global RNG; use a "
                    f"seeded default_rng(seed) generator instead"))
        elif resolved in _TIME_CALLS:
            findings.append(mod.finding(
                name, node,
                f"direct {resolved}() read in decision code; route "
                f"through the injectable clock (clock= parameter / "
                f"self._clock) so fake-clock tests and replay can "
                f"pin it"))
    scopes = [mod.tree] + [
        n for n in ast.walk(mod.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        _check_iteration(scope, mod, findings)
    return findings


def applies(relpath: str, options: dict) -> bool:
    return in_scope(relpath, options.get("paths", []))
