"""apexlint — repo-specific static analysis for apex_tpu invariants.

The serving stack's guarantees (bit-exact replay, dispatch-ahead
overlap, one-trace-per-bucket, RLock-guarded ops access) are enforced
dynamically by soaks and pinned tests; this package checks them
*statically*, at the AST level, so a regression is caught as a class
instead of as one seed's instance — the same move the reference
Apex's amp pillar makes with its whitelist/blacklist cast
classification (PAPER.md).

Entry points:

- ``python tools/apexlint.py [paths...]`` — the CLI (``--rule``,
  ``--json``, ``--baseline``, ``--update-baseline``; exit 1 on new
  findings).  The ``lint`` build-matrix axis and the L0 clean-repo
  test both run it against ``[tool.apexlint]`` in pyproject.toml.
- :func:`apex_tpu.analysis.run` over :data:`RULES` — the library
  surface the tests use.

Stdlib-only on purpose: analysis must not import jax or the code it
analyzes.  See ``docs/analysis.md`` for the rule catalogue, the
pragma/baseline workflow, and how to add a rule.
"""

from .core import (
    AnalysisConfig,
    Baseline,
    DEFAULT_BASELINE,
    Finding,
    PARSE_RULE,
    SourceModule,
    in_scope,
    load_config,
    parse_toml_tables,
    run,
)
from .rules import RULES

__all__ = [
    "AnalysisConfig",
    "Baseline",
    "DEFAULT_BASELINE",
    "Finding",
    "PARSE_RULE",
    "RULES",
    "SourceModule",
    "in_scope",
    "load_config",
    "parse_toml_tables",
    "run",
]
