"""Manual mixed-precision conversion helpers (legacy toolkit).

TPU re-design of reference ``apex/fp16_utils/fp16util.py``. The reference
mutates ``nn.Module`` objects in place (``network_to_half`` :35,
``convert_module``/``convert_network`` :44-71, ``prep_param_lists`` :90,
``model_grads_to_master_grads`` :136, ``master_params_to_model_params``
:158); here models are immutable variable pytrees, so every helper is a
pure function over pytrees. Defaults use bfloat16 — the TPU half type —
but fp16 works by passing ``dtype=jnp.float16``.

The batchnorm-stays-fp32 rule (reference ``BN_convert_float`` :22,
``convert_module`` skipping ``_BatchNorm`` :65-66) is expressed as a
module-path pattern policy shared with ``apex_tpu.amp``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.model import (
    BATCHNORM_PATTERNS,
    applier,
    cast_tree,
    _path_matches,
)
from apex_tpu.ops.flatten import flatten, flatten_like, unflatten
from apex_tpu.ops.multi_tensor import multi_tensor_l2norm

Pytree = Any

DEFAULT_HALF = jnp.bfloat16


def tofp16(value, dtype=DEFAULT_HALF):
    """Cast float arrays inside any nested container to the half dtype.

    The input-casting stage of the reference's ``tofp16`` module (:7-19),
    as a function usable on batches/args rather than an nn.Module layer.
    """
    return applier(value, lambda x: x.astype(dtype))


def BN_convert_float(variables: Pytree) -> Pytree:
    """Return ``variables`` with leaves on BatchNorm module paths cast to
    fp32, everything else untouched (reference ``BN_convert_float`` :22-32:
    BN is numerically unstable in fp16).
    """

    def one(path, x):
        x = jnp.asarray(x)
        if (jnp.issubdtype(x.dtype, jnp.floating)
                and _path_matches(path, BATCHNORM_PATTERNS)):
            return x.astype(jnp.float32)
        return x

    return jax.tree_util.tree_map_with_path(one, variables)


def convert_tree(variables: Pytree, dtype) -> Pytree:
    """Cast every float leaf (params, buffers alike) to ``dtype`` —
    the reference's ``convert_module`` (:44-57) without the BN exemption."""
    return cast_tree(variables, dtype)


def convert_network(variables: Pytree, dtype=DEFAULT_HALF) -> Pytree:
    """BN-safe whole-network cast (reference ``convert_network`` :60-71):
    float leaves go to ``dtype`` except those on BatchNorm paths, which
    stay fp32. (The reference also re-flattens RNN params here :68-69; flax
    RNN params are ordinary leaves so nothing extra is needed.)
    """
    return cast_tree(variables, dtype, except_patterns=BATCHNORM_PATTERNS)


def network_to_half(variables: Pytree, dtype=DEFAULT_HALF) -> Pytree:
    """Reference ``network_to_half`` (:35-41): BN-safe half conversion.
    (Input casting, done there by prepending a ``tofp16`` layer, is the
    caller's job here — or use :class:`FP16Model`.)"""
    return convert_network(variables, dtype)


class FP16Model:
    """Half-precision wrapper around a flax module (reference ``FP16Model``
    :73-87): converts the network BN-safely to the half dtype and casts
    float inputs at apply time.
    """

    def __init__(self, network, dtype=DEFAULT_HALF):
        self.network = network
        self.dtype = dtype

    def init(self, rngs, *args, **kwargs) -> Pytree:
        args = tuple(tofp16(a, self.dtype) for a in args)
        kwargs = {k: tofp16(v, self.dtype) for k, v in kwargs.items()}
        return convert_network(self.network.init(rngs, *args, **kwargs),
                               self.dtype)

    def apply(self, variables: Pytree, *args, **kwargs):
        args = tuple(tofp16(a, self.dtype) for a in args)
        kwargs = {k: tofp16(v, self.dtype) for k, v in kwargs.items()}
        return self.network.apply(variables, *args, **kwargs)

    def __call__(self, variables: Pytree, *args, **kwargs):
        return self.apply(variables, *args, **kwargs)


def prep_param_lists(params: Pytree, flat_master: bool = False):
    """Create fp32 master copies of ``params`` (reference :90-133).

    Returns ``(model_params, master_params)`` where ``model_params`` is the
    input pytree unchanged and ``master_params`` is an fp32 copy — either a
    matching pytree, or, with ``flat_master=True``, a tuple
    ``(flat_fp32, FlatSpec)`` holding one contiguous buffer (the reference
    requires a single dtype for the flat path :99-104; here mixed dtypes are
    simply promoted into the fp32 buffer).
    """
    if flat_master:
        flat, spec = flatten(params, dtype=jnp.float32)
        return params, (flat, spec)
    return params, cast_tree(params, jnp.float32)


def model_grads_to_master_grads(model_grads: Pytree,
                                master_params=None,
                                flat_master: bool = False):
    """Cast model-layout grads to fp32 master layout (reference :136-155).

    With ``flat_master=True``, ``master_params`` must be the
    ``(flat, spec)`` pair from :func:`prep_param_lists` and a flat fp32 grad
    buffer is returned; otherwise an fp32 grad pytree.
    """
    if flat_master:
        if master_params is None:
            raise ValueError(
                "flat_master=True needs the (flat, spec) master pair")
        _, spec = master_params
        return flatten_like(model_grads, spec, dtype=jnp.float32)
    return cast_tree(model_grads, jnp.float32)


def master_params_to_model_params(model_params: Pytree, master_params,
                                  flat_master: bool = False) -> Pytree:
    """Copy master values back into the model's dtypes (reference :158-179).

    Pure version: returns the new model-param pytree (leafwise cast of the
    fp32 masters to each model leaf's dtype).
    """
    if flat_master:
        flat, spec = master_params
        return unflatten(flat, spec)
    return jax.tree_util.tree_map(
        lambda p, m: jnp.asarray(m).astype(jnp.asarray(p).dtype),
        model_params, master_params)


def clip_grad_norm(grads: Pytree, max_norm: float,
                   norm_type: float = 2.0) -> Tuple[Pytree, jax.Array]:
    """Global-norm gradient clipping (the reference re-exports torch's
    ``clip_grad_norm`` with a version shim, :182-187; used by
    ``FP16_Optimizer.clip_master_grads``).

    Returns ``(clipped_grads, total_norm)``. Norm math in fp32; the clip
    coefficient is branch-free so it jits.
    """
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(jnp.asarray(g).astype(jnp.float32)))
             for g in leaves])) if leaves else jnp.asarray(0.0, jnp.float32)
    elif norm_type == 2.0:
        total = multi_tensor_l2norm(grads)
    else:
        p = float(norm_type)
        acc = sum(jnp.sum(jnp.abs(jnp.asarray(g).astype(jnp.float32)) ** p)
                  for g in leaves)
        total = acc ** (1.0 / p)
    coef = jnp.minimum(1.0, max_norm / (total + 1e-6))
    clipped = jax.tree_util.tree_map(
        lambda g: (jnp.asarray(g) * coef.astype(jnp.result_type(g))), grads)
    return clipped, total
