"""apex_tpu.fp16_utils — manual mixed-precision toolkit (legacy API).

Mirrors the reference ``apex/fp16_utils`` (``__init__.py:1-16``): model
half-conversion helpers, master-param copies, legacy loss scalers, and the
general FP16_Optimizer — re-designed as pure functions over variable
pytrees (see each module's docstring for the mapping). The amp API
(``apex_tpu.amp``) supersedes this toolkit, exactly as in the reference.
"""

from apex_tpu.fp16_utils.fp16util import (
    BN_convert_float,
    FP16Model,
    clip_grad_norm,
    convert_network,
    convert_tree,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    tofp16,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler
from apex_tpu.fp16_utils.fp16_optimizer import (
    FP16OptimizerState,
    FP16_Optimizer,
)

__all__ = [
    "BN_convert_float",
    "DynamicLossScaler",
    "FP16Model",
    "FP16OptimizerState",
    "FP16_Optimizer",
    "LossScaler",
    "clip_grad_norm",
    "convert_network",
    "convert_tree",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "network_to_half",
    "prep_param_lists",
    "tofp16",
]
