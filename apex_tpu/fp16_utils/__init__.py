"""apex_tpu.fp16_utils — manual mixed-precision toolkit (legacy API).

Mirrors the reference ``apex/fp16_utils``: model half-conversion helpers,
master-param copies, legacy loss scalers, and the general FP16_Optimizer.
"""

__all__ = []
