"""Legacy loss scalers for the manual FP16_Optimizer API.

Re-design of reference ``apex/fp16_utils/loss_scaler.py``: ``LossScaler``
(static scale, :10-44) and ``DynamicLossScaler`` (:47-140; init 2**32,
halve on overflow, double after 1000 clean steps). Unlike the jit-carried
``apex_tpu.amp.LossScaler``, these are deliberately *stateful host-side
objects* — the legacy API contract is eager: ``has_overflow`` inspects real
gradient values (one device->host sync, mirroring the reference's per-param
CPU check :84-110) and ``update_scale`` mutates the object. Use the amp
scaler for fully-on-device training; use these for the legacy
``fp16_utils.FP16_Optimizer`` workflow and for tests that need eager
overflow probes.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.ops.multi_tensor import tree_any_nonfinite

Pytree = Any


class LossScaler:
    """Static loss scaler (reference :10-44): scale never changes; overflow
    never reported."""

    def __init__(self, scale: float = 1.0):
        self.cur_scale = float(scale)

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def has_overflow(self, grads: Pytree) -> bool:  # reference :21-23
        return False

    def update_scale(self, overflow: bool) -> None:  # reference :28-29
        pass

    def scale_gradient(self, grads: Pytree) -> Pytree:
        """Multiply grads by the scale (reference ``scale_gradient`` :25-26
        — a backward hook there; a pure tree map here)."""
        return jax.tree_util.tree_map(
            lambda g: g * jnp.asarray(self.cur_scale, g.dtype), grads)

    def unscale_gradient(self, grads: Pytree) -> Pytree:
        inv = 1.0 / self.cur_scale
        return jax.tree_util.tree_map(
            lambda g: (jnp.asarray(g).astype(jnp.float32) * inv), grads)

    def backward(self, loss):
        """Return the scaled loss (the reference calls
        ``loss*scale; .backward()`` :31-44 — differentiation is the caller's
        job in JAX)."""
        return loss.astype(jnp.float32) * self.cur_scale


class DynamicLossScaler(LossScaler):
    """Dynamic loss scaler (reference :47-140): starts huge and backs off.

    ``init_scale=2**32``, ``scale_factor=2``, ``scale_window=1000`` — note
    these legacy defaults differ from amp's (2**16 / window 2000).
    """

    def __init__(self, init_scale: float = 2.0 ** 32,
                 scale_factor: float = 2.0, scale_window: int = 1000):
        super().__init__(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.last_overflow_iter = -1
        self.iter = 0

    def has_overflow(self, grads: Pytree) -> bool:
        """Eager non-finite probe over all grads (reference
        ``has_overflow``/``_has_inf_or_nan`` :84-110). One host sync."""
        return bool(tree_any_nonfinite(grads))

    def update_scale(self, overflow: bool) -> None:
        """Reference :115-127: halve on overflow; double after
        ``scale_window`` clean iterations."""
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.iter
        elif (self.iter - self.last_overflow_iter) % self.scale_window == 0:
            self.cur_scale *= self.scale_factor
        self.iter += 1
