"""General FP16_Optimizer: fp32 master weights around any optimizer.

Re-design of reference ``apex/fp16_utils/fp16_optimizer.py`` (:13-643),
the manual/explicit counterpart of amp O2. The reference splits params into
fp16 / fp32-from-fp16 / fp32 groups (:126-157) and mutates
``optimizer.param_groups``; here the model params stay one pytree (possibly
mixed bf16/fp16/fp32 leaves) and the master copy is simply the fp32 cast of
that tree — fp32 leaves get a same-value master, exactly matching the
reference's "fp32_from_fp32" group semantics with zero bookkeeping.

API mapping (reference -> here):

- ``optimizer.backward(loss)`` (:462)        -> ``scale_loss(loss, state)``
  inside the function being differentiated; autodiff produces scaled grads.
- ``update_master_grads()`` (:525)           -> ``update_master_grads(grads,
  state)`` returning fp32 master grads + overflow + new state.
- ``clip_master_grads(max_norm)`` (:274)     -> ``clip_master_grads(...)``
  pure function returning (clipped, norm).
- ``step()`` (:361)                          -> ``step(params, grads, state)``
  (runs the whole protocol; skip-on-overflow is a branch-free select).
- ``state_dict``/``load_state_dict`` (:298-359, "option 2": masters saved
  separately from the wrapped optimizer) -> pytree in/out helpers.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.amp.optimizer import _tree_select
from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.fp16_utils.fp16util import clip_grad_norm
from apex_tpu.ops.multi_tensor import multi_tensor_unscale

Pytree = Any


class FP16OptimizerState(NamedTuple):
    master: Pytree             # fp32 master params (same tree as model)
    inner: Any                 # wrapped optimizer state (over masters)
    scaler: LossScalerState


class FP16_Optimizer:
    """Master-weight wrapper for any optax ``GradientTransformation``.

    ``static_loss_scale`` may be a float or the string ``"dynamic"``
    (reference accepts both spellings, :83-124); or pass
    ``dynamic_loss_scale=True``. Legacy dynamic defaults (2**16 init,
    window 1000) follow the reference's FP16_Optimizer ctor.
    """

    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = False):
        self.optimizer = init_optimizer
        if static_loss_scale == "dynamic":
            dynamic_loss_scale = True
        args = dynamic_loss_args or {}
        if dynamic_loss_scale:
            # legacy DynamicLossScaler defaults (reference loss_scaler.py:47:
            # init 2**32, factor 2, window 1000) — NOT amp's 2**16/2000
            self.loss_scaler = LossScaler(
                "dynamic",
                init_scale=args.get("init_scale", 2.0 ** 32),
                scale_factor=args.get("scale_factor", 2.0),
                scale_window=args.get("scale_window", 1000),
                max_loss_scale=args.get("max_loss_scale", 2.0 ** 32))
        else:
            self.loss_scaler = LossScaler(float(static_loss_scale))
        self.verbose = verbose

    # -- state ------------------------------------------------------------
    def init(self, params: Pytree) -> FP16OptimizerState:
        master = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p).astype(jnp.float32), params)
        return FP16OptimizerState(
            master=master,
            inner=self.optimizer.init(master),
            scaler=self.loss_scaler.init())

    # -- per-iteration protocol -------------------------------------------
    def scale_loss(self, loss, state: FP16OptimizerState):
        """Scaled loss to differentiate (replaces ``backward(loss)``)."""
        return self.loss_scaler.scale_loss(loss, state.scaler)

    def update_master_grads(self, grads: Pytree, state: FP16OptimizerState):
        """Unscale model grads into fp32 master grads; detect overflow
        (reference :525-580). Returns (master_grads, overflow, state with
        updated scaler)."""
        g, overflow = multi_tensor_unscale(
            grads, state.scaler.loss_scale, out_dtype=jnp.float32)
        new_scaler = self.loss_scaler.update(state.scaler, overflow)
        return g, overflow, state._replace(scaler=new_scaler)

    def clip_master_grads(self, master_grads: Pytree, max_norm: float,
                          norm_type: float = 2.0):
        """Clip fp32 master grads by global norm (reference :274-296).
        Returns (clipped_grads, total_norm)."""
        return clip_grad_norm(master_grads, max_norm, norm_type)

    def step(self, params: Pytree, grads: Pytree, state: FP16OptimizerState,
             *, max_grad_norm: Optional[float] = None
             ) -> Tuple[Pytree, FP16OptimizerState]:
        """Full protocol: unscale -> (clip) -> inner step on masters ->
        skip-select -> cast masters back to model dtypes (reference
        :361-460; the master->model copy is :452-457)."""
        g, overflow, state = self.update_master_grads(grads, state)
        if max_grad_norm is not None:
            g, _ = self.clip_master_grads(g, max_grad_norm)
        updates, new_inner = self.optimizer.update(g, state.inner,
                                                   state.master)
        new_master = optax.apply_updates(state.master, updates)
        keep = ~overflow
        master = _tree_select(keep, new_master, state.master)
        inner = _tree_select(keep, new_inner, state.inner)
        new_params = jax.tree_util.tree_map(
            lambda p, m: m.astype(jnp.asarray(p).dtype), params, master)
        params_out = _tree_select(keep, new_params, params)
        return params_out, FP16OptimizerState(master=master, inner=inner,
                                              scaler=state.scaler)

    # -- checkpointing ----------------------------------------------------
    def state_dict(self, state: FP16OptimizerState) -> dict:
        """Serializable dict: masters + scaler saved alongside the inner
        state — the reference's "option 2" layout (:298-317) where fp32
        masters are first-class checkpoint content."""
        return {
            "master_params": state.master,
            "optimizer_state": state.inner,
            "loss_scaler": state.scaler._asdict(),
        }

    def load_state_dict(self, d: dict) -> FP16OptimizerState:
        """Invert :meth:`state_dict` (reference :319-359)."""
        return FP16OptimizerState(
            master=d["master_params"],
            inner=d["optimizer_state"],
            scaler=LossScalerState(**d["loss_scaler"]))

    # -- introspection ----------------------------------------------------
    def loss_scale(self, state: FP16OptimizerState):
        return state.scaler.loss_scale

    def inspect_master_grad_data(self, master_grads: Pytree):
        """Flat list of master-grad arrays (reference :582-615's debugging
        aid)."""
        return jax.tree_util.tree_leaves(master_grads)
