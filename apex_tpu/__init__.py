"""apex_tpu — a TPU-native training-utilities framework.

A brand-new implementation of the capabilities of NVIDIA Apex (reference:
SunDoge/apex snapshot, see SURVEY.md) designed for TPUs from the ground up:

- ``apex_tpu.amp``: automatic mixed precision (O0-O3 optimization levels,
  fp32 master weights, dynamic loss scaling carried *inside* jit — no host
  syncs; overflow -> skip-step via ``lax`` selects).
- ``apex_tpu.optimizers``: fused optimizers (FusedAdam, FusedLAMB) over
  flat parameter buffers, with Pallas TPU kernels on the hot path.
- ``apex_tpu.ops``: multi-tensor primitives (scale/axpby/l2norm) returning
  carried overflow flags, the TPU equivalent of the reference's ``amp_C``
  CUDA extension.
- ``apex_tpu.parallel``: data-parallel training over ``jax.sharding.Mesh``
  axes (``psum``/``pmean`` over ICI), DistributedDataParallel/Reducer with
  the reference's numeric policy knobs, synchronized BatchNorm with exact
  parallel-variance stat merges and process groups, LARC, multi-host
  bootstrap.
- ``apex_tpu.normalization``: FusedLayerNorm backed by Pallas forward and
  backward kernels (jnp fallback on CPU).
- ``apex_tpu.fp16_utils``: manual mixed-precision toolkit (legacy API):
  BN-safe half conversion, fp32 master-param helpers, legacy loss scalers,
  general FP16_Optimizer.
- ``apex_tpu.RNN``: LSTM/GRU/ReLU/Tanh/mLSTM stacks compiled as
  ``lax.scan`` loops.
- ``apex_tpu.reparameterization``: weight normalization as pure pytree
  transforms.
- ``apex_tpu.serving``: batched inference — block-table KV cache,
  jitted prefill/decode engine, continuous-batching scheduler, and the
  ``InferenceServer`` front door.

Unlike the reference (a PyTorch extension), models here are flax/JAX pytrees
and the training step is a pure function compiled once by XLA. The apex API
names are kept so users of the reference can map concepts 1:1; the internals
are idiomatic JAX (see SURVEY.md section 7 for the design mapping).
"""

from apex_tpu import ops
from apex_tpu import amp
from apex_tpu import data
from apex_tpu import models
from apex_tpu import utils
from apex_tpu import optimizers
from apex_tpu import normalization
from apex_tpu import parallel
from apex_tpu import fp16_utils
from apex_tpu import multi_tensor_apply
from apex_tpu import RNN
from apex_tpu import reparameterization
from apex_tpu import serving

__version__ = "0.1.0"

__all__ = [
    "RNN",
    "amp",
    "data",
    "models",
    "utils",
    "fp16_utils",
    "multi_tensor_apply",
    "normalization",
    "ops",
    "optimizers",
    "parallel",
    "reparameterization",
    "serving",
]
