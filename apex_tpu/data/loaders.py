"""Host-side batch iterators + device prefetch.

Replaces the reference examples' torchvision/DALI input path (worker
processes + pinned-memory non_blocking copies,
``examples/imagenet/main_amp.py``) with the TPU idiom: a background
thread that stages the next batch onto the device (optionally sharded
over a mesh) while the current step runs — host→device transfer overlaps
compute, the same overlap the reference buys with CUDA streams.
"""

from __future__ import annotations

import glob
import os
import queue
import threading
from typing import Iterator, Optional, Tuple

import numpy as np


def synthetic_loader(batch_size: int, image_size: int = 224,
                     num_classes: int = 1000, channels: int = 3,
                     seed: int = 0,
                     native: bool = True) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Endless random NHWC uint8 batches (benchmark/CI path, no IO)."""
    rng = np.random.RandomState(seed)
    shape = (batch_size, image_size, image_size, channels)
    while True:
        x = rng.randint(0, 256, shape, dtype=np.uint8)
        y = rng.randint(0, num_classes, (batch_size,), dtype=np.int32)
        yield x, y


def npz_loader(data_dir: str, batch_size: int,
               steps_per_epoch: Optional[int] = None, shuffle: bool = True,
               seed: int = 0,
               native: bool = True) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream batches from ``.npz`` shards holding ``x`` (N,H,W,C uint8)
    and ``y`` (N int). Batches are assembled with the native C++ gather
    when the extension is available (``apex_tpu.ops.native``), else numpy
    fancy indexing."""
    shards = sorted(glob.glob(os.path.join(data_dir, "*.npz")))
    if not shards:
        raise FileNotFoundError(f"no .npz shards in {data_dir}")
    from apex_tpu.ops import native as native_ops
    use_native = native and native_ops.available
    rng = np.random.RandomState(seed)
    emitted = 0
    while True:
        order = rng.permutation(len(shards)) if shuffle else range(len(shards))
        for si in order:
            with np.load(shards[si]) as z:
                x, y = z["x"], z["y"]
            n = x.shape[0]
            perm = rng.permutation(n) if shuffle else np.arange(n)
            for i in range(n // batch_size):
                idx = perm[i * batch_size:(i + 1) * batch_size]
                idx = np.ascontiguousarray(idx, dtype=np.int64)
                if use_native:
                    xb = native_ops.gather_rows(x, idx)
                    yb = y[idx]
                else:
                    xb, yb = x[idx], y[idx]
                yield xb, yb
                emitted += 1
                if steps_per_epoch and emitted % steps_per_epoch == 0:
                    pass  # epoch boundaries are the caller's loop's job


def prefetch_to_device(iterator, size: int = 2, sharding=None):
    """Wrap a host batch iterator with a background thread that moves
    batches to device (with ``sharding`` when given) ``size`` steps ahead.

    The TPU analog of pinned-memory + ``non_blocking=True`` copies: by the
    time the consumer asks for batch N+1 it is already on-chip.
    """
    import jax

    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()

    def put(batch):
        if sharding is not None:
            batch = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), batch)
        else:
            batch = jax.tree_util.tree_map(jax.device_put, batch)
        q.put(batch)

    def producer():
        try:
            for batch in iterator:
                put(batch)
        finally:
            q.put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        yield item
