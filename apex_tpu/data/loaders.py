"""Host-side batch iterators + device prefetch.

Replaces the reference examples' torchvision/DALI input path (worker
processes + pinned-memory non_blocking copies,
``examples/imagenet/main_amp.py``) with the TPU idiom: a background
thread that stages the next batch onto the device (optionally sharded
over a mesh) while the current step runs — host→device transfer overlaps
compute, the same overlap the reference buys with CUDA streams.
"""

from __future__ import annotations

import glob
import os
import queue
import threading
from typing import Iterator, Optional, Tuple

import numpy as np


def synthetic_loader(batch_size: int, image_size: int = 224,
                     num_classes: int = 1000, channels: int = 3,
                     seed: int = 0,
                     native: bool = True) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Endless random NHWC uint8 batches (benchmark/CI path, no IO)."""
    rng = np.random.RandomState(seed)
    shape = (batch_size, image_size, image_size, channels)
    while True:
        x = rng.randint(0, 256, shape, dtype=np.uint8)
        y = rng.randint(0, num_classes, (batch_size,), dtype=np.int32)
        yield x, y


def npz_loader(data_dir: str, batch_size: int,
               steps_per_epoch: Optional[int] = None, shuffle: bool = True,
               seed: int = 0, native: bool = True,
               num_shards: int = 1,
               shard_index: int = 0) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream batches from ``.npz`` shards holding ``x`` (N,H,W,C uint8)
    and ``y`` (N int). Batches are assembled with the native C++ gather
    when the extension is available (``apex_tpu.ops.native``), else numpy
    fancy indexing.

    ``num_shards``/``shard_index``: multi-host sample sharding (the
    ``DistributedSampler`` role, see :func:`image_folder_loader`) —
    identical per-epoch permutations on every host, strided disjoint
    row slices per shard within each npz file.
    """
    if not 0 <= shard_index < num_shards:
        raise ValueError(
            f"shard_index {shard_index} not in [0, {num_shards})")
    shards = sorted(glob.glob(os.path.join(data_dir, "*.npz")))
    if not shards:
        raise FileNotFoundError(f"no .npz shards in {data_dir}")
    from apex_tpu.ops import native as native_ops
    use_native = native and native_ops.available
    rng = np.random.RandomState(seed)
    emitted = 0
    while True:
        order = rng.permutation(len(shards)) if shuffle else range(len(shards))
        for si in order:
            with np.load(shards[si]) as z:
                x, y = z["x"], z["y"]
            n = x.shape[0]
            perm = rng.permutation(n) if shuffle else np.arange(n)
            if num_shards > 1:
                usable = (n // num_shards) * num_shards
                perm = perm[:usable][shard_index::num_shards]
            if len(perm) < batch_size:
                # without this, a too-small file (or per-shard slice)
                # yields zero batches and the endless loop would spin
                # forever producing nothing
                raise ValueError(
                    f"{shards[si]}: {n} rows / {num_shards} shards "
                    f"< batch_size {batch_size}; this shard cannot "
                    "produce a single batch")
            for i in range(len(perm) // batch_size):
                idx = perm[i * batch_size:(i + 1) * batch_size]
                idx = np.ascontiguousarray(idx, dtype=np.int64)
                if use_native:
                    xb = native_ops.gather_rows(x, idx)
                    yb = y[idx]
                else:
                    xb, yb = x[idx], y[idx]
                yield xb, yb
                emitted += 1
                if steps_per_epoch and emitted % steps_per_epoch == 0:
                    pass  # epoch boundaries are the caller's loop's job


def _list_image_folder(root: str):
    """torchvision-ImageFolder convention: ``root/<class_name>/*.{jpg,...}``;
    classes sorted alphabetically -> contiguous label ids."""
    exts = (".jpg", ".jpeg", ".png", ".bmp", ".webp")
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise FileNotFoundError(f"no class directories under {root}")
    samples = []
    for label, cls in enumerate(classes):
        for path in sorted(glob.glob(os.path.join(root, cls, "*"))):
            if path.lower().endswith(exts):
                samples.append((path, label))
    if not samples:
        raise FileNotFoundError(f"no images under {root}")
    return samples, classes


def _decode_train(path: str, image_size: int, rng: np.random.RandomState):
    """RandomResizedCrop(scale 0.08-1.0) + horizontal flip — the
    reference's training transform (``examples/imagenet/main_amp.py``
    torchvision pipeline), PIL-only."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        w, h = im.size
        area = w * h
        for _ in range(10):
            target = area * rng.uniform(0.08, 1.0)
            ar = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                x0 = rng.randint(0, w - cw + 1)
                y0 = rng.randint(0, h - ch + 1)
                im = im.resize((image_size, image_size), Image.BILINEAR,
                               box=(x0, y0, x0 + cw, y0 + ch))
                break
        else:  # fallback: center crop of the short side
            s = min(w, h)
            x0, y0 = (w - s) // 2, (h - s) // 2
            im = im.resize((image_size, image_size), Image.BILINEAR,
                           box=(x0, y0, x0 + s, y0 + s))
        arr = np.asarray(im, np.uint8)
    if rng.rand() < 0.5:
        arr = arr[:, ::-1]
    return arr


def _decode_eval(path: str, image_size: int):
    """Resize(short side = size*256/224) + CenterCrop(size) — the
    reference's validation transform."""
    from PIL import Image

    resize = int(image_size * 256 / 224)
    with Image.open(path) as im:
        im = im.convert("RGB")
        w, h = im.size
        if w < h:
            nw, nh = resize, int(round(h * resize / w))
        else:
            nw, nh = int(round(w * resize / h)), resize
        im = im.resize((nw, nh), Image.BILINEAR)
        x0, y0 = (nw - image_size) // 2, (nh - image_size) // 2
        im = im.crop((x0, y0, x0 + image_size, y0 + image_size))
        return np.asarray(im, np.uint8)


def image_folder_loader(root: str, batch_size: int, image_size: int = 224,
                        train: bool = True, shuffle: Optional[bool] = None,
                        seed: int = 0, num_workers: int = 8,
                        loop: bool = True, samples=None,
                        native: bool = True,
                        num_shards: int = 1, shard_index: int = 0):
    """Stream (x uint8 NHWC, y int32) batches from a torchvision-style
    image folder — the real-data input path the reference gets from
    ``datasets.ImageFolder`` + multi-worker ``DataLoader`` + fast_collate
    (``examples/imagenet/main_amp.py:218-225,256-303``).

    Decode path: with ``native`` (default) JPEG files are decoded by ONE
    GIL-free C call per batch (libjpeg-turbo, one thread per image,
    transform fused into the decode — ``ops.native.decode_jpeg_batch``);
    non-JPEG files and any the native decoder rejects fall back to a PIL
    thread pool.  ``native=False`` forces the PIL pool everywhere (parity
    oracle for tests).

    ``train`` picks the transform (RandomResizedCrop+flip vs
    Resize+CenterCrop).  ``loop=False`` yields one pass (validation) with
    a final short batch.  ``samples`` (from :func:`_list_image_folder`)
    skips re-scanning a directory tree the caller already listed.

    ``num_shards``/``shard_index``: multi-host sample sharding — the
    reference's ``DistributedSampler`` role (its example wraps the
    dataset per rank, ``examples/imagenet/main_amp.py:218-225``).  Every
    shard draws the SAME per-epoch permutation (seeded identically on
    all hosts) and takes its strided slice, so shards are disjoint and
    equal-length (up to ``num_shards-1`` trailing samples of each
    epoch's permutation are dropped), and each host feeds only its own
    batches (pass ``jax.process_count()``/``jax.process_index()``).
    ``batch_size`` is this shard's PER-HOST batch.
    """
    if not 0 <= shard_index < num_shards:
        raise ValueError(
            f"shard_index {shard_index} not in [0, {num_shards})")
    if samples is None:
        samples, _ = _list_image_folder(root)  # eager: bad root fails HERE
    if train and len(samples) // num_shards < batch_size:
        # the drop-ragged-tail rule below would otherwise yield NOTHING
        # and (with loop=True) spin forever
        raise ValueError(
            f"{root}: {len(samples)} images / {num_shards} shards < "
            f"batch_size {batch_size}; a training epoch would produce "
            "zero batches")
    if shuffle is None:
        shuffle = train
    return _image_folder_iter(samples, batch_size, image_size, train,
                              shuffle, seed, num_workers, loop, native,
                              num_shards, shard_index)


def _image_folder_iter(samples, batch_size, image_size, train, shuffle,
                       seed, num_workers, loop, native=True,
                       num_shards=1, shard_index=0):
    from concurrent.futures import ThreadPoolExecutor

    from apex_tpu.ops import native as native_ops

    use_native = native and native_ops.jpeg_available
    rng = np.random.RandomState(seed)
    pool = ThreadPoolExecutor(max_workers=num_workers)

    def decode(item):
        (path, label), item_seed = item
        if train:
            # per-item seed drawn in the MAIN thread (RandomState is not
            # thread-safe; workers only consume their private generator)
            return _decode_train(path, image_size,
                                 np.random.RandomState(item_seed)), label
        return _decode_eval(path, image_size), label

    def assemble(idx, seeds):
        items = [samples[j] for j in idx]
        y = np.asarray([label for _, label in items], np.int32)
        if use_native:
            x = np.empty((len(items), image_size, image_size, 3), np.uint8)
            jpeg_rows = [r for r, (p, _) in enumerate(items)
                         if p.lower().endswith((".jpg", ".jpeg"))]
            jset = set(jpeg_rows)
            rest = [r for r in range(len(items)) if r not in jset]
            if jpeg_rows:
                batch, fail = native_ops.decode_jpeg_batch(
                    [items[r][0] for r in jpeg_rows], image_size,
                    train=train,
                    seeds=np.asarray([seeds[r] for r in jpeg_rows],
                                     np.uint64))
                for k, r in enumerate(jpeg_rows):
                    if fail[k]:
                        rest.append(r)  # corrupt/CMYK: PIL fallback
                    else:
                        x[r] = batch[k]
            if rest:
                decoded = list(pool.map(
                    decode, [(items[r], seeds[r]) for r in rest]))
                for k, r in enumerate(rest):
                    x[r] = decoded[k][0]
            return x, y
        decoded = list(pool.map(
            decode, [(it, s) for it, s in zip(items, seeds)]))
        return np.stack([d[0] for d in decoded]).astype(np.uint8), y

    epoch = 0
    while True:
        order = rng.permutation(len(samples)) if shuffle \
            else np.arange(len(samples))
        if num_shards > 1:
            # DistributedSampler semantics: the permutation rng draws
            # exactly once per epoch on every host (identical streams),
            # each shard takes a strided disjoint slice; the <num_shards
            # remainder is dropped so shards stay equal-length
            usable = (len(order) // num_shards) * num_shards
            order = order[:usable][shard_index::num_shards]
        # augmentation seeds come from a per-(epoch, shard) rng so their
        # consumption can never desynchronize the permutation stream
        # across hosts
        aug_rng = np.random.RandomState(
            (seed * 1000003 + epoch * 9973 + shard_index) % (2 ** 31))
        for i in range(0, len(order), batch_size):
            idx = order[i:i + batch_size]
            if train and len(idx) < batch_size:
                break  # drop ragged train tail (the reference's drop_last)
            seeds = aug_rng.randint(2 ** 31, size=len(idx))
            yield assemble(idx, seeds)
        epoch += 1
        if not loop:
            return


def s2d_batches(iterator):
    """Wrap any (x, y) batch iterator, applying the ResNet
    ``stem="s2d_pre"`` input layout (``models.resnet.s2d_input_transform``)
    to x on HOST — numpy reshape/transpose during batch assembly, like
    the MLPerf TPU ResNet input pipelines. Inside the step the same
    transform costs real per-iteration HBM round-trips (~0.5 ms at
    b256/224px on v5e, BENCH_NOTES.md); here it rides the idle host."""
    from apex_tpu.models.resnet import s2d_input_transform

    for x, y in iterator:
        yield s2d_input_transform(np.asarray(x)), y


def put_global(x, sharding=None):
    """Stage one host array onto devices under ``sharding``.

    Single-process: a plain ``jax.device_put``.  Multi-host: the local
    array is this process's SHARD of the global batch (each host's
    loader yields its ``num_shards``-th of the samples), so the global
    array is assembled with ``jax.make_array_from_process_local_data`` —
    a global batch of ``process_count * local_batch`` rows.  A bare
    ``device_put`` would instead treat every host's rows as the whole
    batch and silently drop the non-addressable remainder.
    """
    import jax

    if sharding is None:
        return jax.device_put(x)
    if jax.process_count() > 1:
        # contract: pass HOST arrays — a device-committed input would
        # round-trip device->host->device here (the loaders all yield
        # numpy)
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(x))
    return jax.device_put(x, sharding)


def prefetch_to_device(iterator, size: int = 2, sharding=None):
    """Wrap a host batch iterator with a background thread that moves
    batches to device (with ``sharding`` when given) ``size`` steps ahead.

    The TPU analog of pinned-memory + ``non_blocking=True`` copies: by the
    time the consumer asks for batch N+1 it is already on-chip.  On
    multi-host, batches assemble into global arrays via
    :func:`put_global`.
    """
    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()

    def put(batch):
        import jax
        batch = jax.tree_util.tree_map(
            lambda x: put_global(x, sharding), batch)
        q.put(batch)

    def producer():
        # a loader exception must surface at the consumer's next(), with
        # its original traceback — not vanish into a bare StopIteration
        try:
            for batch in iterator:
                put(batch)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            q.put(e)
        else:
            q.put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item
