"""apex_tpu.data — host-side input pipelines with device prefetch.

The reference's examples lean on torchvision/DALI loaders with pinned
memory and ``--workers`` processes (``examples/imagenet/main_amp.py``).
The TPU equivalents here:

- :func:`npz_loader` — stream ``.npz`` shards (``x`` NHWC uint8, ``y``
  int) from a directory;
- :func:`image_folder_loader` — real-image path: torchvision-ImageFolder
  directory layout decoded by a PIL thread pool, with the reference's
  train (RandomResizedCrop+flip) and eval (Resize+CenterCrop) transforms;
- :func:`synthetic_loader` — zero-IO random batches for benchmarking;
- :func:`prefetch_to_device` — background-thread host→device transfer so
  step N+1's batch is already on-chip when step N finishes (the pinned-
  memory/non_blocking-copy analog);
- the native fast path (``apex_tpu.ops.native``) accelerates host-side
  batch assembly (gather + layout) in C++ when the extension is built.
"""

from apex_tpu.data.loaders import (
    image_folder_loader,
    npz_loader,
    prefetch_to_device,
    put_global,
    synthetic_loader,
)

__all__ = ["image_folder_loader", "npz_loader", "prefetch_to_device",
           "put_global", "synthetic_loader"]
