"""apex_tpu.multi_tensor_apply — API-parity shim.

The reference exposes a ``multi_tensor_applier`` singleton that chunks
tensor lists and launches ``amp_C`` kernels
(``apex/multi_tensor_apply/multi_tensor_apply.py:3-30``, chunk 2048*32).
On TPU there is no user-visible chunking — XLA tiles — so this module
exists purely so reference code patterns keep working: the applier simply
calls the given apex_tpu op on its pytree arguments.
"""

from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
    MultiTensorApply,
    multi_tensor_applier,
)

__all__ = ["MultiTensorApply", "multi_tensor_applier"]
