"""Compatibility applier (reference ``apex/multi_tensor_apply``).

In the reference, ``multi_tensor_applier(op, noop_flag, tensor_lists,
*args)`` dispatches a chunked CUDA kernel into a caller-provided overflow
buffer. The TPU ops have a different (functional) signature — they take a
pytree and *return* ``(out, overflow)`` — so this applier is a thin
dispatcher, not a drop-in for reference call sites: ``__call__`` simply
forwards its arguments to ``op``. ``chunk_size`` is kept for constructor
parity but ignored (XLA handles tiling). ``available`` is always True —
there is no optional native extension to probe (the reference probes
``import amp_C`` at ``multi_tensor_apply.py:8-14``).
"""


class MultiTensorApply:
    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size  # kept for API parity; unused on TPU

    def __call__(self, op, *args, **kwargs):
        return op(*args, **kwargs)


multi_tensor_applier = MultiTensorApply()
