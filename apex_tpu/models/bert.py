"""BERT encoder — the FusedLayerNorm + FusedLAMB workload.

The reference's LayerNorm and LAMB kernels exist to serve BERT pretraining
(SURVEY.md §2.2: the LAMB CUDA kernels ship with no Python wrapper, used by
NVIDIA's BERT recipes downstream; BASELINE.json config 4 is "BERT-large
pretraining, FusedLAMB + FusedLayerNorm + amp O2 + DDP"). This is that
model, TPU-first:

- post-LN transformer encoder (original BERT) built on
  ``normalization.FusedLayerNorm`` (Pallas kernels on TPU);
- attention as batched einsum -> one fused softmax -> einsum, all
  MXU-shaped (no per-head Python loops);
- pluggable attention: pass ``attention_fn`` (same signature as
  :func:`dot_product_attention`) to swap in a sequence-parallel kernel
  such as ring attention for long sequences;
- static shapes; masking via additive -inf biases (no dynamic slicing).

``BertConfig`` mirrors the standard hyperparameter names so configs port
directly; ``bert_base``/``bert_large`` builders match the published sizes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.pipelined_common import PipelinedCommon
from apex_tpu.normalization import FusedLayerNorm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    # rematerialize each encoder layer in backward (jax.checkpoint):
    # trades ~33% more FLOPs for O(layers) less activation HBM — the
    # lever that lets long sequences fit (pairs with ring/Ulysses SP)
    remat: bool = False
    # >0 replaces each layer's dense MLP with a Switch-MoE of this many
    # experts (models.MoEMlp); per-layer load-balance aux losses are
    # sown into the "losses" collection — apply with
    # mutable=["losses"] and add their sum (weighted) to the training
    # loss. Shard experts with models.EP_RULES for expert parallelism.
    moe_experts: int = 0
    # MoE dispatch mode: "dense" (exact, E x FLOPs) or "capacity"
    # (Switch capacity-factor gather/scatter — the perf path at E >= 8)
    moe_dispatch: str = "dense"
    moe_capacity_factor: float = 1.25


def bert_base() -> "BertConfig":
    return BertConfig()


def bert_large() -> "BertConfig":
    return BertConfig(hidden_size=1024, num_hidden_layers=24,
                      num_attention_heads=16, intermediate_size=4096)


def _dense_init(cfg):
    return nn.initializers.normal(cfg.initializer_range)


def _embed_block(cfg, input_ids, token_type_ids, deterministic):
    """Embedding sum + LN + dropout, shared by :class:`BertEncoder` and
    :class:`BertEmbeddings` so the param names/math cannot drift (must
    be called inside an ``@nn.compact`` body)."""
    s = input_ids.shape[1]
    init = _dense_init(cfg)
    emb = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                   embedding_init=init, name="word_embeddings")(input_ids)
    pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                   embedding_init=init, name="position_embeddings")(
        jnp.arange(s)[None, :])
    # segment table always exists (standard BERT: ids default to 0)
    # so init-without-segments checkpoints still apply with them
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    typ = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                   embedding_init=init,
                   name="token_type_embeddings")(token_type_ids)
    x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                       name="embeddings_ln")(emb + pos + typ)
    return nn.Dropout(cfg.hidden_dropout_prob,
                      deterministic=deterministic)(x)


def _pretraining_heads(cfg, seq):
    """MLM + NSP heads, shared by :class:`BertForPreTraining` and
    :class:`BertHeads` (must be called inside ``@nn.compact``)."""
    init = _dense_init(cfg)
    # MLM: transform -> untied decoder projection
    h = nn.Dense(cfg.hidden_size, kernel_init=init,
                 name="mlm_transform")(seq)
    h = nn.gelu(h, approximate=False)
    h = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                       name="mlm_ln")(h)
    mlm_logits = nn.Dense(cfg.vocab_size, kernel_init=init,
                          name="mlm_decoder")(h).astype(jnp.float32)
    # NSP: [CLS] pooled
    cls = jnp.tanh(nn.Dense(cfg.hidden_size, kernel_init=init,
                            name="pooler")(seq[:, 0]))
    nsp_logits = nn.Dense(2, kernel_init=init,
                          name="nsp_classifier")(cls).astype(jnp.float32)
    return mlm_logits, nsp_logits


def dot_product_attention(q, k, v, bias=None, dropout_fn=None):
    """(B, S, H, D) q/k/v -> (B, S, H, D); softmax in fp32."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    scores = scores.astype(jnp.float32)
    if bias is not None:
        scores = scores + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_fn is not None:
        probs = dropout_fn(probs)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class BertSelfAttention(nn.Module):
    cfg: BertConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True):
        cfg = self.cfg
        h, nh = cfg.hidden_size, cfg.num_attention_heads
        dh = h // nh
        init = _dense_init(cfg)

        def proj(name):
            return nn.DenseGeneral((nh, dh), kernel_init=init,
                                   name=name)(x)

        q, k, v = proj("query"), proj("key"), proj("value")
        dropout_fn = None
        if cfg.attention_probs_dropout_prob > 0 and not deterministic:
            drop = nn.Dropout(cfg.attention_probs_dropout_prob,
                              deterministic=False)
            dropout_fn = lambda p: drop(p)
            if self.attention_fn is not None:
                # annotate for fused attention adapters (flash/ring/
                # Ulysses): kernels can't call a probs->probs closure
                # (probs are never materialized), so they consume
                # (rate, per-step seed) and run dropout in-kernel
                # (ops.flash_attention.dropout_params).  The seed comes
                # from the flax 'dropout' rng stream (module path folded
                # in => distinct per layer), redrawn each step.  Only
                # drawn for custom attention_fns so the DEFAULT path's
                # rng stream is unchanged.
                dropout_fn.rate = cfg.attention_probs_dropout_prob
                dropout_fn.seed = jax.random.randint(
                    self.make_rng("dropout"), (), 0,
                    jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
        attn = self.attention_fn or dot_product_attention
        ctx = attn(q, k, v, bias=attn_bias, dropout_fn=dropout_fn)
        return nn.DenseGeneral(h, axis=(-2, -1), kernel_init=init,
                               name="output")(ctx)


class BertLayer(nn.Module):
    cfg: BertConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True):
        cfg = self.cfg
        init = _dense_init(cfg)
        drop = nn.Dropout(cfg.hidden_dropout_prob,
                          deterministic=deterministic)

        attn_out = BertSelfAttention(cfg, self.attention_fn,
                                     name="attention")(
            x, attn_bias, deterministic)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                           name="attention_ln")(x + drop(attn_out))

        if cfg.moe_experts:
            from apex_tpu.models.moe import MoEMlp
            y, aux = MoEMlp(num_experts=cfg.moe_experts,
                            hidden_size=cfg.hidden_size,
                            intermediate_size=cfg.intermediate_size,
                            kernel_init=init, name="moe",
                            dispatch=cfg.moe_dispatch,
                            capacity_factor=cfg.moe_capacity_factor)(x)
            self.sow("losses", "moe_aux", aux)
        else:
            y = nn.Dense(cfg.intermediate_size, kernel_init=init,
                         name="intermediate")(x)
            y = nn.gelu(y, approximate=False)
            y = nn.Dense(cfg.hidden_size, kernel_init=init,
                         name="output")(y)
        return FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                              name="output_ln")(x + drop(y))


class BertEncoder(nn.Module):
    """input_ids/token_type_ids (B, S) int32, attention_mask (B, S)
    {0,1} -> sequence output (B, S, H)."""

    cfg: BertConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.cfg
        x = _embed_block(cfg, input_ids, token_type_ids, deterministic)

        attn_bias = None
        if attention_mask is not None:
            attn_bias = jnp.where(attention_mask[:, None, None, :] > 0,
                                  0.0, -1e9).astype(jnp.float32)

        layer_cls = BertLayer
        if cfg.remat:
            # deterministic (argnum 3, self=0) is a Python bool -> static
            layer_cls = nn.remat(BertLayer, static_argnums=(3,))
        for i in range(cfg.num_hidden_layers):
            x = layer_cls(cfg, self.attention_fn, name=f"layer_{i}")(
                x, attn_bias, deterministic)
        return x


class BertEmbeddings(nn.Module):
    """Embedding sublayer split out for pipeline parallelism (param
    names match the inline embeddings of :class:`BertEncoder`)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None,
                 deterministic: bool = True):
        return _embed_block(self.cfg, input_ids, token_type_ids,
                            deterministic)


class BertStage(nn.Module):
    """``layers_per_stage`` consecutive encoder layers — the GPipe stage
    body for :class:`PipelinedBert` (activation shape preserved)."""

    cfg: BertConfig
    layers_per_stage: int
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True):
        layer_cls = BertLayer
        if self.cfg.remat:
            layer_cls = nn.remat(BertLayer, static_argnums=(3,))
        for i in range(self.layers_per_stage):
            x = layer_cls(self.cfg, self.attention_fn, name=f"layer_{i}")(
                x, attn_bias, deterministic)
        return x


class BertHeads(nn.Module):
    """MLM + NSP heads split out for pipeline parallelism (param names
    match :class:`BertForPreTraining`)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, seq):
        return _pretraining_heads(self.cfg, seq)


class PipelinedBert(PipelinedCommon):
    """BERT-for-pretraining with the encoder stack pipelined over a mesh
    axis (GPipe, ``parallel.gpipe_spmd``) — the PP composition the
    reference never had (SURVEY §2.3).

    Layout: embeddings and heads run replicated on every pipe device
    (they are a few percent of the FLOPs); the ``num_hidden_layers``
    encoder layers split into ``pp`` equal stages whose params live
    STACKED with a leading ``(pp, ...)`` dim, sharded ``P(pipe_axis)``.
    The activation pytree ``(hidden, attention_bias)`` flows through the
    microbatch schedule; the bias rides along unchanged so every stage
    can mask attention.

    Composes with data parallelism: pass ``batch_axis`` and shard the
    batch over it — inside ``shard_map`` the pipe schedule runs
    per-data-shard.  Follows the flax calling convention
    (``init(rng, ids) -> variables``, ``apply(variables, ids, ...)``)
    so ``amp.initialize`` wraps it like any module.

    Dropout composes: pass ``deterministic=False`` and
    ``rngs={"dropout": key}`` like any flax model.  Each (microbatch,
    stage[, data-shard]) folds its coordinates into the key inside the
    pipeline body, so every stage of every microbatch draws an
    independent mask and the schedule stays a pure scan.

    MoE configs compose too: each stage's Switch load-balance aux
    losses (``sow``n into the ``"losses"`` collection by
    ``models.MoEMlp``) accumulate in an extra per-row ``(batch,)``
    leaf riding the activation pytree (every leaf must share the batch
    dim — the rows of a microbatch all carry its running total), and
    ``apply`` returns their mean as a third output —
    ``(mlm_logits, nsp_logits, moe_aux)`` when ``cfg.moe_experts > 0``
    (weight it into the loss like the monolithic model's
    ``mutable=["losses"]`` flow).

    ``seq_axis``: shard the SEQUENCE dim over this mesh axis inside the
    pipeline, paired with a sequence-parallel ``attention_fn`` built
    for the same axis (``parallel.make_ring_attention(seq_axis)``); the
    hidden states and attention bias enter the pipeline sequence-
    sharded and every stage's ring collectives run inside the pipeline
    body, composing dp x sp x pp on one mesh::

        mesh = Mesh(devs.reshape(dp, sp, pp), ("data", "sp", "pipe"))
        pb = PipelinedBert(cfg, mesh, pp=pp, num_microbatches=m,
                           batch_axis="data", seq_axis="sp",
                           attention_fn=parallel.make_ring_attention("sp"))

    ``tp_axis``: layer Megatron tensor parallelism on top — stage
    weights take ``P(pipe, ...model...)`` placement
    (:meth:`shard_variables`) and the TP axis stays GSPMD-automatic
    inside the pipeline's ``shard_map`` (partial-manual mode), so XLA
    inserts the TP collectives while pipe/data run the explicit
    schedule.  KNOWN LIMITATION: half-precision compute (amp O2/O3)
    inside the partial-manual region trips an XLA crash in this jax
    build's CPU backend ("Invalid binary instruction opcode copy",
    ``hlo_instruction.cc``) — ``tp_axis`` is tested fp32; re-check on
    hardware where the TPU backend compiles the same program
    independently.

    Constraint: ``num_hidden_layers % pp == 0``.
    """

    def __init__(self, cfg: BertConfig, mesh, pp: int,
                 num_microbatches: int, pipe_axis: str = "pipe",
                 batch_axis: Optional[str] = None,
                 seq_axis: Optional[str] = None,
                 tp_axis: Optional[str] = None,
                 attention_fn: Optional[Callable] = None):
        if cfg.num_hidden_layers % pp:
            raise ValueError(
                f"num_hidden_layers={cfg.num_hidden_layers} must divide "
                f"into pp={pp} equal stages")
        if seq_axis is not None and attention_fn is None:
            raise ValueError(
                "seq_axis requires a sequence-parallel attention_fn for "
                "the same axis (parallel.make_ring_attention(seq_axis)) "
                "— plain attention would silently attend only within "
                "each sequence shard")
        self.cfg = cfg
        self.mesh = mesh
        self.pp = pp
        self.num_microbatches = num_microbatches
        self.pipe_axis = pipe_axis
        self.batch_axis = batch_axis
        self.seq_axis = seq_axis
        self.tp_axis = tp_axis
        self.attention_fn = attention_fn
        self.embed = BertEmbeddings(cfg)
        self.stage = BertStage(cfg, cfg.num_hidden_layers // pp,
                               attention_fn)
        # init traces OUTSIDE shard_map where a sequence-parallel
        # attention_fn's collectives have no bound axis; attention_fn
        # creates no params, so a plain-attention twin yields the
        # identical parameter tree
        self._stage_init = BertStage(cfg, cfg.num_hidden_layers // pp,
                                     None)
        self.heads = BertHeads(cfg)

    def init(self, rng, input_ids, attention_mask=None,
             token_type_ids=None, deterministic: bool = True):
        r_embed, r_stage, r_heads = jax.random.split(rng, 3)
        embed_p = self.embed.init(r_embed, input_ids, token_type_ids,
                                  True)["params"]
        x0 = self.embed.apply({"params": embed_p}, input_ids,
                              token_type_ids, True)
        bias0 = self._bias(input_ids, attention_mask)
        stage_p = jax.vmap(
            lambda r: self._stage_init.init(r, x0, bias0, True)["params"])(
            jax.random.split(r_stage, self.pp))
        heads_p = self.heads.init(r_heads, x0)["params"]
        return {"params": {"embed": embed_p, "stages": stage_p,
                           "heads": heads_p}}

    # param_spec_tree / shard_variables / constrain_grads /
    # _partial_manual_kwargs / _dropout_setup come from PipelinedCommon
    tp_rules_name = "bert_tp_rules"

    def _bias(self, input_ids, attention_mask):
        b, s = input_ids.shape
        if attention_mask is None:
            return jnp.zeros((b, 1, 1, s), jnp.float32)
        return jnp.where(attention_mask[:, None, None, :] > 0,
                         0.0, -1e9).astype(jnp.float32)

    def _schedule_input(self, h, b, needs_rng):
        """The ``(hidden, bias[, mb_ids], aux0)`` activation tuple both
        schedules feed their stage_fn.  The microbatch-id row assembly
        and the vma-typed aux zero init must stay IDENTICAL between the
        GPipe and 1F1B paths, or the dropout keys / pytree layout drift
        (``test_bert_1f1b_dropout_matches_gpipe_autodiff`` pins this).

        - aux inherits h's varying axes (the stage adds h-derived
          values), so its zero init must carry the same vma type or the
          scan carry types mismatch;
        - mb ids: one id per row, assigned the way the schedules split
          the (local) batch — contiguous b_local/m groups.
        """
        from apex_tpu.parallel.collectives import vary_like

        aux0 = vary_like(jnp.zeros((h.shape[0],), jnp.float32), h)
        if needs_rng:
            return (h, b, self._microbatch_ids(h), aux0)
        return (h, b, aux0)

    def _build_stage_fn(self, needs_rng, base_key, deterministic):
        """The per-stage body both schedules share (GPipe ``apply`` and
        :meth:`loss_and_grad_1f1b`).  Activation pytree:
        ``(hidden, bias, mb_ids, aux)`` when dropout rngs are live,
        ``(hidden, bias, aux)`` otherwise — ``mb_ids`` carries one
        microbatch id per row for per-(microbatch, stage) dropout keys,
        ``aux`` accumulates per-row MoE load-balance losses (zero and
        DCE'd for dense configs)."""
        has_moe = self.cfg.moe_experts > 0

        def run_stage(sp, h, b, rngs_):
            if has_moe:
                # read the stage's sown MoE aux losses purely: mutable
                # returns them instead of mutating hidden state
                out, mut = self.stage.apply(
                    {"params": sp}, h, b,
                    deterministic if rngs_ is None else False,
                    rngs=rngs_, mutable=["losses"])
                aux = sum(jnp.sum(leaf) for leaf in
                          jax.tree_util.tree_leaves(mut["losses"]))
                return out, aux.astype(jnp.float32)
            out = self.stage.apply(
                {"params": sp}, h, b,
                deterministic if rngs_ is None else False, rngs=rngs_)
            return out, jnp.float32(0)

        def stage_fn(sp, xb):
            h, b, mb, aux = (xb if needs_rng else
                             (xb[0], xb[1], None, xb[2]))
            stage_rngs = None
            if needs_rng:
                # independent mask per (microbatch, stage[, shard]) —
                # the key chain lives in PipelinedCommon so the two
                # families cannot drift
                stage_rngs = {
                    "dropout": self._stage_dropout_key(base_key, mb)}
            out, stage_aux = run_stage(sp, h, b, stage_rngs)
            # aux accumulates across stages in a per-row (b/m,) leaf of
            # the activation pytree (the schedules require the shared
            # batch dim; zero for non-MoE, where XLA removes it)
            aux = aux + stage_aux
            if needs_rng:
                return (out, b, mb, aux)
            return (out, b, aux)

        return stage_fn

    def apply(self, variables, input_ids, attention_mask=None,
              token_type_ids=None, deterministic: bool = True,
              rngs=None):
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel.pipeline import gpipe_spmd

        cfg = self.cfg
        needs_rng, base_key, embed_rngs = self._dropout_setup(
            deterministic, rngs, "PipelinedBert.apply")

        p = variables["params"]
        x = self.embed.apply({"params": p["embed"]}, input_ids,
                             token_type_ids, deterministic,
                             rngs=embed_rngs)
        bias = self._bias(input_ids, attention_mask)

        has_moe = cfg.moe_experts > 0
        stage_fn = self._build_stage_fn(needs_rng, base_key,
                                        deterministic)

        run = gpipe_spmd(stage_fn, self.pipe_axis, self.num_microbatches)

        def run_wrapped(sp, xb):
            outs = run(sp, self._schedule_input(*xb, needs_rng))
            out, aux = outs[0], outs[-1]
            if self.seq_axis is not None:
                # each sequence shard routes only its own tokens, so its
                # aux is a LOCAL estimate; the mean over shards is the
                # standard per-device aux of sharded MoE training — a
                # valid load-balance regularizer, but NOT bitwise the
                # full-sequence statistic (the Switch aux is a product
                # of token means, which doesn't commute with sharding)
                aux = lax.pmean(aux, self.seq_axis)
            return out, aux

        # h: (B, S, H) batch- and optionally sequence-sharded; the bias
        # (B, 1, 1, S) shards its key dim with the sequence so each ring
        # hop sees its KV shard's mask; the aux output is per-row (B,)
        hspec = P(self.batch_axis, self.seq_axis)
        bspec = P(self.batch_axis, None, None, self.seq_axis)
        rowspec = P(self.batch_axis)
        f = jax.shard_map(
            run_wrapped, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(self.pipe_axis),
                                             p["stages"]),
                      (hspec, bspec)),
            out_specs=(hspec, rowspec), **self._partial_manual_kwargs())
        seq, aux = f(p["stages"], (x, bias))
        mlm, nsp = self.heads.apply({"params": p["heads"]}, seq)
        if has_moe:
            # every row of a (shard, microbatch) group carries that
            # group's stage-summed aux; the mean over rows is the mean
            # over groups — matching the monolithic model's full-batch
            # per-layer aux scale (each layer's aux is itself a mean
            # over its tokens)
            return mlm, nsp, jnp.mean(aux)
        return mlm, nsp

    def loss_and_grad_1f1b(self, variables, input_ids, loss_fn, targets,
                           attention_mask=None, token_type_ids=None,
                           deterministic: bool = True, rngs=None,
                           moe_aux_weight: float = 0.0):
        """Memory-bounded training step: the interleaved 1F1B schedule
        (``parallel.onef1b_spmd``) instead of autodiff-through-GPipe —
        live encoder activations bounded by ``pp`` stage inputs per
        device instead of growing with the microbatch count.

        ``loss_fn(mlm_logits, nsp_logits, target_mb) -> scalar`` (mean
        over the microbatch rows); ``targets`` is any pytree of
        per-example arrays (leading batch dim), sliced into microbatches
        alongside the hidden states.  Returns ``(loss, grads)`` with
        ``grads`` matching ``variables["params"]`` — embeddings get
        their grads through the pipeline's input cotangent, the MLM/NSP
        heads through the schedule's differentiated ``loss_params``.

        Composes with ``batch_axis`` (grads are global-batch means, as
        DDP semantics require), and with ``seq_axis`` for SCAN-FREE
        sequence-parallel attention (Ulysses: all_to_all + local
        attention).  The ring exclusion was root-caused in round 4
        (``tools/repro_ring_1f1b.py``, bisected variants A-K): it is an
        **XLA SPMD-partitioner miscompile, not a semantic constraint**
        — every minimal collective-in-divergent-branch form computes
        correctly, but with a scan-carried sp-ppermute inside the
        schedule's pipe-divergent cond branches, the non-first stage's
        inject/inbox ``where(axis_index==0, ...)`` select resolves to
        the wrong side (stage 1 silently computes on the raw microbatch
        instead of its inbox; reproduces at sp=1 where the ppermute is
        a no-op self-loop, ~40-line repro, jax 0.9.0).  Attention
        factories advertise the fence via ``onef1b_compatible``
        (``make_ulysses_attention`` True, ``make_ring_attention``
        False); ring-SP stays on the GPipe schedule — one uniform
        program, no divergent cond for the partitioner to get wrong.
        ``tp_axis`` DOES compose (round 4): the same partial-manual
        shard_map as the GPipe path — GSPMD's Megatron collectives are
        plain (not scan-carried) and every model-axis group member
        takes the same branch per tick, the proven-safe class; grads
        pinned vs the monolithic model at dp x tp x pp
        (``test_bert_1f1b_dp_tp_pp_matches_monolithic``).
        Under ``seq_axis`` the last-stage loss
        all_gathers the microbatch hidden over sp (mb-sized, cheap) so
        ``loss_fn`` stays sequence-oblivious; the gather replicates
        the loss computation per sp shard and its transpose sums the
        copies, so stage grads and the input cotangent carry a 1/n_sp
        correction (see run_wrapped).

        MoE configs (dense or capacity dispatch, experts NOT sharded
        over an ep axis — the PipelinedBert regime) compose: the stage
        body stays collective-free, the per-row aux accumulator rides
        the activation pytree to the last stage, and
        ``moe_aux_weight * mean(aux)`` joins the objective there (the
        same per-microbatch aux estimate the GPipe path returns);
        router grads for earlier stages flow back through the aux
        leaf's cotangent chain.
        """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel.pipeline import onef1b_spmd

        if self.seq_axis is not None:
            # fail CLOSED: only attention_fns that explicitly declare
            # themselves scan-free may run inside the schedule's cond
            # branches — an unknown wrapper around a ring would
            # otherwise silently miscompute (wrong even at sp=1)
            if not getattr(self.attention_fn, "onef1b_compatible",
                           False):
                raise NotImplementedError(
                    "seq_axis under 1F1B needs an attention_fn marked "
                    "onef1b_compatible=True (make_ulysses_attention "
                    "is; ring attention is NOT — its collective-"
                    "carrying scan miscomputes in the schedule's cond "
                    "branches). Tag your own scan-free implementation "
                    "explicitly, or use the GPipe apply() path")
            if self.cfg.moe_experts > 0:
                raise NotImplementedError(
                    "seq_axis + MoE under 1F1B: the sp-local aux "
                    "estimate breaks the loss/grad reduction algebra; "
                    "use the GPipe apply() path")
        # tp x MoE x 1F1B: fenced in round 4 ("aux-leaf out_specs don't
        # compose with partial-manual tp"); re-probed 2026-08-01 after
        # the partial-manual/vma plumbing evolved — the composition now
        # compiles AND pins exactly against GPipe autodiff for both
        # dispatch modes incl. early-stage router grads
        # (test_bert_1f1b_tp_moe_matches_gpipe_autodiff), so the fence
        # is lifted.
        needs_rng, base_key, embed_rngs = self._dropout_setup(
            deterministic, rngs, "loss_and_grad_1f1b")

        p = variables["params"]

        def embed_f(ep):
            return self.embed.apply({"params": ep}, input_ids,
                                    token_type_ids, deterministic,
                                    rngs=embed_rngs)

        x, embed_vjp = jax.vjp(embed_f, p["embed"])
        bias = self._bias(input_ids, attention_mask)
        stage_fn = self._build_stage_fn(needs_rng, base_key,
                                        deterministic)

        # static: moe_aux_weight may be a TRACED scalar (e.g. carrying
        # the amp loss scale), so gate on python-level zeroness only
        statically_zero = (isinstance(moe_aux_weight, (int, float))
                           and moe_aux_weight == 0.0)
        use_aux = self.cfg.moe_experts > 0 and not statically_zero
        if self.cfg.moe_experts > 0 and statically_zero:
            import warnings
            warnings.warn(
                "loss_and_grad_1f1b on an MoE config with "
                "moe_aux_weight=0: the load-balance aux term is "
                "dropped and nothing pushes the router toward balance "
                "(the GPipe apply() path returns the aux explicitly); "
                "pass moe_aux_weight to include it",
                stacklevel=2)

        def pl_loss(y, tgt_mb, heads_p):
            # y is the stage activation pytree; hidden is leaf 0, the
            # bias/mb-id side leaves are not part of the objective; the
            # trailing aux leaf joins it for MoE configs
            h = y[0]
            if self.seq_axis is not None:
                # gather the microbatch's sequence shards so loss_fn
                # sees full-sequence logits (runs on every sp shard of
                # the last stage — same branch, uniform; mb-sized so
                # cheap); the gather's transpose re-scatters dh
                h = lax.all_gather(h, self.seq_axis, axis=1, tiled=True)
            mlm, nsp = self.heads.apply({"params": heads_p}, h)
            loss = loss_fn(mlm, nsp, tgt_mb)
            if use_aux:
                loss = loss + moe_aux_weight * jnp.mean(y[-1])
            return loss

        run = onef1b_spmd(stage_fn, pl_loss, self.pipe_axis,
                          self.num_microbatches)

        def run_wrapped(sp, xb, tgt, hp):
            loss, g, dxb, dhp = run(
                sp, self._schedule_input(*xb, needs_rng), tgt, hp)
            dh = dxb[0]
            if self.seq_axis:
                # the tail's all_gather REPLICATES the loss computation
                # on every sp shard, and the gather's transpose SUMS
                # the identical cotangent copies — so everything
                # upstream of the gather (stage partials, dh) carries
                # an extra n_sp factor: pmean (= psum of partials / the
                # replication count) for stage grads, dh / n_sp; head
                # grads accumulate locally as one copy per device ->
                # plain mean; loss pmean (identical values, typing)
                n_sp = lax.axis_size(self.seq_axis)
                g = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.seq_axis), g)
                loss = lax.pmean(loss, self.seq_axis)
                dhp = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.seq_axis), dhp)
                dh = dh / n_sp
            if self.batch_axis:
                # loss and param grads are means over the data shards;
                # each ROW's input grad lives in exactly one shard, so
                # dh scales by 1/n instead of pmean
                n = lax.axis_size(self.batch_axis)
                loss = lax.pmean(loss, self.batch_axis)
                g = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.batch_axis), g)
                dhp = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.batch_axis), dhp)
                dh = dh / n
            return loss, g, dh, dhp

        hspec = P(self.batch_axis, self.seq_axis)
        bspec = P(self.batch_axis, None, None, self.seq_axis)
        # TP runs partial-manual exactly like the GPipe path
        # (_partial_manual_kwargs): GSPMD's Megatron collectives land
        # INSIDE the schedule's cond branches, which is sound for the
        # same reason Ulysses composes — the model-axis collective
        # group at any (data, pipe) coordinate takes the same branch at
        # the same tick, so every group member participates (the
        # ring-SP miscompile needs a SCAN-carried collective + the
        # inject/inbox select — tools/repro_ring_1f1b.py; plain GSPMD
        # collectives are the proven-safe class).
        f = jax.shard_map(
            run_wrapped, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(self.pipe_axis),
                                             p["stages"]),
                      (hspec, bspec),
                      jax.tree_util.tree_map(
                          lambda _: P(self.batch_axis), targets),
                      jax.tree_util.tree_map(lambda _: P(), p["heads"])),
            out_specs=(P(),
                       jax.tree_util.tree_map(
                           lambda _: P(self.pipe_axis), p["stages"]),
                       hspec,
                       jax.tree_util.tree_map(lambda _: P(),
                                              p["heads"])),
            **self._partial_manual_kwargs())
        loss, stage_grads, dh, head_grads = f(p["stages"], (x, bias),
                                              targets, p["heads"])
        (embed_grads,) = embed_vjp(dh)
        # constrain_grads: without it the grads exit the partial-manual
        # shard_map with unspecified tp-axis sharding and one optimizer
        # step strips the Megatron placement (PipelinedCommon)
        return loss, self.constrain_grads(
            {"embed": embed_grads, "stages": stage_grads,
             "heads": head_grads})


class BertForPreTraining(nn.Module):
    """Encoder + MLM head + NSP head (untied decoder matrix)."""

    cfg: BertConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.cfg
        enc = BertEncoder(cfg, self.attention_fn, name="encoder")
        seq = enc(input_ids, attention_mask, token_type_ids, deterministic)
        return _pretraining_heads(cfg, seq)
