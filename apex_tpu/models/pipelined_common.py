"""Shared plumbing of the pipelined model families.

``PipelinedBert`` and ``PipelinedGPT`` differ in their stage bodies and
loss heads but share the schedule-facing contracts: Megatron placement
stacked over the pipe axis, the partial-manual shard_map kwargs for a
GSPMD-automatic tp axis, and the dropout rng prologue.  One copy here
(plus ``parallel.tensor_parallel.pipeline_param_specs``) so a fix
cannot drift between the encoder and decoder families.

The mixin reads the attributes both families set in ``__init__``:
``mesh, pipe_axis, batch_axis, seq_axis, tp_axis, num_microbatches,
cfg`` (cfg carries ``hidden_dropout_prob`` /
``attention_probs_dropout_prob``).
"""

from __future__ import annotations

import jax


class PipelinedCommon:
    #: name of this family's Megatron rules factory in
    #: ``apex_tpu.parallel.tensor_parallel`` (resolved lazily — the
    #: models package must not import parallel at module scope); set by
    #: the subclass, e.g. ``"gpt_tp_rules"``
    tp_rules_name = None

    def param_spec_tree(self, params):
        """The PartitionSpec pytree ``shard_variables`` places by and
        ``loss_and_grad_1f1b`` constrains its grads to — the tp axis is
        GSPMD-automatic inside the schedules' shard_map, so grad
        shardings come out UNSPECIFIED, XLA is free to replicate them,
        and one optimizer step would silently strip the Megatron
        placement off the updated params (found by driving a jitted
        dp x tp x pp train loop: the tied wte lost its vocab sharding
        after step 1)."""
        from apex_tpu.parallel import tensor_parallel

        rules = (getattr(tensor_parallel, self.tp_rules_name)(self.tp_axis)
                 if self.tp_axis is not None else ())
        return tensor_parallel.pipeline_param_specs(
            params, self.mesh, rules, self.pipe_axis)

    def shard_variables(self, variables):
        """Place the variables for this model's mesh: stage stacks on
        the pipe axis; with ``tp_axis``, Megatron placement (this
        family's ``tp_rules``) layers on top — stage leaves get
        ``P(pipe, *tp_spec)``, the outer groups their unstacked TP
        specs.  The TP axis stays GSPMD-automatic inside the pipeline's
        ``shard_map`` (partial-manual mode), so XLA inserts the
        Megatron collectives around the model-sharded matmuls while the
        pipe/data axes run the explicit schedule."""
        from jax.sharding import NamedSharding

        p = variables["params"]
        specs = self.param_spec_tree(p)
        return {"params": jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            dict(p), specs)}

    def constrain_grads(self, grads):
        """Pin 1F1B grads to the params' Megatron specs (see
        ``param_spec_tree``); no-op without ``tp_axis``."""
        if self.tp_axis is None:
            return grads
        from jax.sharding import NamedSharding
        return jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, s)),
            grads, self.param_spec_tree(grads))

    def _partial_manual_kwargs(self):
        """shard_map kwargs shared by the GPipe and 1F1B paths: without
        TP both run fully manual; with ``tp_axis`` the model axis stays
        GSPMD-automatic (partial-manual mode) so XLA inserts the
        Megatron collectives inside the manual schedule, and
        ``check_vma=False`` because vma checking doesn't support
        partial-auto outputs yet (the schedules' pvary discipline still
        applies — tools/repro_ring_1f1b.py variant F runs the 1F1B
        schedule under check_vma=False)."""
        if self.tp_axis is None:
            return {}
        manual = {self.pipe_axis}
        if self.batch_axis:
            manual.add(self.batch_axis)
        if self.seq_axis:
            manual.add(self.seq_axis)
        return dict(axis_names=manual, check_vma=False)

    def _microbatch_ids(self, h):
        """One microbatch id per row, assigned the way the schedules
        split the (local) batch — contiguous b_local/m groups.  Both
        families' ``_schedule_input`` must use THIS formula or the
        dropout keys drift between them."""
        import jax.numpy as jnp

        return jnp.arange(h.shape[0], dtype=jnp.int32) // \
            max(1, h.shape[0] // self.num_microbatches)

    def _stage_dropout_key(self, base_key, mb):
        """The per-(microbatch, stage[, data shard][, seq shard]) key
        chain — the single definition of GPipe/1F1B mask identity for
        both families (a fold-order change applied to one family only
        would silently desynchronize the other's 1F1B-vs-autodiff
        guarantee).  ``mb`` is the microbatch-id row vector riding the
        activation pytree (garbage during bubble ticks, whose outputs
        are discarded).  No tp-axis fold: tp is GSPMD-automatic and the
        mask must agree across the TP group."""
        from jax import lax

        key = jax.random.fold_in(base_key, mb[0])
        key = jax.random.fold_in(key, lax.axis_index(self.pipe_axis))
        if self.batch_axis:
            key = jax.random.fold_in(
                key, lax.axis_index(self.batch_axis))
        if self.seq_axis:
            key = jax.random.fold_in(
                key, lax.axis_index(self.seq_axis))
        return key

    def _dropout_setup(self, deterministic, rngs, caller):
        """Shared rng prologue of both training paths: validates the
        rngs contract and derives the embed key (a fold_in index far
        outside the microbatch-id range the stage keys use).
        Returns ``(needs_rng, base_key, embed_rngs)``."""
        cfg = self.cfg
        needs_rng = not deterministic and (
            cfg.hidden_dropout_prob > 0
            or cfg.attention_probs_dropout_prob > 0)
        if not needs_rng:
            return False, None, None
        if not rngs or "dropout" not in rngs:
            raise ValueError(
                f"{caller}(deterministic=False) with dropout in the "
                "config needs rngs={'dropout': key}")
        base_key = rngs["dropout"]
        return True, base_key, {
            "dropout": jax.random.fold_in(base_key, 2 ** 20)}
