"""MNIST-scale MLP — the minimal end-to-end amp exercise.

The reference's ``examples/simple`` tier trains toy models to demo the amp
API (SURVEY.md §7 stage 2 milestone; BASELINE.json config 1 is an
"examples/simple amp O1 MNIST MLP"). This is that model.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn


class MLP(nn.Module):
    features: Sequence[int] = (1024, 1024)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.Dense(f)(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)
