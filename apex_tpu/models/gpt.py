"""Decoder-only causal language model (GPT-style) — the long-context
flagship of the model zoo.

The reference (apex) ships no models; this family exists because the
framework's long-context machinery — causal flash attention
(``ops.flash_attention``, O(S) memory), ring/Ulysses sequence
parallelism (``parallel.sequence``), per-layer remat — needs a model
whose workload is actually causal and long, the way BERT is the
workload for FusedLAMB/FusedLayerNorm (BASELINE config 4). TPU-first
choices:

- pre-LN blocks (``FusedLayerNorm``, Pallas on TPU) — the stable-at-
  depth variant every modern decoder uses;
- attention as batched einsum -> fp32 softmax -> einsum on the default
  path, with the same pluggable ``attention_fn`` seam as
  ``models.bert`` — ``make_flash_attention(causal=True)`` swaps the
  whole stack onto the fused kernel, ``make_ulysses_attention`` /
  ``make_ring_attention`` shard the sequence axis;
- learned positional embeddings (static shapes; no data-dependent
  control flow under jit);
- weight-tied LM head (embedding transpose) — half the embedding HBM
  of an untied head at vocab scale;
- ``remat=True`` rematerializes each block in backward
  (``jax.checkpoint``) for long sequences.

Causality is enforced in-model (the causal mask/bias is built from
static positions), so callers never thread masks for plain LM
training; padding masks compose additively when given.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.pipelined_common import PipelinedCommon
from apex_tpu.normalization import FusedLayerNorm

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    # rematerialize each block in backward: the long-sequence lever
    remat: bool = False


def gpt_small() -> "GPTConfig":
    """The 124M 12x768 configuration."""
    return GPTConfig()


def gpt_medium() -> "GPTConfig":
    return GPTConfig(hidden_size=1024, num_hidden_layers=24,
                     num_attention_heads=16, intermediate_size=4096)


def _init(cfg):
    return nn.initializers.normal(cfg.initializer_range)


def _embed_block(cfg, input_ids, deterministic, positions=None):
    """Token + position embeddings + dropout, shared by
    :class:`GPTLMHeadModel` and :class:`GPTEmbed` so the param names
    and math cannot drift (same discipline as ``bert._embed_block``;
    must be called inside an ``@nn.compact`` body).  Returns
    ``(x, wte)`` — the wte module for the tied LM head.

    ``positions``: optional (B, S) explicit position indices — the
    serving decode step feeds a single token per sequence at its OWN
    position (each request sits at a different depth), where the
    default ``arange`` would embed everything at position 0."""
    init = _init(cfg)
    s = input_ids.shape[1]
    wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                   embedding_init=init, name="wte")
    x = wte(input_ids)
    if positions is None:
        positions = jnp.arange(s)[None, :]
    x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                     embedding_init=init, name="wpe")(positions)
    x = nn.Dropout(cfg.hidden_dropout_prob,
                   deterministic=deterministic)(x)
    return x, wte


def causal_dot_product_attention(q, k, v, bias=None, dropout_fn=None):
    """Default path: (B, S, H, D) -> (B, S, H, D). The causal mask is
    built from static positions and folded into the additive bias;
    everything else (scaling, fp32 softmax, dropout hook) DELEGATES to
    ``models.bert.dot_product_attention`` so the numeric policy cannot
    drift between the encoder and decoder families."""
    from apex_tpu.models.bert import dot_product_attention

    sq, sk = q.shape[1], k.shape[1]
    cmask = jnp.where(jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :],
                      0.0, NEG_INF)
    bias = (cmask[None, None] if bias is None
            else bias + cmask[None, None])
    return dot_product_attention(q, k, v, bias=bias,
                                 dropout_fn=dropout_fn)


class GPTSelfAttention(nn.Module):
    cfg: GPTConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True,
                 cache_view=None, return_kv: bool = False,
                 kv_quant: bool = False):
        """``cache_view``: serving mode — ``(k_ctx, v_ctx, ctx_bias)``
        with k/v_ctx (B, T, H, D) gathered cache context and ctx_bias
        (B, T) additive (0 keep / NEG_INF for unwritten slots).  With x
        a single new token (B, 1, h) — decode — attention runs over
        [context; self] via ``ops.cached_attention``; with x a prefill
        CHUNK (B, C, h) it runs over [context; chunk] via
        ``ops.chunk_cached_attention`` (all cached positions precede
        the chunk, causal within it).  ``attention_fn`` (a causal
        full-sequence kernel) is deliberately bypassed on both.
        ``return_kv``: also return this call's freshly projected
        ``(k, v)`` so the serving engine can append them to the cache.
        Both default off — the training path is byte-identical to
        before.

        ``kv_quant``: int8-quantized-pool serving (``docs/serving.md``,
        "Quantized KV cache").  The freshly projected K/V quantize AT
        THE SOURCE (:func:`ops.kv_quant.quantize_kv`, per token per
        head) and attention everywhere operates on the QUANTIZED grid
        — the cache context arrives int8 with its scale sidecar
        (``cache_view`` is then the 5-tuple ``(k_ctx, v_ctx, ctx_bias,
        k_scale_ctx, v_scale_ctx)``), the token's own / within-chunk
        K/V concatenate as int8 with their fresh scales, and the
        no-cache causal forward attends the dequantized values.  That
        uniformity is the bit-stability argument: a (query, key)
        pair's score is identical whether the key is fresh this call,
        fresh earlier in the same chunk, or read back from the pool —
        so chunking boundaries, preemption re-prefill, COW, and
        speculation cannot move a logit.  ``return_kv`` then returns
        ``((k_q, k_scale), (v_q, v_scale))`` — byte-for-byte what
        attention just used, ready to scatter."""
        cfg = self.cfg
        h, nh = cfg.hidden_size, cfg.num_attention_heads
        init = _init(cfg)

        def proj(name):
            return nn.DenseGeneral((nh, h // nh), kernel_init=init,
                                   name=name)(x)

        q, k, v = proj("query"), proj("key"), proj("value")
        kv_out = (k, v)
        if kv_quant:
            from apex_tpu.ops.kv_quant import dequantize_kv, quantize_kv

            (k_q, k_s), (v_q, v_s) = quantize_kv(k), quantize_kv(v)
            kv_out = ((k_q, k_s), (v_q, v_s))
        if cache_view is not None:
            from apex_tpu.ops.decode_attention import (
                cached_attention,
                chunk_cached_attention,
            )

            if kv_quant:
                # int8 end to end: quantized context + the chunk's own
                # quantized K/V concatenate with their scale rows; the
                # attention ops widen at read (in-kernel on the Pallas
                # path), so no dequantized context ever materializes
                k_ctx, v_ctx, ctx_bias, ks_ctx, vs_ctx = cache_view
                k_full = jnp.concatenate([k_ctx, k_q], axis=1)
                v_full = jnp.concatenate([v_ctx, v_q], axis=1)
                ks_full = jnp.concatenate([ks_ctx, k_s], axis=1)
                vs_full = jnp.concatenate([vs_ctx, v_s], axis=1)
            else:
                k_ctx, v_ctx, ctx_bias = cache_view
                # the new token(s) attend the gathered past plus
                # themselves
                k_full = jnp.concatenate(
                    [k_ctx.astype(k.dtype), k], axis=1)
                v_full = jnp.concatenate(
                    [v_ctx.astype(v.dtype), v], axis=1)
                ks_full = vs_full = None
            if x.shape[1] == 1:
                # decode: the self slot is always live (bias 0)
                bias = jnp.concatenate(
                    [ctx_bias, jnp.zeros((x.shape[0], 1), jnp.float32)],
                    axis=1)
                ctx = cached_attention(q, k_full, v_full, kv_bias=bias,
                                       k_scale=ks_full,
                                       v_scale=vs_full)
            else:
                # chunked prefill: context masked by ctx_bias, causal
                # within the chunk
                ctx = chunk_cached_attention(q, k_full, v_full,
                                             ctx_bias,
                                             k_scale=ks_full,
                                             v_scale=vs_full)
        else:
            dropout_fn = None
            if cfg.attention_probs_dropout_prob > 0 and not deterministic:
                drop = nn.Dropout(cfg.attention_probs_dropout_prob,
                                  deterministic=False)
                dropout_fn = lambda p: drop(p)
                if self.attention_fn is not None:
                    # same (rate, seed) annotation contract as BERT so
                    # the fused kernels run dropout in-kernel
                    # (ops.flash_attention.dropout_params)
                    dropout_fn.rate = cfg.attention_probs_dropout_prob
                    dropout_fn.seed = jax.random.randint(
                        self.make_rng("dropout"), (), 0,
                        jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
            attn = self.attention_fn or causal_dot_product_attention
            if kv_quant:
                # quantized serving's monolithic prefill: attend the
                # DEQUANTIZED k/v — the same grid every later chunk,
                # decode, or verify step reads back from the pool —
                # through the unchanged causal path (attention_fn
                # included; it is just a different k/v operand)
                k_at = dequantize_kv(k_q, k_s, k.dtype)
                v_at = dequantize_kv(v_q, v_s, v.dtype)
            else:
                k_at, v_at = k, v
            ctx = attn(q, k_at, v_at, bias=attn_bias,
                       dropout_fn=dropout_fn)
        out = nn.DenseGeneral(h, axis=(-2, -1), kernel_init=init,
                              name="output")(ctx)
        if return_kv:
            return out, kv_out
        return out


class GPTBlock(nn.Module):
    """Pre-LN: x + Attn(LN(x)); x + MLP(LN(x)).

    ``cache_view``/``return_kv`` thread straight through to
    :class:`GPTSelfAttention` (serving decode/prefill); the training
    call sites never pass them."""

    cfg: GPTConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True,
                 cache_view=None, return_kv: bool = False,
                 kv_quant: bool = False):
        cfg = self.cfg
        init = _init(cfg)
        drop = nn.Dropout(cfg.hidden_dropout_prob,
                          deterministic=deterministic)
        h = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                           name="attn_ln")(x)
        h = GPTSelfAttention(cfg, self.attention_fn,
                             name="attention")(h, attn_bias,
                                               deterministic,
                                               cache_view=cache_view,
                                               return_kv=return_kv,
                                               kv_quant=kv_quant)
        kv = None
        if return_kv:
            h, kv = h
        x = x + drop(h)
        h = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                           name="mlp_ln")(x)
        h = nn.Dense(cfg.intermediate_size, kernel_init=init,
                     name="mlp_in")(h)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.hidden_size, kernel_init=init,
                     name="mlp_out")(h)
        if return_kv:
            return x + drop(h), kv
        return x + drop(h)


class GPTLMHeadModel(nn.Module):
    """Token + position embeddings -> pre-LN blocks -> final LN ->
    weight-tied LM head. Returns (B, S, V) fp32 logits.

    ``attention_fn``: optional fused/sequence-parallel attention with
    the ``models.bert`` adapter signature. The DEFAULT path and the
    flash path are both causal; adapters must be built causal
    (``make_flash_attention(causal=True)``,
    ``make_ring_attention("sp", causal=True)``) — there is no way to
    express a non-causal LM here.
    ``attention_mask``: optional (B, S) 1/0 padding mask, additive on
    key positions on top of causality.

    Serving hooks (``apex_tpu.serving.engine`` is the caller; training
    code never passes them):

    - ``positions``: explicit (B, S) position-embedding indices
      (decode feeds one token per sequence at its own depth);
    - ``cache_views``: serving mode — ``(k_ctx, v_ctx, ctx_bias)`` with
      k/v_ctx (L, B, T, H, D) per-layer gathered KV-cache context and
      ctx_bias (B, T); each block attends [its context; self] (decode,
      S == 1) or [its context; chunk] causally (chunked prefill,
      S > 1);
    - ``return_kv``: also return the per-layer freshly projected
      ``(k, v)`` list so the engine can write them into the cache
      (prefill uses this with ``cache_views=None`` — the normal causal
      forward, optionally through the flash ``attention_fn``);
    - ``kv_quant``: int8-quantized-pool serving — ``cache_views``
      grows per-layer fp32 scale sidecars (a 5-tuple), fresh K/V
      quantize at projection and attention runs on the quantized grid
      everywhere, and ``return_kv`` yields per-layer
      ``((k_q, k_scale), (v_q, v_scale))`` (``docs/serving.md``,
      "Quantized KV cache").
    """

    cfg: GPTConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, attention_mask=None,
                 deterministic: bool = True,
                 return_hidden: bool = False,
                 positions=None, cache_views=None,
                 return_kv: bool = False,
                 kv_quant: bool = False):
        cfg = self.cfg
        x, wte = _embed_block(cfg, input_ids, deterministic, positions)
        bias = None
        if attention_mask is not None:
            bias = jnp.where(attention_mask[:, None, None, :] > 0,
                             0.0, NEG_INF).astype(jnp.float32)
        block = GPTBlock
        if cfg.remat and not return_kv:
            # deterministic (argnum 3; self=0) is the static arg — the
            # bias is a traced array (same as models.bert). Inference
            # (return_kv) never remats: there is no backward to save
            # memory for, and the kv pytree output confuses the policy.
            block = nn.remat(GPTBlock, static_argnums=(3,))
        kvs = []
        for i in range(cfg.num_hidden_layers):
            cv = None
            if cache_views is not None:
                if kv_quant:
                    # quantized serving: (k, v, bias, k_scale,
                    # v_scale) with int8 payloads and the per-layer
                    # scale sidecar riding along
                    k_ctx, v_ctx, ctx_bias, ks_ctx, vs_ctx = \
                        cache_views
                    cv = (k_ctx[i], v_ctx[i], ctx_bias,
                          ks_ctx[i], vs_ctx[i])
                else:
                    k_ctx, v_ctx, ctx_bias = cache_views
                    cv = (k_ctx[i], v_ctx[i], ctx_bias)
            if return_kv:
                x, kv = block(cfg, self.attention_fn,
                              name=f"block_{i}")(
                    x, bias, deterministic, cache_view=cv,
                    return_kv=True, kv_quant=kv_quant)
                kvs.append(kv)
            else:
                x = block(cfg, self.attention_fn, name=f"block_{i}")(
                    x, bias, deterministic)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                           name="final_ln")(x)
        if return_hidden:
            # for ops.vocab_parallel_lm_loss: under TP the (B, S, V)
            # logits should never be materialized — hand back the
            # pre-head hidden instead and let the vocab-parallel loss
            # consume it with the sharded wte
            return x
        # weight-tied head: logits = x @ wte^T
        logits = wte.attend(x)
        if return_kv:
            return logits.astype(jnp.float32), kvs
        return logits.astype(jnp.float32)


def _lm_masked_sum(logits, input_ids, attention_mask):
    """Masked SUM of next-token cross entropy (no normalization) — the
    microbatch-side half of the exact masked mean: each 1F1B microbatch
    contributes its sum and the precomputed global denominator turns
    the schedule's mean-over-microbatches into the exact global masked
    mean, independent of padding skew (see PipelinedGPT)."""
    import optax

    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], input_ids[:, 1:])
    return (per_tok * attention_mask[:, 1:].astype(per_tok.dtype)).sum()


def lm_loss(logits, input_ids, attention_mask=None):
    """Next-token cross entropy: predict token t+1 from prefix <= t.
    Position S-1 has no target and is dropped; with a padding mask,
    positions whose TARGET is padding are dropped too. Mean over kept
    positions."""
    import optax

    if attention_mask is None:
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], input_ids[:, 1:]).mean()
    # one definition of the shift-and-mask numerator (shared with the
    # 1F1B per-microbatch contribution) so the conventions cannot drift
    keep = attention_mask[:, 1:].sum().astype(logits.dtype)
    return (_lm_masked_sum(logits, input_ids, attention_mask)
            / jnp.maximum(keep, 1.0))


class GPTStage(nn.Module):
    """``n_layers`` consecutive pre-LN blocks — one pipeline stage."""

    cfg: GPTConfig
    n_layers: int
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True):
        block = GPTBlock
        if self.cfg.remat:
            block = nn.remat(GPTBlock, static_argnums=(3,))
        for i in range(self.n_layers):
            x = block(self.cfg, self.attention_fn, name=f"block_{i}")(
                x, attn_bias, deterministic)
        return x


class GPTEmbed(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True):
        x, _ = _embed_block(self.cfg, input_ids, deterministic)
        return x


class PipelinedGPT(PipelinedCommon):
    """GPT over a ``pipe`` mesh axis — the decoder counterpart of
    :class:`models.PipelinedBert` (same schedules,
    ``parallel.pipeline``; same variables convention so
    ``amp.initialize`` wraps it).

    Param groups: ``embed`` (wte/wpe, replicated), ``stages`` (blocks
    stacked ``(pp, ...)`` and pipe-sharded), ``head`` (the final LN;
    the LM projection is TIED to ``embed/wte``). The tied head makes
    the 1F1B grad flow the interesting part: ``wte``'s gradient has an
    input-side contribution (token lookup, via the pipeline's input
    cotangent) and a head-side contribution (the logits projection,
    via the schedule's differentiated ``loss_params``) — they come
    back on separate paths and are SUMMED, which is exactly the tied
    parameter's chain rule.

    ``batch_axis`` composes (DDP mean semantics), and ``seq_axis``
    shards the sequence inside the pipeline (dp x sp x pp) when paired
    with a sequence-parallel ``attention_fn`` for the same axis —
    under 1F1B the attention must be scan-free
    (``make_ulysses_attention``; the ring is fenced, see
    tools/repro_ring_1f1b.py).

    ``tp_axis`` layers Megatron tensor parallelism on top
    (``parallel.gpt_tp_rules``): stage weights take
    ``P(pipe, ...model...)`` placement and the TP axis stays
    GSPMD-automatic inside the pipeline's ``shard_map``
    (partial-manual mode) — same machinery as ``PipelinedBert``.  The
    TIED ``wte`` shards its vocab dim, so the LM-head einsum runs
    column-parallel (each device computes its vocab slice of the
    logits) instead of replicating the whole-vocab matmul.  Same KNOWN
    LIMITATION as PipelinedBert: amp O2/O3 compute inside the
    partial-manual region trips this jax build's XLA CPU backend;
    ``tp_axis`` is tested fp32 (tools/tp_pp_bf16_check.py rechecks the
    TPU backend at live windows).

    Dropout composes like PipelinedBert: ``deterministic=False`` +
    ``rngs={"dropout": key}``; each (microbatch, stage[, shard]) folds
    its coordinates into the key inside the pipeline body.
    """

    def __init__(self, cfg: GPTConfig, mesh, pp: int,
                 num_microbatches: int, pipe_axis: str = "pipe",
                 batch_axis: Optional[str] = None,
                 seq_axis: Optional[str] = None,
                 tp_axis: Optional[str] = None,
                 attention_fn: Optional[Callable] = None):
        if cfg.num_hidden_layers % pp:
            raise ValueError(
                f"num_hidden_layers={cfg.num_hidden_layers} must divide "
                f"into pp={pp} equal stages")
        if seq_axis is not None and attention_fn is None:
            raise ValueError(
                "seq_axis requires a sequence-parallel attention_fn for "
                "the same axis (parallel.make_ulysses_attention(seq_axis, "
                "causal=True)) — plain attention would silently attend "
                "only within each sequence shard")
        self.cfg = cfg
        self.mesh = mesh
        self.pp = pp
        self.num_microbatches = num_microbatches
        self.pipe_axis = pipe_axis
        self.batch_axis = batch_axis
        self.seq_axis = seq_axis
        self.tp_axis = tp_axis
        self.attention_fn = attention_fn
        self.embed = GPTEmbed(cfg)
        self.stage = GPTStage(cfg, cfg.num_hidden_layers // pp,
                              attention_fn)
        self._stage_init = GPTStage(cfg, cfg.num_hidden_layers // pp,
                                    None)
        self.final_ln = FusedLayerNorm(cfg.hidden_size,
                                       eps=cfg.layer_norm_eps)

    def init(self, rng, input_ids):
        r_embed, r_stage, r_head = jax.random.split(rng, 3)
        embed_p = self.embed.init(r_embed, input_ids, True)["params"]
        x0 = self.embed.apply({"params": embed_p}, input_ids, True)
        bias0 = self._bias(input_ids, None)
        stage_p = jax.vmap(
            lambda r: self._stage_init.init(r, x0, bias0, True)["params"])(
            jax.random.split(r_stage, self.pp))
        head_p = self.final_ln.init(r_head, x0)["params"]
        return {"params": {"embed": embed_p, "stages": stage_p,
                           "head": head_p}}

    # param_spec_tree / shard_variables / constrain_grads /
    # _partial_manual_kwargs / _dropout_setup come from PipelinedCommon
    tp_rules_name = "gpt_tp_rules"

    def _schedule_input(self, h, b, needs_rng):
        """Activation tuple both schedules feed their stage_fn:
        ``(hidden, bias[, mb_ids])`` — mb ids carry one microbatch id
        per row (contiguous groups, matching how the schedules split
        the local batch) for per-(microbatch, stage) dropout keys.
        No MoE aux leaf here: GPTConfig has no expert knobs."""
        if needs_rng:
            return (h, b, self._microbatch_ids(h))
        return (h, b)

    def _build_stage_fn(self, needs_rng, base_key, deterministic):
        """The per-stage body both schedules share — the decoder port
        of ``PipelinedBert._build_stage_fn`` (per-(microbatch, stage
        [, shard]) dropout keys derived inside the pipeline body so
        1F1B's rematerialized backward draws the same masks as the
        GPipe forward)."""

        def stage_fn(sp, xb):
            h, b, mb = xb if needs_rng else (xb[0], xb[1], None)
            stage_rngs = None
            if needs_rng:
                stage_rngs = {
                    "dropout": self._stage_dropout_key(base_key, mb)}
            out = self.stage.apply(
                {"params": sp}, h, b,
                deterministic if stage_rngs is None else False,
                rngs=stage_rngs)
            if needs_rng:
                return (out, b, mb)
            return (out, b)

        return stage_fn

    def _bias(self, input_ids, attention_mask):
        b, s = input_ids.shape
        if attention_mask is None:
            return jnp.zeros((b, 1, 1, s), jnp.float32)
        return jnp.where(attention_mask[:, None, None, :] > 0,
                         0.0, NEG_INF).astype(jnp.float32)

    def _head(self, h, head_p, wte):
        x = self.final_ln.apply({"params": head_p}, h)
        return jnp.einsum("bsh,vh->bsv", x, wte).astype(jnp.float32)

    def apply(self, variables, input_ids, attention_mask=None,
              deterministic: bool = True, rngs=None):
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel.pipeline import gpipe_spmd

        needs_rng, base_key, embed_rngs = self._dropout_setup(
            deterministic, rngs, "PipelinedGPT.apply")

        p = variables["params"]
        x = self.embed.apply({"params": p["embed"]}, input_ids,
                             deterministic, rngs=embed_rngs)
        bias = self._bias(input_ids, attention_mask)

        stage_fn = self._build_stage_fn(needs_rng, base_key,
                                        deterministic)
        run = gpipe_spmd(stage_fn, self.pipe_axis, self.num_microbatches)

        def run_wrapped(sp, xb):
            return run(sp, self._schedule_input(*xb, needs_rng))[0]

        hspec = P(self.batch_axis, self.seq_axis)
        bspec = P(self.batch_axis, None, None, self.seq_axis)
        f = jax.shard_map(
            run_wrapped, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(self.pipe_axis),
                                             p["stages"]),
                      (hspec, bspec)),
            out_specs=hspec, **self._partial_manual_kwargs())
        h = f(p["stages"], (x, bias))
        return self._head(h, p["head"],
                          p["embed"]["wte"]["embedding"])

    def loss_and_grad_1f1b(self, variables, input_ids, targets,
                           attention_mask=None,
                           deterministic: bool = True, rngs=None):
        """1F1B training step: ``targets`` are the (B, S) token ids the
        loss shifts against (usually ``input_ids`` itself).  Returns
        ``(loss, grads)`` with grads matching ``variables["params"]``;
        the tied ``wte`` grad sums its embedding-lookup and LM-head
        contributions.

        ``attention_mask`` reaches both the attention bias and the
        loss (pad targets dropped).  The masked loss is EXACT under
        arbitrary padding skew: each microbatch contributes its masked
        SUM over a precomputed global denominator (total valid targets
        / microbatch-shard units), so the schedule's mean over
        microbatches — and the dp pmean — reconstruct the monolithic
        global masked mean regardless of how valid counts distribute
        across microbatches or data shards (the naive mean of
        per-microbatch masked means silently drifts; pinned by
        ``test_pipelined_gpt_1f1b_mask_skewed_padding_exact``).
        """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel.pipeline import onef1b_spmd

        if self.seq_axis is not None and not getattr(
                self.attention_fn, "onef1b_compatible", False):
            # same fail-closed rule as PipelinedBert: only scan-free
            # attention may run inside the schedule's cond branches
            # (the ring's scan-carried collective miscompiles there —
            # tools/repro_ring_1f1b.py)
            raise NotImplementedError(
                "seq_axis under 1F1B needs an attention_fn marked "
                "onef1b_compatible=True (make_ulysses_attention is; "
                "ring attention is NOT). Use the GPipe apply() path "
                "for ring-SP")

        needs_rng, base_key, embed_rngs = self._dropout_setup(
            deterministic, rngs, "loss_and_grad_1f1b")

        p = variables["params"]

        def embed_f(ep):
            return self.embed.apply({"params": ep}, input_ids,
                                    deterministic, rngs=embed_rngs)

        x, embed_vjp = jax.vjp(embed_f, p["embed"])
        bias = self._bias(input_ids, attention_mask)

        stage_fn = self._build_stage_fn(needs_rng, base_key,
                                        deterministic)

        def pl_loss(y, tgt_mb, lp):
            h = y[0]
            if self.seq_axis is not None:
                # gather the microbatch's sequence shards so the loss
                # shift sees the full sequence (runs on every sp shard
                # of the last stage — uniform branch, mb-sized)
                h = lax.all_gather(h, self.seq_axis, axis=1, tiled=True)
            logits = self._head(h, lp["head"], lp["wte"])
            mask = tgt_mb.get("mask")
            if mask is not None:
                # EXACT masked mean under arbitrary padding skew: the
                # microbatch contributes its masked SUM over the global
                # denominator (rides tgt as a per-row constant); the
                # schedule's mean over microbatches and run_wrapped's
                # dp pmean then reconstruct sum(all)/keep(all) exactly
                # — a per-microbatch masked MEAN would silently drift
                # whenever microbatches carry unequal valid counts
                return (_lm_masked_sum(logits, tgt_mb["ids"], mask)
                        / tgt_mb["denom"][0])
            return lm_loss(logits, tgt_mb["ids"])

        run = onef1b_spmd(stage_fn, pl_loss, self.pipe_axis,
                          self.num_microbatches)
        loss_params = {"head": p["head"],
                       "wte": p["embed"]["wte"]["embedding"]}
        tgt_tree = {"ids": targets}
        if attention_mask is not None:
            tgt_tree["mask"] = attention_mask
            # global denominator D = total_keep / (microbatch-shard
            # units): per-mb loss sum/D, meaned over M units per shard
            # and pmean'd over n_dp shards, equals the monolithic
            # global masked mean bit-for-bit in exact arithmetic
            n_dp = (self.mesh.shape[self.batch_axis]
                    if self.batch_axis else 1)
            total_keep = jnp.maximum(
                attention_mask[:, 1:].sum().astype(jnp.float32), 1.0)
            tgt_tree["denom"] = jnp.full(
                (targets.shape[0],),
                total_keep / (self.num_microbatches * n_dp),
                jnp.float32)

        def run_wrapped(sp, xb, tgt, lp):
            loss, g, dxb, dlp = run(
                sp, self._schedule_input(*xb, needs_rng), tgt, lp)
            dh = dxb[0]
            if self.seq_axis:
                # the tail's all_gather REPLICATES the loss per sp
                # shard and its transpose SUMS the identical cotangent
                # copies, so stage partials / head grads / dh carry an
                # extra n_sp factor (same algebra as PipelinedBert)
                n_sp = lax.axis_size(self.seq_axis)
                loss = lax.pmean(loss, self.seq_axis)
                g = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.seq_axis), g)
                dlp = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.seq_axis), dlp)
                dh = dh / n_sp
            if self.batch_axis:
                n = lax.axis_size(self.batch_axis)
                loss = lax.pmean(loss, self.batch_axis)
                g = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.batch_axis), g)
                dlp = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, self.batch_axis), dlp)
                dh = dh / n
            return loss, g, dh, dlp

        hspec = P(self.batch_axis, self.seq_axis)
        bspec = P(self.batch_axis, None, None, self.seq_axis)
        f = jax.shard_map(
            run_wrapped, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(self.pipe_axis),
                                             p["stages"]),
                      (hspec, bspec),
                      jax.tree_util.tree_map(lambda _: P(self.batch_axis),
                                             tgt_tree),
                      jax.tree_util.tree_map(lambda _: P(), loss_params)),
            out_specs=(P(),
                       jax.tree_util.tree_map(
                           lambda _: P(self.pipe_axis), p["stages"]),
                       hspec,
                       jax.tree_util.tree_map(lambda _: P(),
                                              loss_params)),
            **self._partial_manual_kwargs())
        loss, stage_grads, dh, lp_grads = f(p["stages"], (x, bias),
                                            tgt_tree, loss_params)
        (embed_grads,) = embed_vjp(dh)
        # tied wte: embedding-lookup grad + LM-head grad, summed (the
        # vjp's cotangent tree is fresh, so shallow-copying the two
        # dicts we touch keeps the mutation local and explicit)
        embed_grads = {**embed_grads, "wte": dict(embed_grads["wte"])}
        embed_grads["wte"]["embedding"] = (
            embed_grads["wte"]["embedding"] + lp_grads["wte"])
        # constrain_grads: without it the grads exit the partial-manual
        # shard_map with unspecified tp-axis sharding and one optimizer
        # step strips the Megatron placement (PipelinedCommon)
        return loss, self.constrain_grads(
            {"embed": embed_grads, "stages": stage_grads,
             "head": lp_grads["head"]})
