"""Decoder-only causal language model (GPT-style) — the long-context
flagship of the model zoo.

The reference (apex) ships no models; this family exists because the
framework's long-context machinery — causal flash attention
(``ops.flash_attention``, O(S) memory), ring/Ulysses sequence
parallelism (``parallel.sequence``), per-layer remat — needs a model
whose workload is actually causal and long, the way BERT is the
workload for FusedLAMB/FusedLayerNorm (BASELINE config 4). TPU-first
choices:

- pre-LN blocks (``FusedLayerNorm``, Pallas on TPU) — the stable-at-
  depth variant every modern decoder uses;
- attention as batched einsum -> fp32 softmax -> einsum on the default
  path, with the same pluggable ``attention_fn`` seam as
  ``models.bert`` — ``make_flash_attention(causal=True)`` swaps the
  whole stack onto the fused kernel, ``make_ulysses_attention`` /
  ``make_ring_attention`` shard the sequence axis;
- learned positional embeddings (static shapes; no data-dependent
  control flow under jit);
- weight-tied LM head (embedding transpose) — half the embedding HBM
  of an untied head at vocab scale;
- ``remat=True`` rematerializes each block in backward
  (``jax.checkpoint``) for long sequences.

Causality is enforced in-model (the causal mask/bias is built from
static positions), so callers never thread masks for plain LM
training; padding masks compose additively when given.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    # rematerialize each block in backward: the long-sequence lever
    remat: bool = False


def gpt_small() -> "GPTConfig":
    """The 124M 12x768 configuration."""
    return GPTConfig()


def gpt_medium() -> "GPTConfig":
    return GPTConfig(hidden_size=1024, num_hidden_layers=24,
                     num_attention_heads=16, intermediate_size=4096)


def _init(cfg):
    return nn.initializers.normal(cfg.initializer_range)


def causal_dot_product_attention(q, k, v, bias=None, dropout_fn=None):
    """Default path: (B, S, H, D) -> (B, S, H, D). The causal mask is
    built from static positions and folded into the additive bias;
    everything else (scaling, fp32 softmax, dropout hook) DELEGATES to
    ``models.bert.dot_product_attention`` so the numeric policy cannot
    drift between the encoder and decoder families."""
    from apex_tpu.models.bert import dot_product_attention

    sq, sk = q.shape[1], k.shape[1]
    cmask = jnp.where(jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :],
                      0.0, NEG_INF)
    bias = (cmask[None, None] if bias is None
            else bias + cmask[None, None])
    return dot_product_attention(q, k, v, bias=bias,
                                 dropout_fn=dropout_fn)


class GPTSelfAttention(nn.Module):
    cfg: GPTConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True):
        cfg = self.cfg
        h, nh = cfg.hidden_size, cfg.num_attention_heads
        init = _init(cfg)

        def proj(name):
            return nn.DenseGeneral((nh, h // nh), kernel_init=init,
                                   name=name)(x)

        q, k, v = proj("query"), proj("key"), proj("value")
        dropout_fn = None
        if cfg.attention_probs_dropout_prob > 0 and not deterministic:
            drop = nn.Dropout(cfg.attention_probs_dropout_prob,
                              deterministic=False)
            dropout_fn = lambda p: drop(p)
            if self.attention_fn is not None:
                # same (rate, seed) annotation contract as BERT so the
                # fused kernels run dropout in-kernel
                # (ops.flash_attention.dropout_params)
                dropout_fn.rate = cfg.attention_probs_dropout_prob
                dropout_fn.seed = jax.random.randint(
                    self.make_rng("dropout"), (), 0,
                    jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
        attn = self.attention_fn or causal_dot_product_attention
        ctx = attn(q, k, v, bias=attn_bias, dropout_fn=dropout_fn)
        return nn.DenseGeneral(h, axis=(-2, -1), kernel_init=init,
                               name="output")(ctx)


class GPTBlock(nn.Module):
    """Pre-LN: x + Attn(LN(x)); x + MLP(LN(x))."""

    cfg: GPTConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, attn_bias, deterministic: bool = True):
        cfg = self.cfg
        init = _init(cfg)
        drop = nn.Dropout(cfg.hidden_dropout_prob,
                          deterministic=deterministic)
        h = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                           name="attn_ln")(x)
        h = GPTSelfAttention(cfg, self.attention_fn,
                             name="attention")(h, attn_bias,
                                               deterministic)
        x = x + drop(h)
        h = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                           name="mlp_ln")(x)
        h = nn.Dense(cfg.intermediate_size, kernel_init=init,
                     name="mlp_in")(h)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.hidden_size, kernel_init=init,
                     name="mlp_out")(h)
        return x + drop(h)


class GPTLMHeadModel(nn.Module):
    """Token + position embeddings -> pre-LN blocks -> final LN ->
    weight-tied LM head. Returns (B, S, V) fp32 logits.

    ``attention_fn``: optional fused/sequence-parallel attention with
    the ``models.bert`` adapter signature. The DEFAULT path and the
    flash path are both causal; adapters must be built causal
    (``make_flash_attention(causal=True)``,
    ``make_ring_attention("sp", causal=True)``) — there is no way to
    express a non-causal LM here.
    ``attention_mask``: optional (B, S) 1/0 padding mask, additive on
    key positions on top of causality.
    """

    cfg: GPTConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.cfg
        b, s = input_ids.shape
        init = _init(cfg)
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       embedding_init=init, name="wte")
        x = wte(input_ids)
        x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                         embedding_init=init, name="wpe")(
            jnp.arange(s)[None, :])
        x = nn.Dropout(cfg.hidden_dropout_prob,
                       deterministic=deterministic)(x)
        bias = None
        if attention_mask is not None:
            bias = jnp.where(attention_mask[:, None, None, :] > 0,
                             0.0, NEG_INF).astype(jnp.float32)
        block = GPTBlock
        if cfg.remat:
            # deterministic (argnum 3; self=0) is the static arg — the
            # bias is a traced array (same as models.bert)
            block = nn.remat(GPTBlock, static_argnums=(3,))
        for i in range(cfg.num_hidden_layers):
            x = block(cfg, self.attention_fn, name=f"block_{i}")(
                x, bias, deterministic)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps,
                           name="final_ln")(x)
        # weight-tied head: logits = x @ wte^T
        logits = wte.attend(x)
        return logits.astype(jnp.float32)


def lm_loss(logits, input_ids, attention_mask=None):
    """Next-token cross entropy: predict token t+1 from prefix <= t.
    Position S-1 has no target and is dropped; with a padding mask,
    positions whose TARGET is padding are dropped too. Mean over kept
    positions."""
    import optax

    targets = input_ids[:, 1:]
    lg = logits[:, :-1]
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        lg, targets)
    if attention_mask is None:
        return per_tok.mean()
    keep = attention_mask[:, 1:].astype(per_tok.dtype)
    return (per_tok * keep).sum() / jnp.maximum(keep.sum(), 1.0)
