"""DCGAN generator/discriminator — the multi-model/multi-optimizer workload.

The reference's ``examples/dcgan`` is an empty README promising "an example
showing use of multiple models/optimizers/losses" with amp
(``examples/dcgan/README.md``; the API hooks are ``num_losses`` and
``loss_id``, reference ``frontend.py:248-254``). This supplies the actual
models so that exercise is runnable: standard DCGAN (Radford et al. 2016)
in NHWC for TPU.

BatchNorm uses the norm-factory pattern so SyncBN conversion works on GANs
too. Generator maps (B, 1, 1, z_dim) noise to (B, 64, 64, C) images in
[-1, 1]; discriminator mirrors it down to per-image logits.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any

default_norm = functools.partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5)

# DCGAN init: N(0, 0.02)
dcgan_init = nn.initializers.normal(0.02)


class Generator(nn.Module):
    z_dim: int = 100
    base_features: int = 64
    out_channels: int = 3
    norm: ModuleDef = default_norm

    @nn.compact
    def __call__(self, z, train: bool = True):
        f = self.base_features
        x = z.reshape((z.shape[0], 1, 1, self.z_dim))
        # 1x1 -> 4x4 -> 8 -> 16 -> 32 -> 64
        x = nn.ConvTranspose(f * 8, (4, 4), (1, 1), padding="VALID",
                             use_bias=False, kernel_init=dcgan_init)(x)
        x = self.norm(use_running_average=not train)(x)
        x = nn.relu(x)
        for mult in (4, 2, 1):
            x = nn.ConvTranspose(f * mult, (4, 4), (2, 2), padding="SAME",
                                 use_bias=False, kernel_init=dcgan_init)(x)
            x = self.norm(use_running_average=not train)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(self.out_channels, (4, 4), (2, 2),
                             padding="SAME", use_bias=False,
                             kernel_init=dcgan_init)(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    base_features: int = 64
    norm: ModuleDef = default_norm

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.shape[1] != 64 or x.shape[2] != 64:
            # the DCGAN topology (4 stride-2 convs + a 4x4 VALID head) is
            # 64px-specific; other sizes silently collapse to 0-dim maps
            raise ValueError(
                f"DCGAN discriminator expects 64x64 inputs, got "
                f"{x.shape[1]}x{x.shape[2]}")
        f = self.base_features
        x = nn.Conv(f, (4, 4), (2, 2), padding=1, use_bias=False,
                    kernel_init=dcgan_init)(x)
        x = nn.leaky_relu(x, 0.2)
        for mult in (2, 4, 8):
            x = nn.Conv(f * mult, (4, 4), (2, 2), padding=1, use_bias=False,
                        kernel_init=dcgan_init)(x)
            x = self.norm(use_running_average=not train)(x)
            x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(1, (4, 4), (1, 1), padding="VALID", use_bias=False,
                    kernel_init=dcgan_init)(x)
        return x.reshape((x.shape[0],)).astype(jnp.float32)
