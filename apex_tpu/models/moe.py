"""Mixture-of-experts MLP with expert parallelism over a mesh axis.

The reference has no MoE/EP (SURVEY §2.3 — absent). TPU-first design:
experts live as one stacked parameter ``(E, ...)`` so sharding the
leading expert dim across an ``"expert"`` mesh axis (``EP_RULES`` +
``parallel.shard_params``) makes GSPMD run each device's experts locally
and combine with one reduce — expert parallelism with zero bespoke
dispatch machinery.  Two dispatch modes share that contract:

- ``dispatch="dense"`` (default): every expert computes every token and
  the router's one-hot masks the combine.  Trades E x MLP FLOPs for
  perfect static shapes — no capacity factor, no token dropping, exact —
  the right call for modest E, and the parity oracle for the sparse path.
- ``dispatch="capacity"``: Switch-style capacity-factor gather/scatter.
  Each expert processes at most ``C = ceil(capacity_factor * T / E)``
  tokens: tokens gather into an ``(E, C, H)`` buffer by routing slot
  (static shapes, XLA-friendly), the expert MLP runs once per *assigned*
  token instead of once per (token, expert) pair, and a scatter-add
  combines.  Tokens past an expert's capacity are DROPPED — they output
  zero from this block and ride the caller's residual connection, the
  standard Switch overflow semantics (Fedus et al. 2021 sec 2.2).  With
  ``capacity_factor >= E`` no token can drop and the output equals the
  dense path's (``tests/distributed/test_moe_ep.py``).

Router: softmax gate, top-1 selection scaled by the gate probability
(Switch Transformer, Fedus et al. 2021), plus the standard load-balance
auxiliary loss ``E * mean(gate_prob) . mean(assignment)`` returned to
the caller (weight it into the training loss).  The router runs in fp32
end to end: the Dense is named ``router`` to pair with amp's
keep-fp32 policy (``amp.model.ROUTER_PATTERNS`` keeps its kernel fp32
under O1/O2) and computes with ``dtype=float32`` — expert assignment is
a discrete decision, so it never rides bf16 (the paper's "selective
precision").
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P


def ep_rules(axis: str = "expert"):
    """Sharding rules for ``MoEMlp`` params (leading expert dim)."""
    return (
        (r"experts_in$", P(axis, None, None)),
        (r"experts_bias_in$", P(axis, None)),
        (r"experts_out$", P(axis, None, None)),
        (r"experts_bias_out$", P(axis, None)),
    )


EP_RULES = ep_rules()


class MoEMlp(nn.Module):
    """Top-1-routed MLP: ``(B, S, H) -> ((B, S, H), aux_loss)``."""

    num_experts: int
    hidden_size: int
    intermediate_size: int
    kernel_init: Optional[Callable] = None  # default: normal(0.02)
    dispatch: str = "dense"                 # "dense" | "capacity"
    capacity_factor: float = 1.25           # capacity dispatch only

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        if self.dispatch not in ("dense", "capacity"):
            raise ValueError(
                f"MoEMlp dispatch must be 'dense' or 'capacity', got "
                f"{self.dispatch!r}")
        e, h, f = self.num_experts, self.hidden_size, self.intermediate_size
        init = self.kernel_init or nn.initializers.normal(0.02)
        w_in = self.param("experts_in", init, (e, h, f))
        b_in = self.param("experts_bias_in", nn.initializers.zeros, (e, f))
        w_out = self.param("experts_out", init, (e, f, h))
        b_out = self.param("experts_bias_out", nn.initializers.zeros, (e, h))

        # router strictly in fp32 (see module docstring): dtype=float32
        # forces fp32 operands even when x is bf16, precision=HIGHEST
        # keeps the TPU MXU from running the fp32 matmul with bf16
        # multiply passes, and the "router" name keeps the kernel itself
        # un-rounded under amp O1/O2
        gate_logits = nn.Dense(
            e, name="router", kernel_init=init, dtype=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)(x.astype(jnp.float32))
        gate = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        top1 = jnp.argmax(gate, axis=-1)                      # (B, S)
        one_hot = jax.nn.one_hot(top1, e, dtype=gate.dtype)   # (B, S, E)

        if self.dispatch == "capacity":
            out = self._capacity_path(x, w_in, b_in, w_out, b_out, gate,
                                      top1, one_hot)
        else:
            # Switch scaling: route weight = chosen expert's probability
            combine = (one_hot * gate).astype(x.dtype)        # (B, S, E)
            # dense expert compute, masked-combined; contracting over h/f
            # keeps the expert dim outermost so an expert-sharded
            # placement computes local experts only and reduces once
            y = jnp.einsum("bsh,ehf->bsef", x, w_in) + b_in[None, None]
            y = nn.gelu(y, approximate=False)
            y = jnp.einsum("bsef,efh->bseh", y, w_out) + b_out[None, None]
            out = jnp.einsum("bseh,bse->bsh", y, combine)

        # load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e
        frac_tokens = jnp.mean(one_hot, axis=(0, 1))          # f_e
        frac_prob = jnp.mean(gate, axis=(0, 1))               # P_e
        aux = e * jnp.sum(frac_tokens * frac_prob)
        return out, aux.astype(jnp.float32)

    def _capacity_path(self, x, w_in, b_in, w_out, b_out, gate, top1,
                       one_hot):
        """Capacity-factor gather/scatter dispatch (module docstring)."""
        e = self.num_experts
        b, s, h = x.shape
        t = b * s
        cap = max(1, int(math.ceil(self.capacity_factor * t / e)))

        xf = x.reshape(t, h)
        top1_f = top1.reshape(t)
        # chosen expert's probability per token (Switch combine weight)
        gate_top = jnp.sum(one_hot * gate, axis=-1).reshape(t)
        oh = one_hot.reshape(t, e)
        # position of each token within its expert's arrival order
        # (exclusive cumsum over the token dim)
        cum = jnp.cumsum(oh, axis=0) - oh
        pos = cum[jnp.arange(t), top1_f].astype(jnp.int32)
        keep = pos < cap
        # routing slot = expert * cap + position; overflow -> dummy slot
        slot = jnp.where(keep, top1_f.astype(jnp.int32) * cap + pos,
                         e * cap)

        # invert token->slot into slot->token (kept slots are unique;
        # dropped tokens all land on the dummy and are discarded with it)
        token_for_slot = jnp.full((e * cap + 1,), t, jnp.int32)
        token_for_slot = token_for_slot.at[slot].set(
            jnp.arange(t, dtype=jnp.int32))
        tok = token_for_slot[:e * cap]                        # (E*C,)

        # gather: empty slots read the appended zero row
        xg = jnp.concatenate([xf, jnp.zeros((1, h), xf.dtype)])[tok]
        xe = xg.reshape(e, cap, h)
        y = jnp.einsum("ech,ehf->ecf", xe, w_in) + b_in[:, None]
        y = nn.gelu(y, approximate=False)
        y = jnp.einsum("ecf,efh->ech", y, w_out) + b_out[:, None]

        # combine: scale each slot by its token's gate prob (0 for empty
        # slots via the appended zero) and scatter-add back; dropped
        # tokens' rows stay zero — they ride the caller's residual.
        # Scatter in y's dtype (params may be wider than x, e.g. during
        # amp init) and cast once at the end.
        gate_slot = jnp.concatenate(
            [gate_top, jnp.zeros((1,), gate_top.dtype)])[tok]
        yf = y.reshape(e * cap, h) * gate_slot[:, None].astype(y.dtype)
        out = jnp.zeros((t + 1, h), yf.dtype).at[tok].add(yf)
        return out[:t].reshape(b, s, h).astype(x.dtype)
