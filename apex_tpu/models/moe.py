"""Mixture-of-experts MLP with expert parallelism over a mesh axis.

The reference has no MoE/EP (SURVEY §2.3 — absent). TPU-first design:
experts live as one stacked parameter ``(E, ...)`` and the block
computes a dense einsum over the expert dimension with a top-1 (Switch)
router — so sharding the leading expert dim across an ``"expert"`` mesh
axis (``EP_RULES`` + ``parallel.shard_params``) makes GSPMD run each
device's experts locally and combine with one reduce — expert
parallelism with zero dispatch machinery.  Dense compute (every expert
sees every token, results masked by the router's one-hot) trades E x
MLP FLOPs for perfect static shapes: no capacity factor, no token
dropping, no sort/scatter — the right call for modest expert counts on
the MXU, and exact (the usual capacity-overflow nondeterminism never
appears).  A capacity-based sparse dispatch is an optimization of this
same contract, not a different API.

Router: softmax gate, top-1 selection scaled by the gate probability
(Switch Transformer, Fedus et al. 2021), plus the standard load-balance
auxiliary loss ``E * mean(gate_prob) . mean(assignment)`` returned to
the caller (weight it into the training loss).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P


def ep_rules(axis: str = "expert"):
    """Sharding rules for ``MoEMlp`` params (leading expert dim)."""
    return (
        (r"experts_in$", P(axis, None, None)),
        (r"experts_bias_in$", P(axis, None)),
        (r"experts_out$", P(axis, None, None)),
        (r"experts_bias_out$", P(axis, None)),
    )


EP_RULES = ep_rules()


class MoEMlp(nn.Module):
    """Top-1-routed MLP: ``(B, S, H) -> ((B, S, H), aux_loss)``."""

    num_experts: int
    hidden_size: int
    intermediate_size: int
    kernel_init: Optional[Callable] = None  # default: normal(0.02)

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        e, h, f = self.num_experts, self.hidden_size, self.intermediate_size
        init = self.kernel_init or nn.initializers.normal(0.02)
        w_in = self.param("experts_in", init, (e, h, f))
        b_in = self.param("experts_bias_in", nn.initializers.zeros, (e, f))
        w_out = self.param("experts_out", init, (e, f, h))
        b_out = self.param("experts_bias_out", nn.initializers.zeros, (e, h))

        # router in fp32 (precision decides expert assignment)
        gate_logits = nn.Dense(e, name="router",
                               kernel_init=init)(x.astype(jnp.float32))
        gate = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        top1 = jnp.argmax(gate, axis=-1)                      # (B, S)
        one_hot = jax.nn.one_hot(top1, e, dtype=gate.dtype)   # (B, S, E)
        # Switch scaling: route weight = the chosen expert's probability
        combine = (one_hot * gate).astype(x.dtype)            # (B, S, E)

        # dense expert compute, masked-combined; contracting over h/f
        # keeps the expert dim outermost so an expert-sharded placement
        # computes local experts only and reduces once
        y = jnp.einsum("bsh,ehf->bsef", x, w_in) + b_in[None, None]
        y = nn.gelu(y, approximate=False)
        y = jnp.einsum("bsef,efh->bseh", y, w_out) + b_out[None, None]
        out = jnp.einsum("bseh,bse->bsh", y, combine)

        # load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e
        frac_tokens = jnp.mean(one_hot, axis=(0, 1))          # f_e
        frac_prob = jnp.mean(gate, axis=(0, 1))               # P_e
        aux = e * jnp.sum(frac_tokens * frac_prob)
        return out, aux.astype(jnp.float32)
