"""apex_tpu.models — the benchmark model zoo.

The reference library ships no models (its examples pull torchvision);
this package provides the models its headline workloads train — ResNet for
``examples/imagenet`` (amp O2 + DDP + SyncBN), the MNIST MLP for
``examples/simple``, DCGAN for the multi-model/multi-optimizer exercise,
and a BERT encoder for the FusedLAMB + FusedLayerNorm config — all NHWC /
static-shape / bf16-friendly for TPU.
"""

from apex_tpu.models.mlp import MLP
from apex_tpu.models.resnet import (
    BasicBlock,
    Bottleneck,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from apex_tpu.models.dcgan import Discriminator, Generator
from apex_tpu.models.gpt import (
    GPTConfig,
    GPTLMHeadModel,
    PipelinedGPT,
    gpt_medium,
    gpt_small,
    lm_loss,
)
from apex_tpu.models.moe import EP_RULES, MoEMlp, ep_rules
from apex_tpu.models.bert import (
    BertConfig,
    BertEncoder,
    BertForPreTraining,
    PipelinedBert,
    bert_base,
    bert_large,
)

__all__ = [
    "BasicBlock",
    "EP_RULES",
    "GPTConfig",
    "GPTLMHeadModel",
    "PipelinedGPT",
    "gpt_medium",
    "gpt_small",
    "lm_loss",
    "MoEMlp",
    "ep_rules",
    "BertConfig",
    "BertEncoder",
    "BertForPreTraining",
    "PipelinedBert",
    "Bottleneck",
    "Discriminator",
    "Generator",
    "MLP",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "bert_base",
    "bert_large",
]
