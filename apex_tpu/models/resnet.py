"""ResNet family (v1.5) — the framework's flagship benchmark model.

The reference library has no model zoo; its headline workload is torchvision
ResNet-50 driven by ``examples/imagenet/main_amp.py`` (reference :141-148)
under amp + DDP + SyncBN. This is that workload's model, built TPU-first:

- NHWC layout (TPU conv native), channels-last BatchNorm;
- the norm layer is a *factory attribute*, so
  ``parallel.convert_syncbn_model`` can swap ``nn.BatchNorm`` for
  ``SyncBatchNorm`` from outside (the flax analog of the reference's
  recursive module surgery, ``apex/parallel/__init__.py:21-53``);
- v1.5 stride placement (stride on the 3x3, not the 1x1 — torchvision's
  layout, which the reference's example trains);
- all shapes static, compiles to MXU-tiled convs under jit; amp handles
  bf16 casting with BN kept fp32 (pattern match on "BatchNorm").

Matches torchvision structurally: 7x7 stem, maxpool, 4 stages, global avg
pool, fc — so checkpoints map 1:1 modulo NCHW->NHWC transposition.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any

# torch BN defaults: momentum 0.1 (flax: 0.9), eps 1e-5
default_norm = functools.partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5)

conv_init = nn.initializers.variance_scaling(2.0, "fan_out",
                                             "truncated_normal")


class BasicBlock(nn.Module):
    """2-conv residual block (resnet18/34)."""

    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding=1,
                    use_bias=False, kernel_init=conv_init)(x)
        y = self.norm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=1, use_bias=False,
                    kernel_init=conv_init)(y)
        # zero-init the last BN scale (torchvision zero_init_residual
        # improves early training; harmless either way)
        y = self.norm(use_running_average=not train,
                      scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, kernel_init=conv_init,
                               name="downsample_conv")(residual)
            residual = self.norm(use_running_average=not train,
                                 name="downsample_bn")(residual)
        return nn.relu(residual + y)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 block with 4x expansion (resnet50/101/152),
    v1.5: stride lives on the 3x3."""

    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    kernel_init=conv_init)(x)
        y = self.norm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding=1,
                    use_bias=False, kernel_init=conv_init)(y)
        y = self.norm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    kernel_init=conv_init)(y)
        y = self.norm(use_running_average=not train,
                      scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, kernel_init=conv_init,
                               name="downsample_conv")(residual)
            residual = self.norm(use_running_average=not train,
                                 name="downsample_bn")(residual)
        return nn.relu(residual + y)


def space_to_depth(x, block: int = 2):
    """NHWC (B, H, W, C) -> (B, H/b, W/b, b*b*C); channel order
    (dh, dw, c) — the layout :func:`stem_to_s2d` rearranges the stem
    kernel into. Method-call ops only, so it runs on numpy arrays
    (host-side input pipeline) and jax arrays alike."""
    b_, h, w, c = x.shape
    x = x.reshape(b_, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b_, h // block, w // block, block * block * c)


def s2d_input_transform(x):
    """Host/outside-jit half of the ``stem="s2d_pre"`` split: NHWC
    (B, H, W, C) image batch -> (B, (H+6)/2, (W+6)/2, 4C) space-to-depth
    layout the pre-transformed stem consumes.

    ``stem="s2d"`` runs this pad+reshape+transpose INSIDE the train step,
    where it costs real HBM round-trips every iteration (~0.5 ms at
    b256/224px on v5e, xprof-measured: the 163 MB reshape + transpose
    copy show up as data-formatting ops, BENCH_NOTES.md). The transform
    is a pure layout change of the input, so it belongs with the input
    pipeline (``data.loaders`` applies it on host when asked, like the
    MLPerf TPU ResNet input pipelines do); inside the step the stem
    reduces to one dense VALID conv.

    Works on numpy or jnp arrays (pure reshape/transpose ops).
    """
    import numpy as np
    pad = np.pad if isinstance(x, np.ndarray) else jnp.pad
    return space_to_depth(pad(x, ((0, 0), (4, 2), (4, 2), (0, 0))), 2)


def stem_to_s2d(kernel):
    """Rearrange a standard (7, 7, C, F) stride-2 stem kernel into the
    EXACTLY equivalent (4, 4, 4C, F) stride-1 kernel over
    space-to-depth input (``ResNet(stem="s2d")``); used by the torch
    checkpoint converter when the target model runs the s2d stem.

    Derivation: zero-pad the kernel to 8x8 at the top-left so window
    starts align to even offsets, then fold each 2x2 spatial sub-block
    into the channel dim in ``space_to_depth``'s (dh, dw, c) order.
    """
    k7, _, c, f = kernel.shape
    assert kernel.shape[:2] == (7, 7), kernel.shape
    k8 = jnp.zeros((8, 8, c, f), kernel.dtype).at[1:, 1:].set(kernel)
    # (8, 8, C, F) -> (4, dh, 4, dw, C, F) -> (4, 4, dh, dw, C, F)
    k8 = k8.reshape(4, 2, 4, 2, c, f)
    return jnp.transpose(k8, (0, 2, 1, 3, 4, 5)).reshape(4, 4, 4 * c, f)


class ResNet(nn.Module):
    """Input NHWC, output (B, num_classes) logits.

    ``stem``: ``"conv"`` is the standard torchvision 7x7/stride-2 stem;
    ``"s2d"`` computes the SAME function via a space-to-depth transform
    + 4x4/stride-1 conv — the MLPerf ResNet TPU optimization: a
    (4, 4, 12, W) kernel tiles the MXU far better than (7, 7, 3, W)
    with its 3-deep contraction. ``"s2d_pre"`` is the same stem with
    the transform hoisted OUT of the step: the model consumes input
    already in :func:`s2d_input_transform` layout (the input pipeline's
    job — ``data.loaders`` does it host-side), so per-step HBM traffic
    for the pad/reshape/transpose disappears. Exact equivalence (same
    math, same ``stem_conv_s2d`` weights, related to the 7x7 kernel by
    :func:`stem_to_s2d`) is pinned in ``tests/L0/test_models.py``.
    """

    stage_sizes: Sequence[int]
    block: ModuleDef
    num_classes: int = 1000
    width: int = 64
    norm: ModuleDef = default_norm
    stem: str = "conv"

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.stem in ("s2d", "s2d_pre"):
            if self.stem == "s2d":
                # pad left 4 (the folded kernel's top-left zero pad +
                # the conv's padding 3), right 2 (the conv's right
                # padding that the last window reaches): h+6 stays even
                # and the VALID conv yields exactly h/2 outputs — no
                # slicing
                h, w = x.shape[1], x.shape[2]
                if h % 2 or w % 2:
                    raise ValueError(
                        f"stem='s2d' needs even spatial dims; got {(h, w)}")
                x = s2d_input_transform(x)
            # s2d_pre: input arrives already transformed (the input
            # pipeline ran s2d_input_transform on host)
            x = nn.Conv(self.width, (4, 4), (1, 1), padding="VALID",
                        use_bias=False, kernel_init=conv_init,
                        name="stem_conv_s2d")(x)
        elif self.stem == "conv":
            x = nn.Conv(self.width, (7, 7), (2, 2), padding=3,
                        use_bias=False, kernel_init=conv_init,
                        name="stem_conv")(x)
        else:
            raise ValueError(f"stem must be 'conv', 's2d' or 's2d_pre', "
                             f"got {self.stem!r}")
        x = self.norm(use_running_average=not train, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(filters=self.width * 2 ** i, norm=self.norm,
                               strides=strides)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        # classifier in fp32: the matmul is tiny and logits feed a softmax
        x = nn.Dense(self.num_classes, name="fc")(x.astype(jnp.float32))
        return x


def _resnet(stages, block):
    def build(num_classes: int = 1000, norm: ModuleDef = default_norm,
              width: int = 64, stem: str = "conv") -> ResNet:
        return ResNet(stage_sizes=stages, block=block,
                      num_classes=num_classes, norm=norm, width=width,
                      stem=stem)
    return build


ResNet18 = _resnet([2, 2, 2, 2], BasicBlock)
ResNet34 = _resnet([3, 4, 6, 3], BasicBlock)
ResNet50 = _resnet([3, 4, 6, 3], Bottleneck)
ResNet101 = _resnet([3, 4, 23, 3], Bottleneck)
ResNet152 = _resnet([3, 8, 36, 3], Bottleneck)
