"""Per-iteration amp protocol: scale_loss / disable_casts.

Port of reference ``apex/amp/handle.py``. The reference's ``scale_loss``
context manager does three jobs: scale the loss on entry, and on exit
unscale grads + update the scale + maybe patch ``optimizer.step`` into a
one-shot skip (``handle.py:16-150``). Under functional autodiff the
gradients don't exist inside the context, so the protocol splits cleanly:

- ``scale_loss`` (here) = the entry half: yields ``loss * current_scale``
  for use inside the loss function passed to ``jax.grad``;
- the exit half (unscale, update_scale, skip-step) lives in
  ``AmpOptimizer.step`` — see ``apex_tpu/amp/optimizer.py``.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from apex_tpu.amp import _amp_state
from apex_tpu.amp.optimizer import AmpOptimizerState
from apex_tpu.amp.scaler import LossScalerState


def _resolve_scaler_state(state, loss_id: int) -> LossScalerState:
    if isinstance(state, LossScalerState):
        return state
    if isinstance(state, AmpOptimizerState):
        return state.loss_scalers[loss_id]
    if hasattr(state, "loss_scalers"):
        return state.loss_scalers[loss_id]
    raise TypeError(
        "scale_loss needs a LossScalerState or AmpOptimizerState (pass the "
        f"optimizer *state*, not the optimizer object); got {type(state)}")


@contextlib.contextmanager
def scale_loss(loss, state, loss_id: int = 0):
    """``with amp.scale_loss(loss, opt_state) as scaled_loss:``

    Yields ``loss.float() * loss_scale`` (reference ``handle.py:116``).
    Use inside the function being differentiated; return the scaled loss
    from it so gradients arrive scaled, then ``AmpOptimizer.step`` unscales.

    Unlike the reference, ``state`` is the *optimizer state pytree* (or a
    bare ``LossScalerState``), not the optimizer object — inside a jitted
    step the scale must be a traced value, not a captured constant.
    """
    if _amp_state._amp_state.opt_properties is not None and not \
            _amp_state._amp_state.opt_properties.enabled:
        yield loss
        return
    sstate = _resolve_scaler_state(state, loss_id)
    yield jnp.asarray(loss, jnp.float32) * sstate.loss_scale


def scale(loss, state, loss_id: int = 0):
    """Function form of :func:`scale_loss` for non-context-manager use."""
    with scale_loss(loss, state, loss_id) as s:
        return s


@contextlib.contextmanager
def disable_casts():
    """Trace-time escape hatch: code under this context runs without amp
    input/param casting (reference ``handle.py:160``)."""
    old = _amp_state._amp_state.casts_disabled
    _amp_state._amp_state.casts_disabled = True
    try:
        yield
    finally:
        _amp_state._amp_state.casts_disabled = old
