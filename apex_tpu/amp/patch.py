"""O1 per-op precision enforcement: trace-time namespace patching.

The reference enforces its op lists by monkey-patching every whitelisted
function on ``torch`` / ``torch.Tensor`` / ``torch.nn.functional``
(``apex/amp/amp.py:90-148``, ``wrap.py:10-29``): a user calling
``softmax`` gets fp32 no matter what their model code says.  JAX has the
same honest analog available because *tracing is Python execution*: a
wrapper installed on ``jax.nn.softmax`` runs at trace time, and the casts
it inserts become part of the jaxpr that XLA compiles.  No graph
rewriting, no interceptors — the same design as the reference, one layer
up.

What is patched (from ``apex_tpu.amp.lists``):

- ``FP32_OPS``  — softmax family, losses, pointwise transcendentals,
  reductions: half-precision float args are upcast to fp32 before the
  call (reference ``FP32_FUNCS``);
- ``FP16_OPS``  — user-facing matmul entry points (``jnp.matmul`` etc.):
  fp32 args are cast to the half compute dtype (reference
  ``FP16_FUNCS``).  Library matmuls (flax Dense/Conv) are already half
  via AmpModel's module-boundary casting, so only direct calls need it;
- ``PROMOTE_OPS`` need no patch: jax's type promotion already computes
  ``bf16 op f32`` in f32 (the reference needed ``CASTS`` because torch
  *errors* on mixed dtypes).

The wrappers are installed once (``amp.initialize`` with an O1-style
``cast_ops`` property) and stay inert unless the *currently active*
properties enable op casting and ``disable_casts`` is not in effect —
mirroring the reference's handle-is-active check (``handle.py:20-40``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

# bind the singleton instance straight from the submodule: the package
# __init__ rebinds its `_amp_state` attribute to this same instance, so
# attribute-style module imports are ambiguous here
from apex_tpu.amp._amp_state import _amp_state as _STATE
from apex_tpu.amp.lists import BANNED_OPS, FP16_OPS, FP32_OPS, check_banned

_HALF_DTYPES = (jnp.float16, jnp.bfloat16)

# (module, attribute) -> original function, for every installed patch
_originals = {}


def _props():
    return _STATE.opt_properties


def _active() -> bool:
    p = _props()
    return (p is not None and bool(p.enabled) and bool(p.cast_ops)
            and not _STATE.casts_disabled)


def _half_dtype():
    p = _props()
    cmt = getattr(p, "cast_model_type", None) if p is not None else None
    return cmt if cmt not in (None, False) else jnp.bfloat16


def _is_float_array(x) -> bool:
    return hasattr(x, "dtype") and hasattr(x, "ndim") and \
        jnp.issubdtype(x.dtype, jnp.floating)


def _cast_args(args, kwargs, cast: Callable):
    from apex_tpu.amp.model import applier
    args = tuple(applier(a, cast) for a in args)
    kwargs = {k: applier(v, cast) for k, v in kwargs.items()}
    return args, kwargs


def _maybe_float(x):
    if _is_float_array(x) and x.dtype in _HALF_DTYPES:
        return x.astype(jnp.float32)
    return x


def _maybe_half(x):
    if _is_float_array(x) and x.dtype == jnp.float32:
        return x.astype(_half_dtype())
    return x


def _wrap(fn: Callable, mode: str) -> Callable:
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _active():
            if mode == "banned":  # reference amp.py:164-171
                check_banned(fn.__name__)
            cast = _maybe_float if mode == "fp32" else _maybe_half
            args, kwargs = _cast_args(args, kwargs, cast)
        return fn(*args, **kwargs)

    wrapper.__amp_original__ = fn
    return wrapper


def _targets() -> List[Tuple[Any, str, str]]:
    """(module, attr, mode) for every function to patch.  Names follow the
    policy tables in ``lists.py``; jnp spellings differ from the torch
    names (arccos vs acos etc.)."""
    import jax.scipy.special as jsp

    fp32_jnp = (
        "exp", "expm1", "log", "log10", "log1p", "log2", "power",
        "cosh", "sinh", "tan", "arccos", "arcsin", "arctan",
        "cumsum", "cumprod", "mean", "sum", "prod", "std", "var",
    )
    fp32_nn = ("softmax", "log_softmax", "standardize")
    fp32_jsp = ("logsumexp", "erf", "erfc")
    half_jnp = ("matmul", "dot", "vdot", "inner", "tensordot", "einsum")

    out = []
    out += [(jnp, n, "fp32") for n in fp32_jnp if hasattr(jnp, n)]
    out += [(jax.nn, n, "fp32") for n in fp32_nn if hasattr(jax.nn, n)]
    out += [(jsp, n, "fp32") for n in fp32_jsp if hasattr(jsp, n)]
    out += [(jnp.linalg, "norm", "fp32")]
    out += [(jnp, n, "half") for n in half_jnp if hasattr(jnp, n)]
    # banned ops (BCE on probabilities — fp16-range-unsafe, reference
    # functional_overrides.py:67-77): no baked-in jax/optax namespace
    # ships one today (optax's sigmoid_binary_cross_entropy takes
    # LOGITS, which is the safe form), so this sweep arms the guard for
    # any namespace that grows one; user-code registration is enforced
    # through amp.functional._register / banned_function.
    for mod in (jnp, jax.nn):
        out += [(mod, n, "banned") for n in BANNED_OPS if hasattr(mod, n)]

    try:
        import optax
        fp32_optax = (
            "softmax_cross_entropy",
            "softmax_cross_entropy_with_integer_labels",
            "sigmoid_binary_cross_entropy", "l2_loss", "huber_loss",
            "kl_divergence", "log_cosh",
        )
        for mod in (optax, getattr(optax, "losses", None)):
            if mod is None:
                continue
            out += [(mod, n, "fp32") for n in fp32_optax
                    if hasattr(mod, n)]
    except Exception:  # pragma: no cover
        pass

    # sanity: every patched name must be covered by the policy tables
    known = FP32_OPS | FP16_OPS | BANNED_OPS | {
        "arccos", "arcsin", "arctan", "standardize", "power", "vdot",
        "inner", "tensordot", "l2_loss", "huber_loss", "kl_divergence",
        "log_cosh",
    }
    assert all(n in known for _, n, _m in out), \
        [n for _, n, _m in out if n not in known]
    return out


def install_o1_patches() -> None:
    """Install the op-policy wrappers (idempotent).  Called by
    ``amp.initialize`` when the chosen opt level enables op casting; the
    wrappers check the active amp state at trace time, so installation is
    permanent and cheap (reference installs at ``amp.init``, ``amp.py:68``)."""
    for mod, name, mode in _targets():
        key = (id(mod), name)
        if key in _originals:
            continue
        fn = getattr(mod, name)
        if hasattr(fn, "__amp_original__"):
            continue
        _originals[key] = (mod, name, fn)
        setattr(mod, name, _wrap(fn, mode))


def remove_o1_patches() -> None:
    """Restore every patched function (used by tests)."""
    for mod, name, fn in list(_originals.values()):
        setattr(mod, name, fn)
    _originals.clear()
