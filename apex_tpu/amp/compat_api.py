"""Legacy amp API shims: ``amp.init`` handles and ``OptimWrapper``.

The reference keeps two generations of API alive: the old handle-based one
(``amp.init()`` -> ``AmpHandle``/``NoOpHandle`` with ``wrap_optimizer`` and
per-handle ``scale_loss``, ``apex/amp/amp.py:68``, ``handle.py:166-277``,
``opt.py:9``) and the new ``amp.initialize`` front end. These shims keep
old call sites working against the functional core; new code should use
``amp.initialize`` + ``AmpOptimizer``.

(The reference's ``compat.py``/``rnn_compat.py`` torch-version shims have
no TPU meaning — there is no pre-1.0 ``torch._VF`` here to paper over —
so the API ends with the handle generation.)
"""

from __future__ import annotations

import contextlib
import warnings


from apex_tpu.amp._amp_state import _amp_state as _amp_state_singleton
from apex_tpu.amp import handle as _handle
from apex_tpu.amp.optimizer import AmpOptimizer
from apex_tpu.amp.properties import Properties
from apex_tpu.amp.scaler import LossScaler


class AmpHandle:
    """Legacy handle (reference ``handle.py:166``): owns a default dynamic
    scaler config and wraps optimizers on request."""

    def __init__(self, loss_scale="dynamic", enable_caching: bool = True,
                 verbose: bool = False):
        self._enabled = True
        self._loss_scale = loss_scale
        self._verbose = verbose

    @property
    def is_active(self):
        return self._enabled

    @property
    def has_cache(self):
        # weight-cast caching is jit memoization here; report True for
        # API compatibility
        return True

    def wrap_optimizer(self, optimizer, num_loss: int = 1) -> AmpOptimizer:
        """Reference ``OptimWrapper`` construction (``opt.py:9``): returns
        the loss-scale-aware optimizer wrapper."""
        scaler = (LossScaler("dynamic") if self._loss_scale == "dynamic"
                  else LossScaler(float(self._loss_scale)))
        return AmpOptimizer(optimizer, scaler, num_losses=num_loss)

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer_state, loss_id: int = 0):
        with _handle.scale_loss(loss, optimizer_state, loss_id) as s:
            yield s

    def _deactivate(self):
        self._enabled = False


class NoOpHandle:
    """Disabled-amp handle (reference ``handle.py:250``)."""

    is_active = False
    has_cache = False

    def wrap_optimizer(self, optimizer, num_loss: int = 1) -> AmpOptimizer:
        return AmpOptimizer(optimizer, LossScaler(1.0), num_losses=num_loss)

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer_state, loss_id: int = 0):
        yield loss

    def _deactivate(self):
        pass


def init(enabled: bool = True, loss_scale="dynamic",
         enable_caching: bool = True, verbose: bool = False, **kwargs):
    """Legacy entry point (reference ``amp.py:68``). Prefer
    ``amp.initialize``."""
    warnings.warn(
        "amp.init is the legacy handle API; prefer amp.initialize "
        "(opt_level presets).", DeprecationWarning, stacklevel=2)
    if not enabled:
        return NoOpHandle()
    props = Properties()
    props.enabled = True
    props.opt_level = "O1"
    props.cast_ops = True
    props.loss_scale = loss_scale
    _amp_state_singleton.opt_properties = props
    return AmpHandle(loss_scale, enable_caching, verbose)


# alias matching the reference's class name for old imports
OptimWrapper = AmpOptimizer
