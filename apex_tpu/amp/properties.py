"""amp option bag and O0-O3 optimization-level presets.

Port of the validated ``Properties`` object and preset classes from the
reference ``apex/amp/frontend.py:6-190``. The option semantics map to TPU
as follows:

- ``cast_model_type``: the "half" dtype. On TPU the default half type is
  ``bfloat16`` (MXU-native, no loss scaling strictly required); ``float16``
  is honored if the user asks for it.
- ``patch_torch_functions`` (O1's torch-namespace monkey-patching) has no
  honest analog in traced JAX; the equivalent knob here is ``cast_ops``:
  compute runs in half via cast-at-apply while canonical params stay fp32,
  with norm-layer params excluded by a module-path policy
  (see ``apex_tpu/amp/model.py``). The attribute name is kept as an alias
  so reference-style ``properties.patch_torch_functions`` reads work.
- ``keep_batchnorm_fp32``, ``master_weights``, ``loss_scale``: same meaning
  as the reference.
"""

from __future__ import annotations

import warnings

import jax.numpy as jnp


class AmpOptimizationError(ValueError):
    pass


_OPTIONS = (
    "enabled",
    "opt_level",
    "cast_model_type",
    "cast_ops",
    "keep_batchnorm_fp32",
    "master_weights",
    "loss_scale",
)


class Properties:
    """Mutable, validated option bag (reference ``frontend.py:6-96``).

    Options start unset (None) and are filled by an opt-level preset, then
    optionally overridden one-by-one by ``amp.initialize`` kwargs —
    overrides after the preset print a warning, matching the reference's
    "Processing user overrides" flow (``frontend.py:334-347``).
    """

    def __init__(self):
        self.options = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,
            "cast_ops": None,
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
        }

    def _update_options_dict(self, new_options):
        for k, v in new_options.items():
            if k in self.options:
                self.options[k] = v
            else:
                raise AmpOptimizationError(f"Tried to set unexpected option {k}")

    def __getattr__(self, name):
        if "options" in self.__dict__ and name in self.__dict__["options"]:
            return self.options[name]
        if name == "patch_torch_functions":  # reference-name alias
            return self.options["cast_ops"]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if "options" not in self.__dict__:
            super().__setattr__(name, value)
            return
        if name == "patch_torch_functions":
            name = "cast_ops"
        if name not in self.options:
            super().__setattr__(name, value)
            return
        # validated setters (reference frontend.py:50-96)
        if name == "cast_model_type":
            if self.opt_level == "O1" and value is not None:
                if value is not False and value != jnp.float32:
                    warnings.warn(
                        "O1 inserts casts around ops, not the model weights "
                        "themselves, so with O1 cast_model_type is normally "
                        "left None.")
            value = _canonical_dtype(value)
        elif name == "keep_batchnorm_fp32":
            if isinstance(value, str):
                if value not in ("True", "False"):
                    raise AmpOptimizationError(
                        f"keep_batchnorm_fp32 string must be 'True' or "
                        f"'False'; got {value!r}")
                value = value == "True"
        elif name == "loss_scale":
            if value != "dynamic" and value is not None:
                value = float(value)
        self.options[name] = value

    def __repr__(self):
        return "\n".join(f"{k:24}: {v}" for k, v in self.options.items())


def _canonical_dtype(value):
    """Accept torch-style strings/dtypes and map to jnp dtypes."""
    if value is None or value is False:
        return value
    if isinstance(value, str):
        value = {
            "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
            "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
            "float32": jnp.float32, "fp32": jnp.float32, "float": jnp.float32,
        }.get(value.lower(), value)
        if isinstance(value, str):
            raise AmpOptimizationError(f"Unrecognized dtype string {value!r}")
    return value


# TPU's native half type. The reference hardcodes torch.float16; on TPU the
# MXU computes natively in bf16 and fp16 has no hardware advantage.
HALF = jnp.bfloat16
FLOAT = jnp.float32


class O3:
    """Pure half. "Speed of light" baseline (reference ``frontend.py:101``)."""

    brief = "O3: Pure half-precision (speed-of-light baseline)."
    more = ("Calls .astype(half) on the whole model and input data; no "
            "master weights; static loss scale 1.0. On TPU half defaults to "
            "bfloat16, so this is usually numerically fine, unlike fp16 O3 "
            "on GPU. Try keep_batchnorm_fp32=True for stats stability.")

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = HALF
        properties.cast_ops = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O2:
    """Half model + fp32 masters + dynamic scale (reference ``frontend.py:123``)."""

    brief = "O2: Insert casts at the model boundary; fp32 master weights."
    more = ("Model params and inputs run in half except batchnorm; the "
            "canonical optimizer-side params are fp32 masters; dynamic loss "
            "scaling by default.")

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = HALF
        properties.cast_ops = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        return properties


class O1:
    """Op-policy mixed precision + dynamic scale (reference ``frontend.py:146``)."""

    brief = "O1: Insert casts around MXU-bound ops (op-level policy)."
    more = ("Canonical params stay fp32; compute is cast to half per the "
            "module policy (norm layers and reductions in fp32). The TPU "
            "re-design of the reference's torch-namespace patching.")

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.cast_ops = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O0:
    """Pure fp32 baseline (reference ``frontend.py:168``)."""

    brief = "O0: Pure fp32 training."
    more = "Everything fp32; a useful accuracy baseline."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.cast_ops = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


opt_levels = {"O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}
