"""apex_tpu.amp — automatic mixed precision for TPU.

Public surface mirrors the reference ``apex/amp`` (``frontend.py``,
``handle.py``, ``scaler.py``): ``initialize`` with O0-O3 optimization
levels, the ``scale_loss`` protocol, and master-weight management — built on
a functional core (state pytrees, branch-free scale updates) so the whole
train step compiles under ``jax.jit``.
"""

from apex_tpu.amp.scaler import LossScaler, LossScalerState

__all__ = ["LossScaler", "LossScalerState"]
