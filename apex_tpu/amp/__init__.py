"""apex_tpu.amp — automatic mixed precision for TPU.

Public surface mirrors the reference ``apex/amp``: ``initialize`` with
O0-O3 optimization levels, the ``scale_loss`` protocol, precision
decorators, and master-weight management — built on a functional core
(state pytrees, branch-free scale updates) so the whole train step
compiles under ``jax.jit``.

Canonical usage::

    model, optimizer = amp.initialize(model, optax.sgd(1e-3), opt_level="O2")
    params = model.init(rng, x)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply(p, batch["x"])
            loss = cross_entropy(logits, batch["y"])
            with amp.scale_loss(loss, opt_state) as scaled_loss:
                return scaled_loss, loss
        (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, opt_state = optimizer.step(params, grads, opt_state)
        return params, opt_state, loss
"""

from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.amp.properties import Properties, opt_levels, AmpOptimizationError
from apex_tpu.amp.model import AmpModel, applier, cast_tree
from apex_tpu.amp.optimizer import AmpOptimizer, AmpOptimizerState
from apex_tpu.amp.frontend import initialize
from apex_tpu.amp.handle import scale_loss, scale, disable_casts
from apex_tpu.amp.functional import (
    banned_function,
    half_function,
    float_function,
    promote_function,
    master_params,
    register_float_function,
    register_half_function,
    register_promote_function,
)
from apex_tpu.amp._amp_state import _amp_state, maybe_print
from apex_tpu.amp import lists
from apex_tpu.amp.patch import install_o1_patches, remove_o1_patches
from apex_tpu.amp.compat_api import AmpHandle, NoOpHandle, OptimWrapper, init

__all__ = [
    "AmpHandle",
    "NoOpHandle",
    "OptimWrapper",
    "init",
    "lists",
    "AmpModel",
    "AmpOptimizer",
    "AmpOptimizerState",
    "AmpOptimizationError",
    "LossScaler",
    "LossScalerState",
    "Properties",
    "applier",
    "cast_tree",
    "disable_casts",
    "float_function",
    "half_function",
    "initialize",
    "master_params",
    "maybe_print",
    "opt_levels",
    "promote_function",
    "register_float_function",
    "register_half_function",
    "register_promote_function",
    "scale",
    "scale_loss",
]
