"""amp.initialize — the mixed-precision entry point.

Port of reference ``apex/amp/frontend.py:194-396``: validates the opt_level,
applies its preset Properties, applies user overrides (with the reference's
"Processing user overrides" prints), and wraps the model(s)/optimizer(s).

Differences from the reference, by TPU design:

- models are flax modules (or apply_fn callables); optimizers are optax
  ``GradientTransformation``s or apex_tpu fused optimizers. The returned
  ``AmpModel``/``AmpOptimizer`` are *stateless wrappers* — params and
  optimizer state are created by ``model.init`` / ``optimizer.init`` and
  threaded through the user's (jit-compiled) train step.
- ``patch_torch_functions`` is accepted as an alias for ``cast_ops``.
- default half dtype is bfloat16 (override with
  ``cast_model_type=jnp.float16``).
"""

from __future__ import annotations

from typing import Optional, Sequence

from apex_tpu.amp import _amp_state
from apex_tpu.amp._amp_state import maybe_print
from apex_tpu.amp.model import AmpModel
from apex_tpu.amp.optimizer import AmpOptimizer
from apex_tpu.amp.properties import Properties, opt_levels
from apex_tpu.amp.scaler import LossScaler


def initialize(
    models,
    optimizers=None,
    enabled: bool = True,
    opt_level: str = "O1",
    cast_model_type=None,
    cast_ops: Optional[bool] = None,
    patch_torch_functions: Optional[bool] = None,
    keep_batchnorm_fp32=None,
    master_weights: Optional[bool] = None,
    loss_scale=None,
    min_loss_scale: Optional[float] = None,
    max_loss_scale: float = 2.0 ** 24,
    num_losses: int = 1,
    verbosity: int = 1,
    keep_fp32_patterns: Optional[Sequence[str]] = None,
):
    """Initialize models and optimizers for mixed-precision training.

    Returns the same shape as its inputs: ``(model,)``-like single values if
    singles were passed, lists if lists were passed; ``(models, optimizers)``
    pair when optimizers is not None, else just models — matching the
    reference's return-shape restoration (``_initialize.py:253-268``).
    """
    _amp_state._amp_state.verbosity = verbosity

    if not enabled:
        properties = Properties()
        properties.enabled = False
        _amp_state._amp_state.opt_properties = properties
        if optimizers is None:
            return _wrap_disabled_models(models, properties)
        return (_wrap_disabled_models(models, properties),
                _wrap_optimizers(optimizers, properties, num_losses,
                                 min_loss_scale, max_loss_scale))

    if opt_level not in opt_levels:
        raise RuntimeError(
            f"Unexpected optimization level {opt_level}. Options are 'O0', "
            "'O1', 'O2', 'O3'. Note the prefix is the capital letter O, "
            "not the number zero.")

    properties = opt_levels[opt_level](Properties())
    maybe_print(f"Selected optimization level {opt_level}", True)
    maybe_print(f"Defaults for this optimization level are:", True)
    for k, v in properties.options.items():
        maybe_print(f"{k:24} : {v}", True)

    if patch_torch_functions is not None and cast_ops is None:
        cast_ops = patch_torch_functions
    overrides = dict(cast_model_type=cast_model_type, cast_ops=cast_ops,
                     keep_batchnorm_fp32=keep_batchnorm_fp32,
                     master_weights=master_weights, loss_scale=loss_scale)
    explicit = {k: v for k, v in overrides.items() if v is not None}
    if explicit:
        maybe_print("Processing user overrides (additional kwargs that are "
                    "not None)...", True)
        for k, v in explicit.items():
            setattr(properties, k, v)
    maybe_print("After processing overrides, optimization options are:", True)
    for k, v in properties.options.items():
        maybe_print(f"{k:24} : {v}", True)

    _amp_state._amp_state.opt_properties = properties

    if properties.enabled and properties.cast_ops:
        # O1: enforce the per-op precision policy by patching the traced
        # namespaces (reference amp.init, apex/amp/amp.py:68-171)
        from apex_tpu.amp.patch import install_o1_patches
        install_o1_patches()

    single_model = not isinstance(models, list)
    model_list = [models] if single_model else models
    wrapped_models = [AmpModel(m, properties, keep_fp32_patterns)
                      for m in model_list]
    models_out = wrapped_models[0] if single_model else wrapped_models

    if optimizers is None:
        return models_out

    optimizers_out = _wrap_optimizers(optimizers, properties, num_losses,
                                      min_loss_scale, max_loss_scale)
    return models_out, optimizers_out


def _make_scaler(properties, min_loss_scale, max_loss_scale) -> LossScaler:
    ls = properties.loss_scale
    kwargs = dict(min_loss_scale=min_loss_scale,
                  max_loss_scale=max_loss_scale)
    if ls == "dynamic":
        return LossScaler("dynamic", **kwargs)
    return LossScaler(float(ls) if ls is not None else 1.0, **kwargs)


def _wrap_optimizers(optimizers, properties, num_losses, min_loss_scale,
                     max_loss_scale):
    single = not isinstance(optimizers, list)
    opt_list = [optimizers] if single else optimizers
    scaler = _make_scaler(properties, min_loss_scale, max_loss_scale)
    wrapped = [AmpOptimizer(o, scaler, num_losses=num_losses)
               for o in opt_list]
    return wrapped[0] if single else wrapped


def _wrap_disabled_models(models, properties):
    single = not isinstance(models, list)
    model_list = [models] if single else models
    wrapped = [AmpModel(m, properties) for m in model_list]
    return wrapped[0] if single else wrapped
