"""The precision-policy data: which ops run half, fp32, or promote.

Port of the reference's op lists (``apex/amp/lists/functional_overrides.py``,
``torch_overrides.py``, ``tensor_overrides.py``) — the data that drives O1.
The reference applies these by monkey-patching the ``torch``/``Tensor``/
``F`` namespaces (``amp.py:90-148``); under JAX, traced functions cannot be
patched after the fact, so the policy is applied structurally:

- *module-boundary casting*: ``AmpModel`` casts params/inputs to the half
  dtype and keeps norm-layer params fp32 (``FP32_MODULE_PATTERNS`` below
  feeds ``model.NORM_PATTERNS``);
- *fp32-by-construction*: ops in ``FP32_OPS`` are ones XLA should see in
  fp32 — model code upcasts before softmax/losses/norm math. apex_tpu's
  own layers (FusedLayerNorm, SyncBatchNorm, attention, model zoo heads)
  already do this; the table is the normative list for user models;
- *user extension*: ``half_function``/``float_function``/
  ``promote_function`` decorators (``functional.py``) wrap arbitrary user
  functions with the same semantics as registering them into the
  reference's lists (``amp.py:46-64``).

``policy_for(op_name)`` answers "what would apex O1 do for this op".
"""

from __future__ import annotations

# MXU-bound ops: run in the half dtype (reference FP16_FUNCS,
# torch_overrides.py:84-104 — conv*/linear/matmul/BLAS family).
FP16_OPS = frozenset({
    "conv", "conv_general_dilated", "conv_transpose", "dense", "linear",
    "matmul", "dot", "dot_general", "einsum", "bmm", "mm", "mv",
    "addmm", "addbmm", "baddbmm", "conv1d", "conv2d", "conv3d",
    "conv_transpose1d", "conv_transpose2d", "conv_transpose3d",
    "prelu", "rnn_matmul",
})

# Numerically-sensitive ops: run in fp32 (reference FP32_FUNCS,
# functional_overrides.py:29-65, torch_overrides.py:106-138 — losses,
# softmax family, norms, pointwise transcendentals, reductions).
FP32_OPS = frozenset({
    "softmax", "log_softmax", "softmin", "cross_entropy", "nll_loss",
    "l1_loss", "mse_loss", "smooth_l1_loss", "kl_div",
    "binary_cross_entropy_with_logits", "softmax_cross_entropy",
    "softmax_cross_entropy_with_integer_labels",
    "sigmoid_binary_cross_entropy", "cosine_embedding_loss",
    "layer_norm", "group_norm", "batch_norm", "instance_norm",
    "local_response_norm", "normalize", "rms_norm",
    "exp", "expm1", "log", "log10", "log1p", "log2", "pow", "erf",
    "erfc", "erfinv", "acos", "asin", "atan", "cosh", "sinh", "tan",
    "logsumexp", "cumprod", "cumsum", "dist", "mean", "norm", "prod",
    "std", "sum", "var", "renorm",
})

# Dtype-agreement ops: promote mixed inputs to the widest float dtype
# (reference CASTS, torch_overrides.py:152-173).
PROMOTE_OPS = frozenset({
    "add", "addcdiv", "addcmul", "atan2", "cross", "div", "mul",
    "bilinear", "dot_elementwise", "eq", "ge", "gt", "le", "lt", "ne",
    "equal", "sub", "where", "minimum", "maximum",
})

# Sequence ops promoting across a list of tensors (reference
# SEQUENCE_CASTS, torch_overrides.py:177-180).
SEQUENCE_PROMOTE_OPS = frozenset({"cat", "concatenate", "stack"})

# Banned under amp: fp16 output range makes them unsafe; the reference
# raises and points at the *_with_logits form
# (functional_overrides.py:67-77).
BANNED_OPS = frozenset({"binary_cross_entropy"})

# Module-name patterns whose params stay fp32 under O1/O2 policies;
# re-exported into model.NORM_PATTERNS / BATCHNORM_PATTERNS.
FP32_MODULE_PATTERNS = (
    r"BatchNorm", r"SyncBatchNorm", r"LayerNorm", r"GroupNorm", r"RMSNorm",
)


def policy_for(op_name: str) -> str:
    """Return the O1 policy for ``op_name``: one of 'half', 'fp32',
    'promote', 'sequence_promote', 'banned', or 'passthrough'."""
    name = op_name.lower().rsplit(".", 1)[-1]
    if name in BANNED_OPS:
        return "banned"
    if name in FP16_OPS:
        return "half"
    if name in FP32_OPS:
        return "fp32"
    if name in PROMOTE_OPS:
        return "promote"
    if name in SEQUENCE_PROMOTE_OPS:
        return "sequence_promote"
    return "passthrough"


def banned_message(op_name: str) -> str:
    """The single source of the banned-op remediation text (shared by
    :func:`check_banned` and ``amp.banned_function``)."""
    return (
        f"amp does not work out-of-the-box with `{op_name}` — the fp16 "
        "range makes it unsafe. Use the *_with_logits / "
        "sigmoid_binary_cross_entropy form instead, or wrap the call "
        "site in apex_tpu.amp.disable_casts to compute it outside "
        "amp's policy.")


def check_banned(op_name: str) -> None:
    """Raise (like the reference's banned-function wrapper,
    ``amp.py:164-171``) if ``op_name`` must not be used under amp."""
    if policy_for(op_name) == "banned":
        raise RuntimeError(banned_message(op_name))
