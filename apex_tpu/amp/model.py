"""Model-side casting machinery: the TPU re-design of apex's model surgery.

The reference casts models in two ways: O2/O3 call ``model.to(half)`` /
``convert_network`` (BN-safe) and monkey-patch ``model.forward`` to cast
inputs (``apex/amp/_initialize.py:183-208``); O1 monkey-patches torch
namespaces per an op whitelist (``apex/amp/amp.py:68-171``).

Here a model is a flax module (or bare apply_fn) over an immutable variable
pytree, so "casting the model" becomes a pure function of the variables at
apply time:

- the canonical (optimizer-side) variables stay fp32 for O0/O1/O2 — these
  ARE the master weights; O3 stores half canonically (no masters);
- ``AmpModel.apply`` casts params and float inputs to the compute layout for
  the chosen opt level before calling the wrapped module;
- parameters belonging to normalization layers are kept fp32 per a
  module-path policy (the equivalent of ``convert_network`` skipping
  ``_BatchNorm`` children, reference ``fp16_utils/fp16util.py:60-69``).

Because the cast sits inside the traced/jitted step, XLA fuses it into the
consuming matmuls; autodiff through the cast routes gradients back to the
fp32 canonical params — which is exactly the reference's master-gradient
flow (``_process_optimizer.py:13-75``) with zero bookkeeping.

The per-call weight-cast cache of the reference (``amp/utils.py:87-119``)
is unnecessary: within one traced step each cast is computed once by CSE;
across steps params change anyway.
"""

from __future__ import annotations

import contextlib
import re
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.amp import _amp_state
from apex_tpu.amp.properties import Properties
from apex_tpu.utils.paths import path_components

Pytree = Any

# Module-path components whose params stay fp32 under cast policies.
# BatchNorm matches the reference's keep_batchnorm_fp32; the other norm
# layers are kept fp32 under O1's op policy (the reference's FP32_FUNCS
# includes layer_norm/group_norm — functional_overrides.py:29-65).
# Patterns are matched against individual path components; the short names
# are anchored so e.g. "subnet"/"normal_init" don't accidentally match.
BATCHNORM_PATTERNS = (r"BatchNorm", r"SyncBatchNorm", r"^bn(_|\d|$)",
                      r"_bn$")
NORM_PATTERNS = BATCHNORM_PATTERNS + (r"LayerNorm", r"GroupNorm", r"RMSNorm",
                                      r"^norm(_|\d|$)", r"_norm$",
                                      r"^ln(_|\d|$)", r"_ln$")
# MoE router weights stay fp32 under the O1 and O2 policies too: top-1
# expert assignment is a DISCRETE function of the gate logits, so bf16
# rounding of the router kernel flips token->expert routing decisions
# (Switch Transformer keeps the router in fp32 — "selective precision",
# Fedus et al. 2021 sec 2.4).  models.MoEMlp names its gate Dense
# "router" to pair with this.
ROUTER_PATTERNS = (r"^router$",)


def _path_matches(path, patterns) -> bool:
    names = path_components(path)
    return any(re.search(pat, name) for pat in patterns for name in names)


def _module_matches(module, patterns) -> bool:
    """Does a flax module instance look like a kept-fp32 norm layer?
    Checked against both the class name (BatchNorm, SyncBatchNorm, ...)
    and the instance name (stem_bn, downsample_bn, ...) so it agrees with
    the param-path policy in ``_path_matches``."""
    names = [type(module).__name__]
    inst = getattr(module, "name", None)
    if inst:
        names.append(str(inst))
    return any(re.search(pat, name) for pat in patterns for name in names)


def cast_tree(tree: Pytree, dtype, *, except_patterns: Sequence[str] = ()):
    """Cast float leaves of ``tree`` to ``dtype``; leaves on paths matching
    ``except_patterns`` and all non-float leaves pass through unchanged."""

    def one(path, x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        if except_patterns and _path_matches(path, except_patterns):
            return x
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(one, tree)


def applier(value, cast_fn: Callable):
    """Recursively apply ``cast_fn`` to arrays inside nested containers.

    Port of the reference's ``applier`` (``_initialize.py:36-58``): dives
    into dict/list/tuple (incl. namedtuple) containers, applies ``cast_fn``
    to float arrays, passes everything else through (strings, ints, None,
    non-float arrays such as integer label tensors).
    """
    if isinstance(value, (jax.Array,)) or hasattr(value, "dtype"):
        arr = jnp.asarray(value)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            return cast_fn(arr)
        return value
    if isinstance(value, dict):
        return {k: applier(v, cast_fn) for k, v in value.items()}
    if isinstance(value, tuple) and hasattr(value, "_fields"):  # namedtuple
        return type(value)(*(applier(v, cast_fn) for v in value))
    if isinstance(value, (list, tuple)):
        return type(value)(applier(v, cast_fn) for v in value)
    return value


class AmpModel:
    """Casting wrapper around a flax module (or bare apply_fn).

    Returned by ``amp.initialize``; exposes ``init``/``apply`` with the same
    signatures as the wrapped flax module, inserting the opt-level's dtype
    policy. ``unwrapped`` gives back the original module.
    """

    def __init__(self, module, properties: Properties,
                 keep_fp32_patterns: Optional[Sequence[str]] = None):
        self.module = module
        self._properties = properties
        p = properties
        self.half_dtype = (p.cast_model_type
                           if p.cast_model_type not in (None, False)
                           else jnp.bfloat16)
        if keep_fp32_patterns is not None:
            self.keep_fp32_patterns = tuple(keep_fp32_patterns)
        elif p.cast_ops:  # O1: norm layers + MoE routers stay fp32
            self.keep_fp32_patterns = NORM_PATTERNS + ROUTER_PATTERNS
        elif p.keep_batchnorm_fp32:  # O2 (and O3 w/ override)
            self.keep_fp32_patterns = BATCHNORM_PATTERNS + ROUTER_PATTERNS
        else:
            self.keep_fp32_patterns = ()

    # -- layout helpers ---------------------------------------------------
    @property
    def properties(self) -> Properties:
        return self._properties

    @property
    def unwrapped(self):
        return self.module

    def _compute_cast_needed(self) -> bool:
        p = self._properties
        return bool(p.enabled) and (
            p.cast_ops or p.cast_model_type not in (None, False))

    def canonical_variables(self, variables: Pytree) -> Pytree:
        """Cast freshly-initialized variables to the canonical (optimizer-
        side) layout: fp32 masters for O0/O1/O2, half for O3."""
        p = self._properties
        if not p.enabled:
            return variables
        if p.opt_level == "O3" or (
                p.cast_model_type not in (None, False) and not p.master_weights
                and p.opt_level != "O0"):
            return cast_tree(variables, self.half_dtype,
                             except_patterns=self.keep_fp32_patterns)
        return cast_tree(variables, jnp.float32)

    def compute_variables(self, variables: Pytree) -> Pytree:
        """Cast canonical variables to the compute layout for apply."""
        p = self._properties
        if not p.enabled or _amp_state._amp_state.casts_disabled:
            return variables
        if p.opt_level == "O0":
            return cast_tree(variables, jnp.float32)
        if self._compute_cast_needed():
            return cast_tree(variables, self.half_dtype,
                             except_patterns=self.keep_fp32_patterns)
        return variables

    def cast_inputs(self, args, kwargs):
        p = self._properties
        if not p.enabled or _amp_state._amp_state.casts_disabled:
            return args, kwargs
        if p.opt_level == "O0":
            cast = lambda x: x.astype(jnp.float32)
        elif self._compute_cast_needed():
            cast = lambda x: x.astype(self.half_dtype)
        else:
            return args, kwargs
        args = tuple(applier(a, cast) for a in args)
        kwargs = {k: applier(v, cast) for k, v in kwargs.items()}
        return args, kwargs

    def _norm_output_recast(self):
        """Context manager installing a flax method interceptor that casts
        kept-fp32 norm layers' *outputs* back to the half compute dtype.

        Without it, flax's dtype promotion silently drags everything
        downstream of a fp32 BatchNorm up to fp32 — including every conv —
        because ``bf16 x  op  f32 scale -> f32`` propagates.  The reference
        does not have this problem: torch's batch_norm with half input and
        fp32 weight emits *half* (``fp16_utils/fp16util.py:22-33`` keeps BN
        fp32 precisely because mixed-dtype BN works there).  The interceptor
        restores those semantics: statistics and affine params stay exactly
        fp32 (flax computes stats in fp32 internally regardless), only the
        returned activation is recast, so the convs stay on the MXU in
        bf16.  Perf-critical: without this, amp O2 ResNet runs its convs in
        fp32 and MFU collapses."""
        import flax.linen as nn

        half = self.half_dtype
        patterns = self.keep_fp32_patterns

        def recast(x):
            if hasattr(x, "dtype") and hasattr(x, "astype") and \
                    x.dtype == jnp.float32:
                return x.astype(half)
            return x

        def interceptor(next_fun, args, kwargs, context):
            out = next_fun(*args, **kwargs)
            # recast only modules that are kept fp32 AND look like norm
            # layers: a user-supplied keep_fp32_patterns entry (e.g. a
            # final classifier kept fp32 for logit accuracy) must keep
            # its fp32 output — the seam mend is for norms only
            if context.method_name == "__call__" and \
                    _module_matches(context.module, patterns) and \
                    _module_matches(context.module, NORM_PATTERNS):
                out = jax.tree.map(recast, out)
            return out

        return nn.intercept_methods(interceptor)

    def _apply_context(self):
        """Interceptor scope for ``apply``: active only when compute casting
        is on AND some params are deliberately kept fp32 (so there is a
        dtype seam to mend).  Installed regardless of whether the wrapped
        object is itself an ``nn.Module``: pipeline wrappers like
        ``models.PipelinedBert`` are plain classes whose INNER applies are
        flax modules, and ``nn.intercept_methods`` is a global trace-time
        context that reaches them; for bare apply_fns with no flax calls
        it is a no-op."""
        if (self._compute_cast_needed() and self.keep_fp32_patterns
                and not _amp_state._amp_state.casts_disabled):
            return self._norm_output_recast()
        return contextlib.nullcontext()

    # -- flax-like surface ------------------------------------------------
    def init(self, rngs, *args, **kwargs) -> Pytree:
        args, kwargs = self.cast_inputs(args, kwargs)
        variables = self.module.init(rngs, *args, **kwargs)
        return self.canonical_variables(variables)

    def apply(self, variables: Pytree, *args, **kwargs):
        variables = self.compute_variables(variables)
        args, kwargs = self.cast_inputs(args, kwargs)
        if hasattr(self.module, "apply"):
            with self._apply_context():
                return self.module.apply(variables, *args, **kwargs)
        return self.module(variables, *args, **kwargs)

    def __call__(self, variables: Pytree, *args, **kwargs):
        return self.apply(variables, *args, **kwargs)

    def loss_and_grad_1f1b(self, variables: Pytree, *args, **kwargs):
        """amp-composed passthrough to the wrapped model's 1F1B
        loss-and-grad (``models.PipelinedBert.loss_and_grad_1f1b``):
        params cast to the compute layout and the norm-seam interceptor
        active around the schedule's rematerialized applies, so grads
        come back in the half compute dtype — exactly how amp grads
        arrive on the autodiff path — for ``AmpOptimizer.step`` to
        unscale onto the fp32 masters."""
        if not hasattr(self.module, "loss_and_grad_1f1b"):
            raise AttributeError(
                f"{type(self.module).__name__} has no loss_and_grad_1f1b "
                "(only pipeline models with the 1F1B schedule do)")
        variables = self.compute_variables(variables)
        with self._apply_context():
            return self.module.loss_and_grad_1f1b(variables, *args,
                                                  **kwargs)
