"""User-facing precision decorators + master_params.

The reference lets users register their own functions into the O1 casting
machinery via ``amp.half_function`` / ``float_function`` /
``promote_function`` (``apex/amp/amp.py:30-64``). Here the decorators wrap
the function directly (no registry/monkey-patching): float array arguments
are cast on the way in, at trace time, honoring ``disable_casts``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.amp import _amp_state
from apex_tpu.amp.model import applier
from apex_tpu.amp.optimizer import AmpOptimizerState


def _amp_active() -> bool:
    """Active amp configuration and casts not disabled — THE predicate
    every decorator in this module gates on."""
    props = _amp_state._amp_state.opt_properties
    return (props is not None and bool(props.enabled)
            and not _amp_state._amp_state.casts_disabled)


def _active_half_dtype():
    if not _amp_active():
        return None
    props = _amp_state._amp_state.opt_properties
    if props.cast_model_type not in (None, False):
        return props.cast_model_type
    if props.cast_ops:
        return jnp.bfloat16
    return None


def _cast_args(args, kwargs, dtype):
    args = tuple(applier(a, lambda x: x.astype(dtype)) for a in args)
    kwargs = {k: applier(v, lambda x: x.astype(dtype))
              for k, v in kwargs.items()}
    return args, kwargs


def half_function(fn):
    """Run ``fn`` with float args cast to the active half dtype
    (reference ``amp.py:30``)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        dtype = _active_half_dtype()
        if dtype is not None:
            args, kwargs = _cast_args(args, kwargs, dtype)
        return fn(*args, **kwargs)
    return wrapper


def float_function(fn):
    """Run ``fn`` with float args cast to fp32 (reference ``amp.py:34``)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        props = _amp_state._amp_state.opt_properties
        if props is not None and props.enabled and not \
                _amp_state._amp_state.casts_disabled:
            args, kwargs = _cast_args(args, kwargs, jnp.float32)
        return fn(*args, **kwargs)
    return wrapper


def promote_function(fn):
    """Run ``fn`` with float args promoted to the widest float dtype among
    them (reference ``amp.py:38``; widest-type promotion ``wrap.py:65-90``)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        props = _amp_state._amp_state.opt_properties
        if props is None or not props.enabled or \
                _amp_state._amp_state.casts_disabled:
            return fn(*args, **kwargs)
        dtypes = []

        def collect(x):
            dtypes.append(x.dtype)
            return x

        applier(args, collect)
        applier(kwargs, collect)
        float_dtypes = [d for d in dtypes if jnp.issubdtype(d, jnp.floating)]
        if not float_dtypes:
            return fn(*args, **kwargs)
        widest = jnp.result_type(*float_dtypes)
        args, kwargs = _cast_args(args, kwargs, widest)
        return fn(*args, **kwargs)
    return wrapper


def banned_function(fn):
    """Wrap ``fn`` to raise under active amp (the reference's banned
    wrapper, ``amp.py:164-171``): decorating IS the ban declaration —
    the call errors whenever amp is active (``disable_casts`` is the
    escape hatch), whatever the function is named."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _amp_active():
            from apex_tpu.amp.lists import banned_message
            raise RuntimeError(banned_message(fn.__name__))
        return fn(*args, **kwargs)
    wrapper.__amp_original__ = fn
    return wrapper


def _register(module, fn_name: str, wrapper):
    from apex_tpu.amp import lists

    # the reference refuses banned fns no matter how they're registered
    # (functional_overrides.py:67-77): registering BCE-on-probabilities
    # for half casting would legitimize an fp16-unsafe op
    lists.check_banned(fn_name)
    fn = getattr(module, fn_name)
    setattr(module, fn_name, wrapper(fn))


def register_half_function(module, fn_name: str) -> None:
    """Patch ``module.fn_name`` to run with half-cast float args
    (reference ``amp.py:46-50``). Unlike the decorators, this mutates the
    module attribute — call before tracing (e.g. right after imports),
    matching the reference's requirement to register before
    ``amp.init``."""
    _register(module, fn_name, half_function)


def register_float_function(module, fn_name: str) -> None:
    """Patch ``module.fn_name`` to run in fp32 (reference ``amp.py:52``)."""
    _register(module, fn_name, float_function)


def register_promote_function(module, fn_name: str) -> None:
    """Patch ``module.fn_name`` to promote mixed float args (reference
    ``amp.py:58``)."""
    _register(module, fn_name, promote_function)


def master_params(params):
    """Iterate the fp32 master parameters (reference ``_amp_state.py:61``).

    Under apex_tpu's design the canonical params *are* the masters for
    O0-O2 (see ``apex_tpu/amp/model.py``), so this simply yields the leaves
    of the given params pytree. Pass the params, not the optimizer state —
    the optimizer state holds moments, not masters.
    """
    if isinstance(params, AmpOptimizerState):
        raise TypeError(
            "master_params takes the params pytree, not AmpOptimizerState "
            "(the state holds optimizer moments; the canonical params are "
            "the fp32 masters).")
    yield from jax.tree_util.tree_leaves(params)
