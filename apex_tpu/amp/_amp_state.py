"""Cross-module amp state + rank-0-aware printing.

Port of reference ``apex/amp/_amp_state.py``. The mutable global here only
holds *trace-time* configuration (verbosity, casts_disabled, the active
Properties) — all numeric state (loss scales, overflow flags) lives in
explicit state pytrees, unlike the reference where loss_scalers hang off
this singleton.
"""

from __future__ import annotations

import warnings


class AmpState:
    def __init__(self):
        self.hard_override = False
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        self.casts_disabled = False
        self.opt_properties = None


_amp_state = AmpState()


def warn_or_err(msg: str):
    """Reference ``_amp_state.py:28``: hard_override downgrades errors."""
    if _amp_state.hard_override:
        warnings.warn(msg)
    else:
        raise RuntimeError(
            msg + "  If you're sure you know what you're doing, supply "
            "hard_override=True to amp.initialize.")


def _is_rank0() -> bool:
    try:
        import jax
        return jax.process_index() == 0
    except Exception:
        return True


def maybe_print(msg: str, rank0: bool = False):
    """Verbosity-gated print, optionally only on process 0 (reference
    ``_amp_state.py:43-52``, WORLD_SIZE detection replaced by
    ``jax.process_index``)."""
    if _amp_state.verbosity > 0:
        if not rank0 or _is_rank0():
            print(msg)
