"""AmpOptimizer — loss-scale-aware optimizer wrapping with skip-step.

The TPU re-design of the reference's optimizer surgery
(``apex/amp/_process_optimizer.py``): where the reference monkey-patches
``optimizer.step``/``zero_grad`` and stashes master params inside
``_amp_stash``, here the optimizer is an immutable wrapper around any
optax ``GradientTransformation`` and all bookkeeping is explicit state:

- canonical params given to ``step`` are already the fp32 masters (see
  ``apex_tpu/amp/model.py``), so the fp16<->fp32 group-splitting machinery
  (``_process_optimizer.py:13-75``) is unnecessary;
- the overflow -> skip-step protocol (reference ``handle.py:130-150``
  patches ``step`` to a one-shot no-op) becomes a branch-free
  ``jnp.where`` select between updated and stale params/optimizer state,
  fully inside jit;
- per-loss scalers (``num_losses``/``loss_id``, reference
  ``_initialize.py:232-236``) are a tuple of scaler states.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, LossScalerState

Pytree = Any


class AmpOptimizerState(NamedTuple):
    inner: Any                                   # wrapped optimizer's state
    loss_scalers: Tuple[LossScalerState, ...]    # one per loss
    applied_steps: jax.Array                     # i32, steps actually taken
    skipped_steps: jax.Array                     # i32, overflow-skipped steps


def _tree_select(pred, on_true, on_false):
    return jax.tree_util.tree_map(
        lambda t, f: jnp.where(pred, t, f), on_true, on_false)


class AmpOptimizer:
    """Wraps an optax-style optimizer with unscale/overflow/skip logic.

    ``inner`` needs ``init(params) -> state`` and
    ``update(grads, state, params) -> (updates, state)`` (the optax
    GradientTransformation protocol; apex_tpu fused optimizers satisfy it).
    """

    def __init__(self, inner, loss_scaler: LossScaler, num_losses: int = 1):
        self.inner = inner
        self.loss_scaler = loss_scaler
        self.num_losses = int(num_losses)

    def with_zero(self, mesh, axis: str = "data",
                  min_shard_elems: Optional[int] = None) -> "AmpOptimizer":
        """ZeRO-1 pairing passthrough: reconfigure the wrapped optimizer's
        fused path to run shard-local over ``axis`` (see
        ``FusedAdam.with_zero`` / ``parallel.shard_optimizer_state``)."""
        if not hasattr(self.inner, "with_zero"):
            return self  # per-leaf optimizers partition shard-local as-is
        return AmpOptimizer(self.inner.with_zero(mesh, axis,
                                                 min_shard_elems),
                            self.loss_scaler, self.num_losses)

    # -- state ------------------------------------------------------------
    def init(self, params: Pytree) -> AmpOptimizerState:
        return AmpOptimizerState(
            inner=self.inner.init(params),
            loss_scalers=tuple(self.loss_scaler.init()
                               for _ in range(self.num_losses)),
            applied_steps=jnp.asarray(0, jnp.int32),
            skipped_steps=jnp.asarray(0, jnp.int32),
        )

    # -- granular protocol (multi-loss / grad accumulation) ---------------
    def unscale_grads(self, grads: Pytree, state: AmpOptimizerState,
                      loss_id: int = 0, *, stashed: Optional[Pytree] = None,
                      update_scale: bool = True):
        """Unscale one loss's grads; returns (grads, overflow, new_state).

        With ``stashed`` accumulates into previously-unscaled grads
        (reference ``scaler.py:149-180``).  ``update_scale=False`` defers
        the dynamic-scale update — the grad-accumulation protocol: the
        reference updates the scale ONCE per optimizer step from the
        overflow state accumulated across every microbatch's unscale
        (``scaler.py:184-210``), so intermediate microbatches pass False
        and the step ends with :meth:`update_scale` on the ORed flag.
        """
        sstate = state.loss_scalers[loss_id]
        if stashed is None:
            g, overflow = self.loss_scaler.unscale(
                grads, sstate, out_dtype=jnp.float32)
        else:
            g, overflow = self.loss_scaler.unscale_with_stashed(
                grads, stashed, sstate)
        if not update_scale:
            return g, overflow, state
        return g, overflow, self.update_scale(state, overflow, loss_id)

    def update_scale(self, state: AmpOptimizerState, overflow,
                     loss_id: int = 0) -> AmpOptimizerState:
        """One dynamic-scale update from an (accumulated) overflow flag —
        the per-step half of the grad-accumulation protocol (see
        :meth:`unscale_grads`)."""
        new_sstate = self.loss_scaler.update(
            state.loss_scalers[loss_id], overflow)
        scalers = tuple(new_sstate if i == loss_id else s
                        for i, s in enumerate(state.loss_scalers))
        return state._replace(loss_scalers=scalers)

    def apply_gradients(self, params: Pytree, grads: Pytree,
                        state: AmpOptimizerState, overflow) -> Tuple[Pytree, AmpOptimizerState]:
        """Inner optimizer step with branch-free skip on overflow.

        Fused optimizers that accept ``skip`` (FusedAdam/FusedLAMB) run
        the select INSIDE their kernel: the wrapper-level tree-selects
        below re-read and re-write the full params + optimizer state
        (~0.9 GB/step at ResNet-50 scale, measured on v5e,
        BENCH_NOTES.md), and the update-diff protocol costs another
        subtract + apply round-trip on top."""
        keep = ~jnp.asarray(overflow)
        if getattr(self.inner, "supports_fused_skip", False):
            params_out, inner_out = self.inner.step(
                params, grads, state.inner, skip=overflow)
        else:
            import optax
            updates, new_inner = self.inner.update(grads, state.inner,
                                                   params)
            new_params = optax.apply_updates(params, updates)
            params_out = _tree_select(keep, new_params, params)
            inner_out = _tree_select(keep, new_inner, state.inner)
        return params_out, state._replace(
            inner=inner_out,
            applied_steps=state.applied_steps + keep.astype(jnp.int32),
            skipped_steps=state.skipped_steps + (~keep).astype(jnp.int32),
        )

    # -- fused one-call step ---------------------------------------------
    def step(self, params: Pytree, grads: Pytree, state: AmpOptimizerState,
             loss_id: int = 0) -> Tuple[Pytree, AmpOptimizerState]:
        """unscale -> scaler update -> inner step with skip; one call.

        Equivalent of the reference per-iteration protocol: exit of
        ``scale_loss`` (unscale + ``update_scale``) followed by the patched
        ``optimizer.step`` (``handle.py:116-150``,
        ``_process_optimizer.py:287-294``).
        """
        g, overflow, state = self.unscale_grads(grads, state, loss_id)
        return self.apply_gradients(params, g, state, overflow)

    # -- introspection ----------------------------------------------------
    def loss_scale(self, state: AmpOptimizerState, loss_id: int = 0):
        return state.loss_scalers[loss_id].loss_scale
