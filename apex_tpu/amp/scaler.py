"""Dynamic loss scaling as a carried state pytree.

Re-design of the reference ``apex/amp/scaler.py`` (``LossScaler`` at :34).
Semantics preserved exactly:

- dynamic scale starts at 2**16, halves on overflow, doubles after 2000
  consecutive overflow-free steps, capped at 2**24
  (reference ``scaler.py:39-45,190-210``);
- ``unscale`` multiplies grads by ``1/scale`` and reports overflow
  (``scaler.py:95-116``);
- ``unscale_with_stashed`` accumulates ``stashed + grads/scale`` where only
  the incoming grads can trip the overflow flag (``scaler.py:149-180``).

Re-designed for XLA: the scaler state is an immutable NamedTuple carried
through the jitted train step, and ``update`` is branch-free ``jnp.where``
arithmetic. The reference's one mandatory device->host sync per step
(``_overflow_buf.item()`` at ``scaler.py:193``) disappears: overflow is a
traced boolean consumed by ``lax``-select skip-step logic, so the entire
train step — including "skip this step" — stays on device.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_unscale,
    tree_any_nonfinite,
)

Pytree = Any


class LossScalerState(NamedTuple):
    """Carried scaler state. A valid leaf of any checkpointable pytree.

    (The reference never checkpointed amp scaler state under the new API —
    SURVEY.md section 5 flags this as a gap; here it is a plain pytree so it
    serializes with the rest of the train state.)
    """

    loss_scale: jax.Array   # f32 scalar, current scale
    unskipped: jax.Array    # i32 scalar, overflow-free steps since last change
    overflow: jax.Array     # bool scalar, did the *last* step overflow


class LossScaler:
    """Static hyperparameters + pure functions over :class:`LossScalerState`.

    ``loss_scale``: "dynamic" or a fixed float (the reference accepts the
    same two via ``amp.initialize(loss_scale=...)``, ``frontend.py:244-254``).
    """

    def __init__(
        self,
        loss_scale: Union[str, float, int] = "dynamic",
        init_scale: float = 2.0 ** 16,
        scale_factor: float = 2.0,
        scale_window: int = 2000,
        min_loss_scale: Optional[float] = None,
        max_loss_scale: float = 2.0 ** 24,
    ):
        if loss_scale == "dynamic":
            self.dynamic = True
            self._init_scale = float(init_scale)
        else:
            self.dynamic = False
            self._init_scale = float(loss_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = float(max_loss_scale)

    # -- state -----------------------------------------------------------
    def init(self) -> LossScalerState:
        return LossScalerState(
            loss_scale=jnp.asarray(self._init_scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            overflow=jnp.asarray(False),
        )

    # -- per-iteration protocol ------------------------------------------
    def scale_loss(self, loss: jax.Array, state: LossScalerState) -> jax.Array:
        """``loss.float() * scale`` (reference ``handle.py:116``)."""
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(self, grads: Pytree, state: LossScalerState, *, out_dtype=None):
        """Grads/scale + overflow flag (reference ``scaler.py:95-116``)."""
        return multi_tensor_unscale(grads, state.loss_scale, out_dtype=out_dtype)

    def unscale_with_stashed(self, grads: Pytree, stashed: Pytree,
                             state: LossScalerState):
        """``stashed + grads/scale``; only ``grads`` can trip the flag.

        Gradient-accumulation path (reference ``scaler.py:149-180`` using
        ``multi_tensor_axpby`` with ``arg_to_check`` = the incoming grads).
        """
        inv = 1.0 / state.loss_scale
        return multi_tensor_axpby(inv, grads, 1.0, stashed, arg_to_check=0)

    def check_overflow(self, grads: Pytree) -> jax.Array:
        """Standalone overflow probe (reference ``scaler.py:6-17``)."""
        return tree_any_nonfinite(grads)

    def update(self, state: LossScalerState, overflow: jax.Array) -> LossScalerState:
        """Post-step scale adjustment (reference ``scaler.py:190-210``).

        Branch-free: on overflow halve the scale (clamped to
        ``min_loss_scale``) and reset the window counter; otherwise count up
        and double the scale (clamped to ``max_loss_scale``) every
        ``scale_window`` clean steps.
        """
        overflow = jnp.asarray(overflow)
        if not self.dynamic:
            return state._replace(overflow=overflow)
        scale = state.loss_scale
        down = scale / self.scale_factor
        if self.min_loss_scale is not None:
            down = jnp.maximum(down, self.min_loss_scale)
        unskipped = jnp.where(overflow, 0, state.unskipped + 1)
        grow = unskipped >= self.scale_window
        up = jnp.minimum(scale * self.scale_factor, self.max_loss_scale)
        new_scale = jnp.where(overflow, down, jnp.where(grow, up, scale))
        unskipped = jnp.where(grow, 0, unskipped)
        return LossScalerState(loss_scale=new_scale, unskipped=unskipped,
                               overflow=overflow)

    # -- convenience -----------------------------------------------------
    def loss_scale(self, state: LossScalerState) -> jax.Array:
        return state.loss_scale

    # -- telemetry --------------------------------------------------------
    def observe(self, state: LossScalerState, registry, *,
                prefix: str = "amp") -> None:
        """Record the carried scaler state into a
        :class:`apex_tpu.observability.MetricsRegistry`: the
        loss-scale gauge (current/peak/running-mean over the calls =
        the scale trajectory), the clean-step window gauge, and the
        overflow-skip counter.

        Host-side — reading the traced scalars forces a device sync,
        so call it OUTSIDE jit at whatever cadence you log (every
        step for the full trajectory, every N for cheap telemetry).
        :class:`apex_tpu.resilience.TrainingSentry` does this per
        step when built with ``registry=``; this hook is for training
        loops that don't run under the sentry
        (``docs/observability.md``)."""
        registry.gauge(f"{prefix}_loss_scale").update(
            float(state.loss_scale))
        registry.gauge(f"{prefix}_unskipped_steps").update(
            int(state.unskipped))
        if bool(state.overflow):
            registry.counter(f"{prefix}_overflow_steps").incr()
