"""apex_tpu.normalization — fused normalization layers (Pallas-backed)."""

__all__ = []
