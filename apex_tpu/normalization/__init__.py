"""apex_tpu.normalization — fused normalization layers (Pallas-backed)."""

from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
)

__all__ = [
    "FusedLayerNorm",
    "fused_layer_norm",
    "fused_layer_norm_affine",
]
