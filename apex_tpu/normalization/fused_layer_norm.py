"""FusedLayerNorm — layer normalization with Pallas TPU kernels.

Re-design of reference ``apex/normalization/fused_layer_norm.py`` and its
CUDA kernels (``csrc/layer_norm_cuda_kernel.cu``): input viewed as
(n1, n2) with n2 = prod(normalized_shape); forward computes per-row
mean/invvar (Welford in the reference; masked two-pass sums here — same
fp32 statistics) and saves them for backward
(``cuApplyLayerNorm`` :280 returns (output, mean, invvar)); backward
computes grad_input in-kernel and reduces grad_gamma/grad_beta across rows
(``cuComputeGradInput`` :524, ``cuComputePartGradGammaBeta`` :405 — the
cross-row reduction is left to XLA here, which emits an efficient
column-sum).

The Pallas path runs rows per grid step with fp32 math whatever the input
dtype (matching the kernel's accumulation dtype); a pure-jnp path is the
CPU fallback and parity oracle, exactly like the reference's CPU fallback
(``fused_layer_norm.py:148-150``).
"""

from __future__ import annotations

import functools
import numbers
from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.pallas_utils import LANES, on_tpu, pallas_auto_gate

Shape = Union[int, Sequence[int]]


def _norm_shape(normalized_shape: Shape) -> Tuple[int, ...]:
    if isinstance(normalized_shape, numbers.Integral):
        return (int(normalized_shape),)
    return tuple(int(d) for d in normalized_shape)


# ---------------------------------------------------------------------------
# jnp reference path
# ---------------------------------------------------------------------------

def _ln_stats(x2: jax.Array, eps: float):
    mean = jnp.mean(x2, axis=-1)
    var = jnp.mean(jnp.square(x2), axis=-1) - jnp.square(mean)
    invvar = jax.lax.rsqrt(var + eps)
    return mean, invvar


def _ln_forward_jnp(x2: jax.Array, eps: float):
    mean, invvar = _ln_stats(x2, eps)
    y = (x2 - mean[:, None]) * invvar[:, None]
    return y, mean, invvar


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, y_ref, mean_ref, invvar_ref, *, n2: int,
                   eps: float):
    x = x_ref[:].astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    mask = cols < n2
    xm = jnp.where(mask, x, 0.0)
    mean = jnp.sum(xm, axis=1, keepdims=True) / n2
    d = jnp.where(mask, x - mean, 0.0)
    var = jnp.sum(d * d, axis=1, keepdims=True) / n2
    invvar = jax.lax.rsqrt(var + eps)
    y_ref[:] = (d * invvar).astype(y_ref.dtype)
    mean_ref[:] = jnp.broadcast_to(mean, mean_ref.shape)
    invvar_ref[:] = jnp.broadcast_to(invvar, invvar_ref.shape)


def _ln_bwd_kernel(dy_ref, xhat_ref, invvar_ref, dx_ref, *, n2: int):
    # dy here is already gamma-scaled (dy * gamma) by the caller
    dy = dy_ref[:].astype(jnp.float32)
    xhat = xhat_ref[:].astype(jnp.float32)
    invvar = invvar_ref[:, 0:1]
    cols = jax.lax.broadcasted_iota(jnp.int32, dy.shape, 1)
    mask = cols < n2
    dy = jnp.where(mask, dy, 0.0)
    xhat = jnp.where(mask, xhat, 0.0)
    sum1 = jnp.sum(dy, axis=1, keepdims=True)
    sum2 = jnp.sum(dy * xhat, axis=1, keepdims=True)
    dx = invvar * (dy - (sum1 + xhat * sum2) / n2)
    dx_ref[:] = jnp.where(mask, dx, 0.0).astype(dx_ref.dtype)


def _pad_cols(x2: jax.Array) -> Tuple[jax.Array, int]:
    n2 = x2.shape[1]
    n2p = max(LANES, ((n2 + LANES - 1) // LANES) * LANES)
    if n2p != n2:
        x2 = jnp.pad(x2, ((0, 0), (0, n2p - n2)))
    return x2, n2


def _row_block(n2p: int, itemsize: int = 4) -> int:
    # keep each VMEM operand block <= ~2 MiB
    rows = max(8, min(512, (2 * 1024 * 1024) // (n2p * itemsize)))
    return (rows // 8) * 8 or 8


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def _ln_fwd_pallas(x2: jax.Array, eps: float, interpret: bool):
    from jax.experimental import pallas as pl

    n1 = x2.shape[0]
    xp, n2 = _pad_cols(x2)
    rows = _row_block(xp.shape[1])
    n1p = ((n1 + rows - 1) // rows) * rows
    if n1p != n1:
        xp = jnp.pad(xp, ((0, n1p - n1), (0, 0)))
    grid = (n1p // rows,)
    row_spec = pl.BlockSpec((rows, xp.shape[1]), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((rows, LANES), lambda i: (i, 0))
    y, mean, invvar = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, n2=n2, eps=eps),
        grid=grid,
        in_specs=[row_spec],
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            # xhat stays fp32: it is the backward residual and feeds the
            # affine scale — rounding it to a half dtype here would inject
            # O(eps_bf16) error that the dweight row-sum amplifies (the
            # reference keeps fp32 stats for the same reason,
            # layer_norm_cuda_kernel.cu accumulation dtype)
            jax.ShapeDtypeStruct(xp.shape, jnp.float32),
            jax.ShapeDtypeStruct((n1p, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n1p, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(xp)
    return y[:n1, :n2], mean[:n1, 0], invvar[:n1, 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _ln_bwd_pallas(dy2: jax.Array, xhat2: jax.Array, invvar: jax.Array,
                   interpret: bool):
    from jax.experimental import pallas as pl

    n1 = dy2.shape[0]
    dyp, n2 = _pad_cols(dy2)
    xhp, _ = _pad_cols(xhat2)
    rows = _row_block(dyp.shape[1])
    n1p = ((n1 + rows - 1) // rows) * rows
    if n1p != n1:
        dyp = jnp.pad(dyp, ((0, n1p - n1), (0, 0)))
        xhp = jnp.pad(xhp, ((0, n1p - n1), (0, 0)))
    iv = jnp.pad(invvar, (0, n1p - n1))[:, None] * jnp.ones((1, LANES),
                                                            jnp.float32)
    grid = (n1p // rows,)
    row_spec = pl.BlockSpec((rows, dyp.shape[1]), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((rows, LANES), lambda i: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, n2=n2),
        grid=grid,
        in_specs=[row_spec, row_spec, stat_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct(dyp.shape, dy2.dtype),
        interpret=interpret,
    )(dyp, xhp, iv)
    return dx[:n1, :n2]


# ---------------------------------------------------------------------------
# custom-vjp functional API
# ---------------------------------------------------------------------------

def _use_pallas(flag: Optional[bool]) -> bool:
    # partial-manual shard_map regions (pipelined TP) auto-partition
    # every op — Mosaic calls are rejected there, jnp path instead
    return pallas_auto_gate(flag)


def _match_vma(cotangent, primal):
    """Reduce a cotangent over the mesh axes it varies on but its primal
    does not. Under shard_map, JAX's transpose rules automatically psum
    cotangents of replicated (invariant) inputs; a custom_vjp must do the
    same by hand or the vma check rejects the bwd output. No-op outside
    shard_map (both vma sets empty)."""
    try:
        extra = jax.typeof(cotangent).vma - jax.typeof(primal).vma
    except AttributeError:
        return cotangent
    if extra:
        cotangent = jax.lax.psum(cotangent, tuple(sorted(extra)))
    return cotangent


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_layer_norm_affine(x, weight, bias, normalized_shape,
                            eps: float = 1e-5,
                            use_pallas: Optional[bool] = None):
    """y = LN(x) * weight + bias over trailing ``normalized_shape`` dims
    (reference ``fused_layer_norm_affine``, ``fused_layer_norm.py:58``)."""
    out, _ = _fla_fwd(x, weight, bias, normalized_shape, eps, use_pallas)
    return out


def _fla_fwd(x, weight, bias, normalized_shape, eps, use_pallas):
    ns = _norm_shape(normalized_shape)
    n2 = int(np.prod(ns))
    lead = x.shape[:x.ndim - len(ns)]
    x2 = x.reshape(-1, n2)
    if _use_pallas(use_pallas):
        xhat2, mean, invvar = _ln_fwd_pallas(x2, eps, not on_tpu())
    else:
        x32 = x2.astype(jnp.float32)
        xhat2, mean, invvar = _ln_forward_jnp(x32, eps)
    w2 = weight.reshape(-1).astype(jnp.float32)
    b2 = bias.reshape(-1).astype(jnp.float32)
    y = (xhat2 * w2[None, :] + b2[None, :]).astype(x.dtype)
    out = y.reshape(lead + ns)
    return out, (xhat2, invvar, weight)


def _fla_bwd(normalized_shape, eps, use_pallas, res, dy):
    xhat2, invvar, weight = res
    in_dtype = dy.dtype  # output dtype == input dtype
    ns = _norm_shape(normalized_shape)
    n2 = int(np.prod(ns))
    dy2 = dy.reshape(-1, n2).astype(jnp.float32)
    w2 = weight.reshape(-1).astype(jnp.float32)
    dyw = dy2 * w2[None, :]
    if _use_pallas(use_pallas):
        dx2 = _ln_bwd_pallas(dyw, xhat2, invvar, not on_tpu())
    else:
        sum1 = jnp.sum(dyw, axis=1, keepdims=True)
        sum2 = jnp.sum(dyw * xhat2, axis=1, keepdims=True)
        dx2 = invvar[:, None] * (dyw - (sum1 + xhat2 * sum2) / n2)
    dweight = jnp.sum(dy2 * xhat2, axis=0).reshape(ns).astype(weight.dtype)
    dbias = jnp.sum(dy2, axis=0).reshape(ns).astype(weight.dtype)
    dweight = _match_vma(dweight, weight)
    dbias = _match_vma(dbias, weight)
    dx = dx2.astype(in_dtype).reshape(dy.shape)
    return dx, dweight, dbias


fused_layer_norm_affine.defvjp(
    lambda x, w, b, ns, eps, up: _fla_fwd(x, w, b, ns, eps, up),
    _fla_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def fused_layer_norm(x, normalized_shape, eps: float = 1e-5,
                     use_pallas: Optional[bool] = None):
    """Non-affine LN (reference ``fused_layer_norm``, :60)."""
    out, _ = _fl_fwd(x, normalized_shape, eps, use_pallas)
    return out


def _fl_fwd(x, normalized_shape, eps, use_pallas):
    ns = _norm_shape(normalized_shape)
    n2 = int(np.prod(ns))
    lead = x.shape[:x.ndim - len(ns)]
    x2 = x.reshape(-1, n2)
    if _use_pallas(use_pallas):
        xhat2, mean, invvar = _ln_fwd_pallas(x2, eps, not on_tpu())
    else:
        xhat2, mean, invvar = _ln_forward_jnp(x2.astype(jnp.float32), eps)
    return xhat2.astype(x.dtype).reshape(lead + ns), (xhat2, invvar)


def _fl_bwd(normalized_shape, eps, use_pallas, res, dy):
    xhat2, invvar = res
    in_dtype = dy.dtype  # output dtype == input dtype
    ns = _norm_shape(normalized_shape)
    n2 = int(np.prod(ns))
    dy2 = dy.reshape(-1, n2).astype(jnp.float32)
    if _use_pallas(use_pallas):
        dx2 = _ln_bwd_pallas(dy2, xhat2, invvar, not on_tpu())
    else:
        sum1 = jnp.sum(dy2, axis=1, keepdims=True)
        sum2 = jnp.sum(dy2 * xhat2, axis=1, keepdims=True)
        dx2 = invvar[:, None] * (dy2 - (sum1 + xhat2 * sum2) / n2)
    return (dx2.astype(in_dtype).reshape(dy.shape),)


fused_layer_norm.defvjp(
    lambda x, ns, eps, up: _fl_fwd(x, ns, eps, up), _fl_bwd)


# ---------------------------------------------------------------------------
# flax module
# ---------------------------------------------------------------------------

class FusedLayerNorm(nn.Module):
    """Module form (reference ``FusedLayerNorm``, ``fused_layer_norm.py:64``).

    ``normalized_shape`` may be an int or shape tuple; ``elementwise_affine``
    adds weight/bias params (named scale/bias for flax ecosystem interop).
    """

    normalized_shape: Any
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: Any = jnp.float32
    use_pallas: Optional[bool] = None

    @nn.compact
    def __call__(self, x):
        ns = _norm_shape(self.normalized_shape)
        if tuple(x.shape[-len(ns):]) != ns:
            raise ValueError(
                f"input trailing dims {x.shape[-len(ns):]} != "
                f"normalized_shape {ns}")
        if self.elementwise_affine:
            weight = self.param("scale", nn.initializers.ones, ns,
                                self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, ns,
                              self.param_dtype)
            return fused_layer_norm_affine(x, weight, bias, ns, self.eps,
                                           self.use_pallas)
        return fused_layer_norm(x, ns, self.eps, self.use_pallas)
