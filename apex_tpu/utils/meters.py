"""Training-loop meters (reference ``examples/imagenet/main_amp.py:445-460``)
plus the serving-side counters (``apex_tpu.serving``: tokens/s, queue
depth)."""

from __future__ import annotations

import time


class AverageMeter:
    """Tracks the latest value and the running (weighted) average."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)


class RateMeter:
    """Events per second over wall time — the serving tokens/s meter.

    ``update(n)`` adds n events; ``rate`` is total events / elapsed
    seconds since construction or :meth:`reset`.  A monotonic clock and
    a floor on elapsed keep it sane for sub-millisecond smoke runs."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self.reset()

    def reset(self):
        self.total = 0
        self._start = self._clock()

    def update(self, n: int = 1):
        self.total += n

    @property
    def elapsed(self) -> float:
        return max(self._clock() - self._start, 1e-9)

    @property
    def rate(self) -> float:
        return self.total / self.elapsed


class CounterMeter:
    """Monotonic named counters — the failure-accounting meter
    (checkpoints written / skipped-corrupt, IO retries, sentry
    rollbacks, serving requests failed by reason).

    ``incr(key)`` only ever counts up (negative increments are a bug in
    the caller and raise), so a snapshot taken later always dominates
    one taken earlier — the property log scrapers and the bench harness
    rely on when they diff two readings."""

    def __init__(self):
        self._counts = {}

    def incr(self, key: str, n: int = 1) -> int:
        if n < 0:
            raise ValueError(f"CounterMeter is monotonic; incr({key!r}, "
                             f"{n}) would decrease it")
        self._counts[key] = self._counts.get(key, 0) + n
        return self._counts[key]

    def count(self, key: str) -> int:
        return self._counts.get(key, 0)

    def __getitem__(self, key: str) -> int:
        return self.count(key)

    @property
    def total(self) -> int:
        return sum(self._counts.values())

    def ratio(self, num: str, *parts: str) -> float:
        """``count(num) / sum(count(p) for p in parts)`` with a 0.0
        empty-denominator convention — the hit-rate helper
        (``ratio("hits", "hits", "misses")``) for stats derived from
        counter pairs."""
        den = sum(self.count(p) for p in parts)
        return self.count(num) / den if den else 0.0

    def as_dict(self) -> dict:
        """Stable-ordered snapshot for logs/stats."""
        return {k: self._counts[k] for k in sorted(self._counts)}


class GaugeMeter:
    """Current / peak / running-mean of a sampled level — the serving
    queue-depth and running-batch-occupancy meter."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.peak = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val):
        val = float(val)
        self.val = val
        self.peak = max(self.peak, val)
        self.sum += val
        self.count += 1

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)
