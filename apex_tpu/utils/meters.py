"""Training-loop meters (reference ``examples/imagenet/main_amp.py:445-460``)."""

from __future__ import annotations


class AverageMeter:
    """Tracks the latest value and the running (weighted) average."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)
