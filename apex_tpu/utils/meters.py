"""Training-loop meters (reference ``examples/imagenet/main_amp.py:445-460``)
plus the serving-side counters (``apex_tpu.serving``: tokens/s, queue
depth).

Since the unified-telemetry layer (``apex_tpu.observability``,
``docs/observability.md``) the counter/gauge meters are VIEWS onto a
shared :class:`~apex_tpu.observability.MetricsRegistry` when
constructed with ``registry=``: the registry owns the values (so one
snapshot / Prometheus scrape covers every subsystem) and the meter
keeps its exact historical API — ``incr``/``count``/``as_dict``/
``ratio``, ``update``/``peak``/``avg`` — on top.  Without a registry
they behave standalone, byte-for-byte as before."""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from apex_tpu.observability.registry import Counter, Gauge


class AverageMeter:
    """Tracks the latest value and the running (weighted) average."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)


class RateMeter:
    """Events per second over wall time — the serving tokens/s meter.

    ``update(n)`` adds n events; ``rate`` is total events / elapsed
    seconds since construction or :meth:`reset` (the lifetime
    average), while :meth:`rate_over` is the rate over just the
    trailing window — what "tokens/s right now" should mean on a
    server that has been up for hours.  Recent events are kept in a
    pruned deque bounded by ``max_window`` seconds, so memory stays
    proportional to recent traffic, not uptime.  A monotonic clock and
    a floor on elapsed keep both sane for sub-millisecond smoke
    runs."""

    def __init__(self, clock=time.perf_counter, max_window: float = 120.0):
        if max_window <= 0:
            raise ValueError(f"max_window must be > 0, got {max_window}")
        self._clock = clock
        self.max_window = float(max_window)
        self.reset()

    def reset(self):
        self.total = 0
        self._start = self._clock()
        self._events = deque()      # (timestamp, n) within max_window

    def update(self, n: int = 1):
        self.total += n
        now = self._clock()
        self._events.append((now, n))
        self._prune(now)

    def _prune(self, now: float) -> None:
        cutoff = now - self.max_window
        ev = self._events
        while ev and ev[0][0] < cutoff:
            ev.popleft()

    @property
    def elapsed(self) -> float:
        return max(self._clock() - self._start, 1e-9)

    @property
    def rate(self) -> float:
        return self.total / self.elapsed

    def rate_over(self, last_n_seconds: float) -> float:
        """Events per second over the trailing ``last_n_seconds``
        (clamped to ``max_window``).  Early in the meter's life — when
        less than a window has elapsed — the denominator is the actual
        elapsed time, so the windowed rate converges to :attr:`rate`
        instead of under-reporting.

        Degenerate windows answer 0.0, never raise or explode: an
        empty window (no events yet, or everything aged out) has no
        rate, and a single sample with zero elapsed time (an update in
        the same clock instant as the read — every first scrape on an
        injected clock) must not divide ~0 into a huge number that a
        dashboard then renders as a traffic spike."""
        if last_n_seconds <= 0:
            raise ValueError(
                f"last_n_seconds must be > 0, got {last_n_seconds}")
        now = self._clock()
        window = min(float(last_n_seconds), self.max_window)
        self._prune(now)
        if not self._events:
            return 0.0
        cutoff = now - window
        n = sum(c for t, c in self._events if t >= cutoff)
        denom = min(window, now - self._start)
        if n == 0 or denom <= 0.0:
            return 0.0
        return n / denom


class CounterMeter:
    """Monotonic named counters — the failure-accounting meter
    (checkpoints written / skipped-corrupt, IO retries, sentry
    rollbacks, serving requests failed by reason).

    ``incr(key)`` only ever counts up (negative increments are a bug in
    the caller and raise), so a snapshot taken later always dominates
    one taken earlier — the property log scrapers and the bench harness
    rely on when they diff two readings.

    With ``registry=`` each key becomes a labeled
    :class:`~apex_tpu.observability.Counter`
    (``<name>{<label>="<key>"}``) owned by the registry; the meter is
    then a view — same API, shared storage."""

    def __init__(self, registry=None, *, name: str = "counters",
                 label: str = "key"):
        self._registry = registry
        self._name = name
        self._label = label
        self._counts = {}           # key -> observability Counter

    def _cell(self, key: str) -> Counter:
        c = self._counts.get(key)
        if c is None:
            if self._registry is not None:
                c = self._registry.counter(self._name,
                                           **{self._label: key})
            else:
                c = Counter(self._name, ((self._label, str(key)),))
            self._counts[key] = c
        return c

    def incr(self, key: str, n: int = 1) -> int:
        if n < 0:
            raise ValueError(f"CounterMeter is monotonic; incr({key!r}, "
                             f"{n}) would decrease it")
        return self._cell(key).incr(n)

    def count(self, key: str) -> int:
        c = self._counts.get(key)
        return c.value if c is not None else 0

    def __getitem__(self, key: str) -> int:
        return self.count(key)

    @property
    def total(self) -> int:
        return sum(c.value for c in self._counts.values())

    def ratio(self, num: str, *parts: str) -> float:
        """``count(num) / sum(count(p) for p in parts)`` with a 0.0
        empty-denominator convention — the hit-rate helper
        (``ratio("hits", "hits", "misses")``) for stats derived from
        counter pairs."""
        den = sum(self.count(p) for p in parts)
        return self.count(num) / den if den else 0.0

    def as_dict(self) -> dict:
        """Stable-ordered snapshot for logs/stats."""
        return {k: self._counts[k].value for k in sorted(self._counts)}


class GaugeMeter:
    """Current / peak / running-mean of a sampled level — the serving
    queue-depth and running-batch-occupancy meter.

    With ``registry=`` + ``name=`` the backing
    :class:`~apex_tpu.observability.Gauge` lives in the registry
    (snapshot/exposition see it); otherwise it is standalone.  Either
    way the meter API is unchanged."""

    def __init__(self, registry=None, *,
                 name: Optional[str] = None, **labels):
        if registry is not None:
            if name is None:
                raise ValueError("GaugeMeter(registry=...) needs name=")
            self._gauge = registry.gauge(name, **labels)
        else:
            self._gauge = Gauge(name or "gauge")

    def reset(self):
        self._gauge.reset()

    def update(self, val):
        self._gauge.update(val)

    @property
    def val(self) -> float:
        return self._gauge.val

    @property
    def peak(self) -> float:
        return self._gauge.peak

    @property
    def sum(self) -> float:
        return self._gauge.sum

    @property
    def count(self) -> int:
        return self._gauge.count

    @property
    def avg(self) -> float:
        return self._gauge.avg
