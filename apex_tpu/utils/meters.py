"""Training-loop meters (reference ``examples/imagenet/main_amp.py:445-460``)
plus the serving-side counters (``apex_tpu.serving``: tokens/s, queue
depth)."""

from __future__ import annotations

import time


class AverageMeter:
    """Tracks the latest value and the running (weighted) average."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)


class RateMeter:
    """Events per second over wall time — the serving tokens/s meter.

    ``update(n)`` adds n events; ``rate`` is total events / elapsed
    seconds since construction or :meth:`reset`.  A monotonic clock and
    a floor on elapsed keep it sane for sub-millisecond smoke runs."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self.reset()

    def reset(self):
        self.total = 0
        self._start = self._clock()

    def update(self, n: int = 1):
        self.total += n

    @property
    def elapsed(self) -> float:
        return max(self._clock() - self._start, 1e-9)

    @property
    def rate(self) -> float:
        return self.total / self.elapsed


class GaugeMeter:
    """Current / peak / running-mean of a sampled level — the serving
    queue-depth and running-batch-occupancy meter."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.peak = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val):
        val = float(val)
        self.val = val
        self.peak = max(self.peak, val)
        self.sum += val
        self.count += 1

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)
