"""apex_tpu.utils — observability and training-loop utilities.

The reference scatters these across examples and test harnesses (no utils
package of its own): ``AverageMeter`` (reference
``examples/imagenet/main_amp.py:445-460``), nvtx range annotations
(``apex/parallel/sync_batchnorm.py:66`` and friends), rank0-aware printing
(``apex/amp/_amp_state.py:43-52``), and torch ``state_dict`` checkpointing
conventions. Here they are first-class:

- :class:`AverageMeter` — running value/average tracker;
- :class:`RateMeter` / :class:`GaugeMeter` — serving-side tokens/s and
  queue-depth/occupancy counters (``apex_tpu.serving``);
- :class:`CounterMeter` — monotonic named counters for failure
  accounting (checkpoints written/skipped-corrupt, IO retries, sentry
  rollbacks, serving requests failed by reason —
  ``apex_tpu.resilience``, ``docs/resilience.md``);
- :func:`trace_annotation` / :func:`annotate_function` — xprof trace
  annotations (the TPU analog of nvtx push/pop);
- :func:`maybe_print` — verbosity- and rank-gated printing;
- :mod:`apex_tpu.utils.checkpoint` — one-call save/restore of a full
  train-state pytree including amp loss-scaler state (fixes the
  reference's amp-state checkpoint gap, SURVEY.md §5), plus the
  crash-consistent :class:`~apex_tpu.utils.checkpoint.CheckpointManager`
  (atomic publish, checksummed manifest, retention, corrupt-fallback
  restore).
"""

from apex_tpu.amp._amp_state import maybe_print
from apex_tpu.utils.meters import (
    AverageMeter,
    CounterMeter,
    GaugeMeter,
    RateMeter,
)
from apex_tpu.utils.profiling import (
    annotate_function,
    trace_annotation,
    start_trace,
    stop_trace,
)
from apex_tpu.utils import checkpoint
from apex_tpu.utils.torch_interop import load_hf_bert, load_torch_resnet

__all__ = [
    "AverageMeter",
    "CounterMeter",
    "GaugeMeter",
    "RateMeter",
    "annotate_function",
    "checkpoint",
    "load_hf_bert",
    "load_torch_resnet",
    "maybe_print",
    "start_trace",
    "stop_trace",
    "trace_annotation",
]
