"""Trace annotations + profiler control — the TPU analog of nvtx.

The reference marks hot phases with ``torch.cuda.nvtx.range_push/pop``
(``apex/parallel/sync_batchnorm.py:66,84,129``,
``optimized_sync_batchnorm_kernel.py:11,66,72,109``) and drives nsight via
``cudaProfilerStart/Stop`` (``tests/distributed/DDP/ddp_race_condition_test.py:44,66``).
On TPU the equivalents are ``jax.profiler.TraceAnnotation`` (shows up in
xprof/tensorboard timelines) and ``jax.profiler.start_trace/stop_trace``.

Annotations are named at trace time; inside jit they label the traced
region rather than per-step execution — which is exactly what xprof
needs (ops carry the annotation through compilation).
"""

from __future__ import annotations

import contextlib
import functools


@contextlib.contextmanager
def trace_annotation(name: str, **kwargs):
    """``with trace_annotation("forward"):`` — nvtx range_push/pop analog."""
    import jax.profiler

    with jax.profiler.TraceAnnotation(name, **kwargs):
        yield


def annotate_function(fn=None, *, name: str = None):
    """Decorator form (nvtx ``@annotate`` analog); labels the wrapped
    function's ops in profiler timelines."""
    if fn is None:
        return functools.partial(annotate_function, name=name)
    import jax.profiler

    return jax.profiler.annotate_function(fn, name=name)


def start_trace(log_dir: str, **kwargs):
    """Begin an xprof trace (``cudaProfilerStart`` analog)."""
    import jax.profiler

    jax.profiler.start_trace(log_dir, **kwargs)


def stop_trace():
    """End the xprof trace (``cudaProfilerStop`` analog)."""
    import jax.profiler

    jax.profiler.stop_trace()
