"""Torch checkpoint interop: load torchvision-style ResNet weights.

The migration path for reference users: the reference's flagship
workload is torchvision ResNet driven by ``examples/imagenet``
(reference ``main_amp.py:141-148``), so "switching frameworks" starts
with carrying those checkpoints over.  ``models.resnet`` is structured
1:1 with torchvision (same stem/stage/block layout, v1.5 strides), so
the conversion is pure renaming + layout transposition:

- conv ``weight`` OIHW -> flax ``kernel`` HWIO;
- linear ``weight`` (O, I) -> ``kernel`` (I, O);
- bn ``weight``/``bias`` -> ``scale``/``bias`` (params) and
  ``running_mean``/``running_var`` -> ``mean``/``var`` (batch_stats);
- ``layer{s}.{i}`` -> the s/i-th ``BasicBlock_k``/``Bottleneck_k`` in
  flax auto-naming order, ``downsample.0/.1`` ->
  ``downsample_conv``/``downsample_bn``.

Accepts a ``state_dict``-like mapping of torch tensors OR numpy arrays
(no torch import needed unless tensors are passed).  Returns
``{"params": ..., "batch_stats": ...}`` ready for
``models.ResNetXX().apply`` — verified numerically against a live
torch model in ``tests/L0/test_torch_interop.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax.numpy as jnp
import numpy as np

_ARCH = {
    "resnet18": ("BasicBlock", [2, 2, 2, 2], 2),
    "resnet34": ("BasicBlock", [3, 4, 6, 3], 2),
    "resnet50": ("Bottleneck", [3, 4, 6, 3], 3),
    "resnet101": ("Bottleneck", [3, 4, 23, 3], 3),
    "resnet152": ("Bottleneck", [3, 8, 36, 3], 3),
}


def _np(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _conv(w) -> jnp.ndarray:
    return jnp.asarray(_np(w).transpose(2, 3, 1, 0))  # OIHW -> HWIO


def _strip_module_prefix(state_dict):
    """DDP-wrapped models save "module."-prefixed keys (the reference's
    own imagenet script does); strip a uniform prefix transparently."""
    if state_dict and all(k.startswith("module.") for k in state_dict):
        return {k[len("module."):]: v for k, v in state_dict.items()}
    return state_dict


def load_torch_resnet(state_dict: Mapping[str, Any],
                      arch: str = "resnet50",
                      norm_name: str = "BatchNorm",
                      stem: str = "conv") -> Dict[str, Any]:
    """Convert a torchvision-format ResNet ``state_dict`` into the
    variables pytree of ``models.ResNetXX`` (see module docstring).

    ``norm_name``: class name of the model's block norm layers — flax
    auto-names them ``{ClassName}_{i}``, so a model built with
    ``norm=parallel.SyncBatchNorm`` (``convert_syncbn_model`` /
    ``--sync_bn``) needs ``norm_name="SyncBatchNorm"``.  The explicitly
    named ``stem_bn``/``downsample_bn`` are unaffected.

    ``stem="s2d"``: emit the checkpoint's 7x7 stem kernel rearranged
    for ``models.ResNet(stem="s2d")`` (``models.resnet.stem_to_s2d`` —
    exactly equivalent math, MXU-friendlier layout)."""
    if arch not in _ARCH:
        raise ValueError(f"unknown arch {arch!r}; have {sorted(_ARCH)}")
    block_name, stage_sizes, convs_per_block = _ARCH[arch]

    state_dict = _strip_module_prefix(state_dict)
    consumed = set()

    class _Tracking:
        """dict view recording which checkpoint keys were consumed, and
        turning missing keys into arch-mismatch guidance."""

        def __getitem__(self, key):
            consumed.add(key)
            try:
                return state_dict[key]
            except KeyError:
                raise ValueError(
                    f"state_dict is missing {key!r}, required by "
                    f"arch={arch!r} — wrong arch for this checkpoint?"
                ) from None

        def __contains__(self, key):
            return key in state_dict

    sd = _Tracking()
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}

    def bn(src: str, dst: str, p: Dict[str, Any], s: Dict[str, Any]):
        p[dst] = {"scale": jnp.asarray(_np(sd[f"{src}.weight"])),
                  "bias": jnp.asarray(_np(sd[f"{src}.bias"]))}
        s[dst] = {"mean": jnp.asarray(_np(sd[f"{src}.running_mean"])),
                  "var": jnp.asarray(_np(sd[f"{src}.running_var"]))}

    if stem in ("s2d", "s2d_pre"):  # identical weights either way — the
        # variants differ only in where the input transform runs
        from apex_tpu.models.resnet import stem_to_s2d
        params["stem_conv_s2d"] = {
            "kernel": stem_to_s2d(_conv(sd["conv1.weight"]))}
    elif stem == "conv":
        params["stem_conv"] = {"kernel": _conv(sd["conv1.weight"])}
    else:  # same validation as ResNet.__call__ — fail HERE, not at apply
        raise ValueError(f"stem must be 'conv', 's2d' or 's2d_pre', "
                         f"got {stem!r}")
    bn("bn1", "stem_bn", params, stats)

    k = 0
    for s, n_blocks in enumerate(stage_sizes, start=1):
        for i in range(n_blocks):
            src = f"layer{s}.{i}"
            blk_p: Dict[str, Any] = {}
            blk_s: Dict[str, Any] = {}
            for c in range(convs_per_block):
                blk_p[f"Conv_{c}"] = {
                    "kernel": _conv(sd[f"{src}.conv{c + 1}.weight"])}
                bn(f"{src}.bn{c + 1}", f"{norm_name}_{c}", blk_p, blk_s)
            if f"{src}.downsample.0.weight" in sd:
                blk_p["downsample_conv"] = {
                    "kernel": _conv(sd[f"{src}.downsample.0.weight"])}
                bn(f"{src}.downsample.1", "downsample_bn", blk_p, blk_s)
            name = f"{block_name}_{k}"
            params[name] = blk_p
            stats[name] = blk_s
            k += 1

    params["fc"] = {"kernel": jnp.asarray(_np(sd["fc.weight"]).T),
                    "bias": jnp.asarray(_np(sd["fc.bias"]))}

    # a checkpoint deeper than `arch` converts key-complete but silently
    # truncated — refuse leftovers instead (num_batches_tracked counters
    # are torch bookkeeping with no flax analog)
    leftovers = [key for key in state_dict
                 if key not in consumed
                 and not key.endswith("num_batches_tracked")]
    if leftovers:
        raise ValueError(
            f"state_dict has {len(leftovers)} keys not consumed by "
            f"arch={arch!r} (e.g. {sorted(leftovers)[:4]}); wrong arch?")
    return {"params": params, "batch_stats": stats}


def load_hf_bert(state_dict: Mapping[str, Any],
                 num_hidden_layers: int,
                 num_attention_heads: int) -> Dict[str, Any]:
    """Convert a HuggingFace ``BertForPreTraining`` ``state_dict`` into
    the params pytree of ``models.BertForPreTraining``.

    Mapping (torch Linear ``weight`` is (out, in); flax kernels are
    (in, out), attention projections DenseGeneral-shaped):

    - ``bert.embeddings.*`` -> ``encoder/{word,position,token_type}_
      embeddings`` + ``embeddings_ln``;
    - ``attention.self.{query,key,value}``: weight.T reshaped
      ``(H, heads, head_dim)``, bias ``(heads, head_dim)``;
    - ``attention.output.dense``: weight.T reshaped
      ``(heads, head_dim, H)``;
    - ``intermediate/output`` denses and LayerNorms 1:1;
    - ``cls.predictions.transform`` -> ``mlm_transform``/``mlm_ln``,
      ``cls.predictions.decoder`` (+ the tied ``cls.predictions.bias``)
      -> ``mlm_decoder``; ``cls.seq_relationship`` -> ``nsp_classifier``;
      ``bert.pooler.dense`` -> ``pooler``.

    Returns ``{"params": ...}``; verified numerically against a live
    ``transformers`` model in ``tests/L0/test_torch_interop.py``.
    """
    raw = {k: _np(v)
           for k, v in _strip_module_prefix(state_dict).items()}
    consumed = set()

    def get(key):
        consumed.add(key)
        try:
            return raw[key]
        except KeyError:
            raise ValueError(
                f"state_dict is missing {key!r} — not a HuggingFace "
                "BertForPreTraining checkpoint, or wrong "
                "num_hidden_layers?") from None

    nh = num_attention_heads

    def lin(src):  # torch Linear -> flax Dense
        return {"kernel": jnp.asarray(get(f"{src}.weight").T),
                "bias": jnp.asarray(get(f"{src}.bias"))}

    def ln(src):
        return {"scale": jnp.asarray(get(f"{src}.weight")),
                "bias": jnp.asarray(get(f"{src}.bias"))}

    def emb(src):
        return {"embedding": jnp.asarray(get(f"{src}.weight"))}

    enc: Dict[str, Any] = {
        "word_embeddings": emb("bert.embeddings.word_embeddings"),
        "position_embeddings": emb("bert.embeddings.position_embeddings"),
        "token_type_embeddings": emb(
            "bert.embeddings.token_type_embeddings"),
        "embeddings_ln": ln("bert.embeddings.LayerNorm"),
    }
    for i in range(num_hidden_layers):
        src = f"bert.encoder.layer.{i}"
        h = get(f"{src}.attention.self.query.weight").shape[1]
        dh = h // nh

        def qkv(name):
            w = get(f"{src}.attention.self.{name}.weight")
            b = get(f"{src}.attention.self.{name}.bias")
            return {"kernel": jnp.asarray(w.T.reshape(h, nh, dh)),
                    "bias": jnp.asarray(b.reshape(nh, dh))}

        out_w = get(f"{src}.attention.output.dense.weight")
        enc[f"layer_{i}"] = {
            "attention": {
                "query": qkv("query"), "key": qkv("key"),
                "value": qkv("value"),
                "output": {
                    "kernel": jnp.asarray(out_w.T.reshape(nh, dh, h)),
                    "bias": jnp.asarray(
                        get(f"{src}.attention.output.dense.bias"))},
            },
            "attention_ln": ln(f"{src}.attention.output.LayerNorm"),
            "intermediate": lin(f"{src}.intermediate.dense"),
            "output": lin(f"{src}.output.dense"),
            "output_ln": ln(f"{src}.output.LayerNorm"),
        }

    if "cls.predictions.decoder.bias" in raw:
        dec_bias = get("cls.predictions.decoder.bias")
        consumed.add("cls.predictions.bias")  # tied duplicate, if present
    else:
        dec_bias = get("cls.predictions.bias")
    params = {
        "encoder": enc,
        "pooler": lin("bert.pooler.dense"),
        "mlm_transform": lin("cls.predictions.transform.dense"),
        "mlm_ln": ln("cls.predictions.transform.LayerNorm"),
        "mlm_decoder": {
            "kernel": jnp.asarray(get("cls.predictions.decoder.weight").T),
            "bias": jnp.asarray(dec_bias)},
        "nsp_classifier": lin("cls.seq_relationship"),
    }

    # refuse silent truncation (e.g. a 24-layer checkpoint converted
    # with num_hidden_layers=12); position_ids is a registered buffer in
    # some transformers versions, bookkeeping with no param analog
    leftovers = [k for k in raw if k not in consumed
                 and not k.endswith("position_ids")]
    if leftovers:
        raise ValueError(
            f"state_dict has {len(leftovers)} keys not consumed with "
            f"num_hidden_layers={num_hidden_layers} "
            f"(e.g. {sorted(leftovers)[:4]}); wrong layer count?")
    return {"params": params}
