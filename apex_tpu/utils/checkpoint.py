"""Checkpoint/resume for full train states (params + opt + amp scaler).

The reference delegates checkpointing to torch ``state_dict`` conventions
and its FP16 optimizers serialize fp32 masters + scaler state separately
(``apex/fp16_utils/fp16_optimizer.py:298-359`` "option 2";
``apex/optimizers/fp16_optimizer.py:211-274``) — but the new amp API has
no ``amp.state_dict`` at all, so O1/O2 loss-scale state is silently lost
on resume (SURVEY.md §5). Here the whole train state — params,
batch_stats, optimizer state *including* ``AmpOptimizerState`` with its
loss-scaler pytrees — is one pytree and checkpointing is one call.

Two layers:

- :func:`save` / :func:`restore` — one-shot pytree IO to a directory.
  Backend: orbax-checkpoint when importable (async-capable, multi-host
  aware), else a numpy ``.npz`` + structure-pickle fallback with the
  same API. Restore always takes a ``target`` pytree so
  namedtuple/custom-node structure (AmpOptimizerState, optax states)
  round-trips exactly.  (The npz fallback's treedef pickle is NOT
  portable across library version bumps — see ``docs/resilience.md``
  for the full caution and when to prefer orbax.)
- :class:`CheckpointManager` — crash-consistent step-numbered
  checkpoints on top of the same backends: atomic publish
  (write-to-tmp → fsync → rename), a manifest with per-leaf checksums,
  retention, corrupt-checkpoint fallback on restore, and optional
  background-thread saves.  ``docs/resilience.md`` documents the
  on-disk layout and the fault-injection recipes that prove it.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

Pytree = Any

try:  # pragma: no cover - environment probe
    import orbax.checkpoint as _ocp
except Exception:  # pragma: no cover
    _ocp = None

_NPZ_FILE = "train_state.npz"
_TREEDEF_FILE = "treedef.pkl"


def _is_orbax_dir(path: str) -> bool:
    return os.path.isdir(path) and not os.path.exists(
        os.path.join(path, _NPZ_FILE))


def save(path: str, state: Pytree, *, force: bool = True) -> None:
    """Save ``state`` (any pytree) to ``path`` (a directory)."""
    path = os.path.abspath(path)
    state = jax.device_get(state)
    if _ocp is not None:
        ckptr = _ocp.PyTreeCheckpointer()
        ckptr.save(path, state, force=force)
        return
    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    np.savez(os.path.join(path, _NPZ_FILE),
             **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
    with open(os.path.join(path, _TREEDEF_FILE), "wb") as f:
        pickle.dump(treedef, f)


def restore(path: str, target: Optional[Pytree] = None) -> Pytree:
    """Restore the pytree saved at ``path``.

    ``target`` (an example pytree of the right structure, e.g. the freshly
    initialized train state) restores custom node types and dtypes
    faithfully; without it, containers come back as plain dict/lists.
    """
    path = os.path.abspath(path)
    if _ocp is not None and _is_orbax_dir(path):
        ckptr = _ocp.PyTreeCheckpointer()
        if target is not None:
            restored = ckptr.restore(path, item=jax.device_get(target))
        else:
            restored = ckptr.restore(path)
        return restored
    treedef_path = os.path.join(path, _TREEDEF_FILE)
    if not os.path.exists(treedef_path) and _is_orbax_dir(path):
        # backend mismatch, named plainly instead of a raw unpickling /
        # missing-file error: the directory has no npz payload, so it
        # was written by the orbax backend, and orbax is not importable
        # here to read it back.
        raise ValueError(
            f"checkpoint at {path} was written by the other backend "
            f"(orbax), but orbax-checkpoint is not importable in this "
            f"environment; install orbax-checkpoint to restore it")
    with open(treedef_path, "rb") as f:
        treedef = pickle.load(f)
    with np.load(os.path.join(path, _NPZ_FILE)) as z:
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if target is not None:
        # re-shape onto the target structure (validates compatibility)
        t_leaves, t_def = jax.tree_util.tree_flatten(target)
        s_leaves = jax.tree_util.tree_leaves(state)
        if len(t_leaves) != len(s_leaves):
            raise ValueError(
                f"checkpoint at {path} has {len(s_leaves)} leaves; "
                f"target expects {len(t_leaves)}")
        state = jax.tree_util.tree_unflatten(t_def, s_leaves)
    return state


# -- crash-consistent manager ---------------------------------------------

MANIFEST_FILE = "manifest.json"
MANIFEST_FORMAT = 1
_STEP_PREFIX = "step_"
_STEP_DIGITS = 8
_TMP_PREFIX = ".tmp-"
_PAYLOAD_DIR = "state"


class CheckpointCorruptError(RuntimeError):
    """A published checkpoint failed integrity verification (missing or
    malformed manifest, leaf-count mismatch, or checksum mismatch)."""


def leaf_checksum(leaf) -> str:
    """``crc32:dtype:shape`` fingerprint of one pytree leaf.  Covers
    value bytes AND geometry, so a silently re-shaped or down-cast leaf
    fails verification even when its bytes collide."""
    a = np.ascontiguousarray(np.asarray(leaf))
    crc = zlib.crc32(a.tobytes()) & 0xFFFFFFFF
    return f"{crc:08x}:{a.dtype.str}:{'x'.join(map(str, a.shape))}"


def tree_checksums(state: Pytree) -> List[str]:
    """Per-leaf :func:`leaf_checksum` fingerprints of ``state``, in
    ``tree_flatten`` order — the same order a :class:`CheckpointManager`
    manifest records them in, so a live pytree can be verified against
    a published checkpoint without re-reading the payload (the serving
    rollout's per-replica swap audit does exactly this)."""
    return [leaf_checksum(x)
            for x in jax.tree_util.tree_leaves(jax.device_get(state))]


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_tree(root: str) -> None:
    """fsync every file and directory under ``root`` (bottom-up) so the
    rename that follows publishes fully durable bytes."""
    for dirpath, _, filenames in os.walk(root, topdown=False):
        for name in filenames:
            _fsync_path(os.path.join(dirpath, name))
        _fsync_path(dirpath)


def step_dir_name(step: int) -> str:
    return f"{_STEP_PREFIX}{step:0{_STEP_DIGITS}d}"


class CheckpointManager:
    """Step-numbered, crash-consistent checkpoints with retention.

    On-disk layout (see ``docs/resilience.md``)::

        root/
          step_00000007/
            manifest.json          # step, backend, per-leaf checksums
            state/                 # backend payload (orbax or npz)

    Guarantees:

    - **Atomic publish** — a checkpoint is written to a dot-tmp sibling,
      fsynced file-by-file, then ``os.rename``d into place (atomic on
      POSIX) and the root directory fsynced.  A crash at ANY point
      leaves either the complete previous state or the complete new one;
      stale tmp dirs are swept on the next save.
    - **Verified restore** — :meth:`restore_latest` recomputes every
      leaf's checksum against the manifest and silently falls back past
      a partial/corrupt checkpoint to the newest good one (accounted in
      ``counters['checkpoints_skipped_corrupt']``).
    - **Retention** — ``keep_last=N`` bounds disk; ``keep_every=K``
      additionally pins every K-th step (milestones survive the sweep).
    - **Transient-IO tolerance** — payload writes run under
      :func:`apex_tpu.resilience.retry` with decorrelated jitter.

    ``save(..., block=False)`` snapshots the state to host and writes on
    a background thread; :meth:`wait` joins and re-raises.  Fault hooks
    (:class:`apex_tpu.resilience.FaultPlan`) are taken from the
    ``fault_plan`` argument or the ``APEX_TPU_FAULTS`` environment.

    Telemetry (``docs/observability.md``): save/restore run under
    ``checkpoint_save`` / ``checkpoint_restore`` tracer spans (a
    ``checkpoint_publish`` instant marks the atomic rename) and their
    wall time feeds ``checkpoint_save_s`` / ``checkpoint_restore_s``
    histograms.  Pass ``registry=`` to put the histograms — and, when
    ``counters`` is not supplied, the counter meter — on a shared
    :class:`apex_tpu.observability.MetricsRegistry`; the tracer
    defaults to the process one (``APEX_TPU_TRACE``).
    """

    def __init__(self, root: str, *,
                 keep_last: Optional[int] = None,
                 keep_every: Optional[int] = None,
                 retry_attempts: int = 4,
                 retry_backoff: float = 0.05,
                 retry_deadline: Optional[float] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 counters=None,
                 fault_plan=None,
                 registry=None,
                 tracer=None):
        from apex_tpu.observability import HistogramMeter, get_tracer
        from apex_tpu.resilience.faults import resolve_fault_plan
        from apex_tpu.utils.meters import CounterMeter

        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        if keep_last is not None and keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.retry_attempts = int(retry_attempts)
        self.retry_backoff = float(retry_backoff)
        self.retry_deadline = retry_deadline
        self._sleep = sleep
        self.registry = registry
        self.tracer = tracer if tracer is not None else get_tracer()
        if counters is not None:
            self.counters = counters
        elif registry is not None:
            self.counters = CounterMeter(registry=registry,
                                         name="checkpoint", label="event")
        else:
            self.counters = CounterMeter()
        if registry is not None:
            self.save_time = registry.histogram("checkpoint_save_s")
            self.restore_time = registry.histogram("checkpoint_restore_s")
        else:
            self.save_time = HistogramMeter("checkpoint_save_s")
            self.restore_time = HistogramMeter("checkpoint_restore_s")
        self.fault_plan = resolve_fault_plan(fault_plan)
        self._thread: Optional[threading.Thread] = None
        self._thread_error: Optional[BaseException] = None

    # -- directory bookkeeping -------------------------------------------

    def steps(self) -> List[int]:
        """Published step numbers, ascending (tmp dirs excluded)."""
        self.wait()
        out = []
        for name in os.listdir(self.root):
            if not name.startswith(_STEP_PREFIX):
                continue
            try:
                step = int(name[len(_STEP_PREFIX):])
            except ValueError:
                continue
            if os.path.exists(os.path.join(self.root, name, MANIFEST_FILE)):
                out.append(step)
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, step_dir_name(step))

    def read_manifest(self, step: int) -> Dict[str, Any]:
        with open(os.path.join(self._dir(step), MANIFEST_FILE)) as f:
            return json.load(f)

    # -- save -------------------------------------------------------------

    def save(self, step: int, state: Pytree,
             metadata: Optional[Dict[str, Any]] = None, *,
             block: bool = True) -> None:
        """Publish ``state`` as the checkpoint for ``step``.

        ``block=False`` returns after snapshotting ``state`` to host
        memory (so the caller may mutate/donate device buffers freely)
        and publishes on a background thread; the next manager call —
        or an explicit :meth:`wait` — joins it and re-raises any
        failure.  Saves are serialized: at most one is in flight."""
        self.wait()
        snapshot = jax.device_get(state)
        if not block:
            self._thread = threading.Thread(
                target=self._save_guarded, args=(step, snapshot, metadata),
                name=f"ckpt-save-{step}", daemon=True)
            self._thread.start()
            return
        self._save_sync(step, snapshot, metadata)

    def _save_guarded(self, step, snapshot, metadata):
        try:
            self._save_sync(step, snapshot, metadata)
        except BaseException as err:  # surfaced by wait()
            self._thread_error = err

    def _save_sync(self, step: int, snapshot: Pytree,
                   metadata: Optional[Dict[str, Any]]) -> None:
        with self.tracer.span("checkpoint_save", step=int(step)):
            with self.save_time.time():
                self._save_body(step, snapshot, metadata)

    def _save_body(self, step: int, snapshot: Pytree,
                   metadata: Optional[Dict[str, Any]]) -> None:
        from apex_tpu.resilience.retry import retry

        final = self._dir(step)
        tmp = os.path.join(self.root,
                           _TMP_PREFIX + step_dir_name(step))
        self._sweep_tmp()

        leaves = jax.tree_util.tree_leaves(snapshot)
        manifest = {
            "format": MANIFEST_FORMAT,
            "step": int(step),
            "backend": "orbax" if _ocp is not None else "npz",
            "num_leaves": len(leaves),
            "leaf_checksums": [leaf_checksum(x) for x in leaves],
            "metadata": metadata or {},
            "written_unix": time.time(),
        }

        def write_tmp():
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            if self.fault_plan is not None:
                self.fault_plan.io_gate(tmp)
            save(os.path.join(tmp, _PAYLOAD_DIR), snapshot)
            with open(os.path.join(tmp, MANIFEST_FILE), "w") as f:
                json.dump(manifest, f, indent=1)
            _fsync_tree(tmp)

        retry(write_tmp,
              attempts=self.retry_attempts,
              backoff=self.retry_backoff,
              deadline=self.retry_deadline,
              sleep=self._sleep,
              on_retry=lambda i, e: self.counters.incr(
                  "checkpoint_retries"))

        if os.path.exists(final):   # re-save of the same step: replace
            shutil.rmtree(final)
        os.rename(tmp, final)       # the publish point (atomic, POSIX)
        _fsync_path(self.root)
        if self.tracer.enabled:
            self.tracer.instant("checkpoint_publish", step=int(step))
        self.counters.incr("checkpoints_written")
        if self.fault_plan is not None:
            self.fault_plan.maybe_tear(final, step)
        self._apply_retention()

    def wait(self) -> None:
        """Join an in-flight background save; re-raise its failure."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        err, self._thread_error = self._thread_error, None
        if err is not None:
            raise err

    def _sweep_tmp(self) -> None:
        for name in os.listdir(self.root):
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    def _apply_retention(self) -> None:
        if self.keep_last is None:
            return
        steps = []
        for name in os.listdir(self.root):
            if name.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        steps.sort()
        protected = set(steps[-self.keep_last:])
        if self.keep_every:
            protected |= {s for s in steps if s % self.keep_every == 0}
        for s in steps:
            if s not in protected:
                shutil.rmtree(self._dir(s), ignore_errors=True)
                self.counters.incr("checkpoints_retired")

    # -- restore ----------------------------------------------------------

    def restore(self, step: int,
                target: Optional[Pytree] = None) -> Pytree:
        """Restore step ``step``, verifying the manifest's leaf count
        and per-leaf checksums; :class:`CheckpointCorruptError` on any
        integrity failure."""
        self.wait()
        with self.tracer.span("checkpoint_restore", step=int(step)):
            with self.restore_time.time():
                return self._restore_body(step, target)

    def _restore_body(self, step: int,
                      target: Optional[Pytree]) -> Pytree:
        ckpt_dir = self._dir(step)
        manifest_path = os.path.join(ckpt_dir, MANIFEST_FILE)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as err:
            raise CheckpointCorruptError(
                f"{ckpt_dir}: unreadable manifest ({err})") from err
        state = restore(os.path.join(ckpt_dir, _PAYLOAD_DIR), target)
        leaves = jax.tree_util.tree_leaves(state)
        if len(leaves) != manifest["num_leaves"]:
            raise CheckpointCorruptError(
                f"{ckpt_dir}: {len(leaves)} leaves restored, manifest "
                f"records {manifest['num_leaves']}")
        for i, (leaf, want) in enumerate(
                zip(leaves, manifest["leaf_checksums"])):
            got = leaf_checksum(leaf)
            if got != want:
                raise CheckpointCorruptError(
                    f"{ckpt_dir}: leaf {i} checksum mismatch "
                    f"(manifest {want}, restored {got})")
        return state

    def restore_latest(self, target: Optional[Pytree] = None,
                       ) -> Optional[Tuple[Pytree, int]]:
        """(state, step) from the newest checkpoint that passes
        verification, scanning backwards past corrupt/partial ones;
        None when no checkpoint restores."""
        for step in reversed(self.steps()):
            try:
                return self.restore(step, target), step
            except KeyboardInterrupt:   # pragma: no cover
                raise
            except Exception:
                # corrupt manifest/payload, truncated file, backend
                # error — all mean "this checkpoint is not a safe
                # restore point"; fall back to the previous one
                self.counters.incr("checkpoints_skipped_corrupt")
                continue
        return None
