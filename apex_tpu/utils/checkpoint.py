"""Checkpoint/resume for full train states (params + opt + amp scaler).

The reference delegates checkpointing to torch ``state_dict`` conventions
and its FP16 optimizers serialize fp32 masters + scaler state separately
(``apex/fp16_utils/fp16_optimizer.py:298-359`` "option 2";
``apex/optimizers/fp16_optimizer.py:211-274``) — but the new amp API has
no ``amp.state_dict`` at all, so O1/O2 loss-scale state is silently lost
on resume (SURVEY.md §5). Here the whole train state — params,
batch_stats, optimizer state *including* ``AmpOptimizerState`` with its
loss-scaler pytrees — is one pytree and checkpointing is one call.

Backend: orbax-checkpoint when importable (async-capable, multi-host
aware), else a numpy ``.npz`` + structure-pickle fallback with the same
API. Restore always takes a ``target`` pytree so namedtuple/custom-node
structure (AmpOptimizerState, optax states) round-trips exactly.

.. caution:: The npz fallback pickles the *treedef* alongside the arrays.
   Pickled treedefs reference the defining classes by module path, so a
   fallback checkpoint is NOT portable across jax/optax/apex_tpu version
   bumps that move or rename state classes (orbax checkpoints restore
   structurally via ``target`` and don't have this problem). Treat npz
   checkpoints as same-environment restart artifacts; for archival or
   cross-version checkpoints, install orbax. On version-mismatch
   ``restore`` raises the underlying unpickling error rather than
   guessing.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

Pytree = Any

try:  # pragma: no cover - environment probe
    import orbax.checkpoint as _ocp
except Exception:  # pragma: no cover
    _ocp = None


def _is_orbax_dir(path: str) -> bool:
    return os.path.isdir(path) and not os.path.exists(
        os.path.join(path, "train_state.npz"))


def save(path: str, state: Pytree, *, force: bool = True) -> None:
    """Save ``state`` (any pytree) to ``path`` (a directory)."""
    path = os.path.abspath(path)
    state = jax.device_get(state)
    if _ocp is not None:
        ckptr = _ocp.PyTreeCheckpointer()
        ckptr.save(path, state, force=force)
        return
    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    np.savez(os.path.join(path, "train_state.npz"),
             **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
    with open(os.path.join(path, "treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def restore(path: str, target: Optional[Pytree] = None) -> Pytree:
    """Restore the pytree saved at ``path``.

    ``target`` (an example pytree of the right structure, e.g. the freshly
    initialized train state) restores custom node types and dtypes
    faithfully; without it, containers come back as plain dict/lists.
    """
    path = os.path.abspath(path)
    if _ocp is not None and _is_orbax_dir(path):
        ckptr = _ocp.PyTreeCheckpointer()
        if target is not None:
            restored = ckptr.restore(path, item=jax.device_get(target))
        else:
            restored = ckptr.restore(path)
        return restored
    with open(os.path.join(path, "treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    with np.load(os.path.join(path, "train_state.npz")) as z:
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if target is not None:
        # re-shape onto the target structure (validates compatibility)
        t_leaves, t_def = jax.tree_util.tree_flatten(target)
        s_leaves = jax.tree_util.tree_leaves(state)
        if len(t_leaves) != len(s_leaves):
            raise ValueError(
                f"checkpoint has {len(s_leaves)} leaves; target expects "
                f"{len(t_leaves)}")
        state = jax.tree_util.tree_unflatten(t_def, s_leaves)
    return state
