"""Pytree key-path helpers shared by the path-matching subsystems.

Key extraction from jax KeyPath entries (DictKey/GetAttrKey/
SequenceKey/FlattenedIndexKey) lives here once; the consumers differ
only in matching semantics:

- ``amp.model`` matches patterns against individual components
  (anchored patterns like ``^bn(_|\\d|$)`` must see one name at a time);
- ``parallel.tensor_parallel`` matches rules against the ``/``-joined
  path (``attention/query/kernel``);
- ``optimizers.param_groups`` keeps ``jax.tree_util.keystr`` — its
  regex format is a documented user contract there.
"""

from __future__ import annotations

from typing import List, Tuple


def path_components(keypath) -> List[str]:
    """Printable name of each entry in a jax tree key path."""
    return [str(getattr(k, "key", getattr(k, "name", k))) for k in keypath]


def path_str(keypath) -> str:
    """``/``-joined form: ``encoder/layer_0/attention/query/kernel``."""
    return "/".join(path_components(keypath))
