"""Step-level flight recorder + postmortem bundles for the serving loop.

The PR-4 telemetry layer answers "what moved" (p99 shifted, a counter
jumped); what it cannot answer is "which scheduler decisions led up to
it" — when a chaos invariant trips at iteration 1840 or a breaker
opens in production, the histograms have already averaged away the
admit/shed/preempt sequence that caused it.  This module is the
missing black box:

- :class:`FlightRecorder` — a bounded ring (``deque(maxlen=...)``) of
  structured per-engine-step records.  ``serving.api`` assembles one
  plain dict per :meth:`InferenceServer.step` — batch composition,
  admit/shed/preempt/evict decisions, allocator + prefix-cache +
  lookahead occupancy, speculation drafted/accepted, ``pressure()``,
  breaker state, step wall time — and :meth:`record` appends it.  A
  long-running server keeps the most recent window;
  :attr:`FlightRecorder.dropped` counts what rolled off.
- :data:`NULL_FLIGHT_RECORDER` — the disabled default, exactly the
  ``NULL_TRACER`` pattern: ``record()`` is a no-op and the serve loop
  guards record *assembly* on ``recorder.enabled``, so the disabled
  path adds zero allocations per step
  (``tests/L0/test_flightrecorder.py`` pins this with tracemalloc).
- :func:`write_postmortem` — dumps a **postmortem bundle**: the
  flight-recorder ring as JSONL, a ``MetricsRegistry.snapshot()``, the
  tracer's Chrome trace, and a manifest tying them together.
  ``InferenceServer`` writes bundles on demand
  (:meth:`~InferenceServer.dump_postmortem`), on breaker-open
  transitions, on ``audit()`` failure, and
  :func:`resilience.chaos.run_soak` writes one on any invariant
  violation.  ``tools/postmortem.py`` renders, slices
  (``--request <uid>``), diffs, and gates (``--assert-complete``)
  bundles.

Recording never draws randomness and never feeds back into scheduler
decisions, so a soak runs byte-identical with the recorder on or off
(pinned by the chaos build-matrix axis).  See ``docs/observability.md``,
"Flight recorder & postmortems".
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Dict, Optional, Tuple

POSTMORTEM_ENV = "APEX_TPU_POSTMORTEM"

# bundle member names — one place, shared with tools/postmortem.py
MANIFEST_NAME = "manifest.json"
FLIGHT_NAME = "flight.jsonl"
METRICS_NAME = "metrics.json"
TRACE_NAME = "trace.json"
# optional member (only when the dumping server has journeys enabled;
# tools/journey.py renders and gates it — docs/observability.md,
# "Request journeys & exemplars")
JOURNEYS_NAME = "journeys.json"


class NullFlightRecorder:
    """The disabled recorder: ``record()`` drops everything and hot
    paths guard record assembly on :attr:`enabled`, so serving with
    the recorder off allocates nothing per step."""

    enabled = False
    steps_recorded = 0
    dropped = 0

    def record(self, rec) -> None:
        pass

    def records(self) -> Tuple[Dict[str, Any], ...]:
        return ()

    def clear(self) -> None:
        pass

    def dump_jsonl(self, path: str) -> str:
        with open(path, "w"):
            pass                    # an empty, still-parseable JSONL
        return path


NULL_FLIGHT_RECORDER = NullFlightRecorder()


class FlightRecorder:
    """Bounded ring of per-step records (plain JSON-able dicts).

    Args:
      capacity: ring bound in steps.  The default (4096) keeps the
        last few minutes of a busy server for a few MB of host memory;
        a soak that wants the whole run sizes it to its iteration
        count.
    """

    enabled = True

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring = deque(maxlen=self.capacity)
        self._recorded = 0

    def record(self, rec: Dict[str, Any]) -> None:
        """Append one step record (newest wins when the ring is
        full)."""
        self._recorded += 1
        self._ring.append(rec)

    @property
    def steps_recorded(self) -> int:
        """Steps recorded since construction or :meth:`clear` —
        including those the ring has since evicted."""
        return self._recorded

    @property
    def dropped(self) -> int:
        """Records evicted by the ring bound."""
        return self._recorded - len(self._ring)

    def records(self) -> Tuple[Dict[str, Any], ...]:
        return tuple(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self._recorded = 0

    def dump_jsonl(self, path: str) -> str:
        """Write the ring as JSON lines (oldest first); returns
        ``path``."""
        with open(path, "w") as f:
            for rec in self._ring:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        return path


def write_postmortem(dirpath: str, *, recorder, registry=None,
                     tracer=None, reason: str = "on_demand",
                     extra: Optional[Dict[str, Any]] = None,
                     journeys: Optional[Dict[str, Any]] = None) -> dict:
    """Write a postmortem bundle into ``dirpath`` (created if needed)
    and return its manifest dict.

    A bundle is four files that cross-reconcile
    (``tools/postmortem.py --assert-complete``):

    - ``flight.jsonl`` — the recorder ring, one step record per line;
    - ``metrics.json`` — ``registry.snapshot()`` at dump time (``{}``
      without a registry);
    - ``trace.json`` — the tracer's Chrome trace (an empty but valid
      trace when tracing is off, so every bundle parses the same way);
    - ``manifest.json`` — ``reason``, step accounting
      (``steps_recorded`` / ``steps_in_bundle`` / ``steps_dropped``),
      the member file names, and any caller ``extra`` (chaos injection
      counts, the violated invariant, ...).

    ``journeys`` (``observability.journey.dump_journeys`` output)
    adds a FIFTH, optional member — ``journeys.json`` — and its
    manifest ``files`` entry.  Journey-less bundles keep the legacy
    four-file shape byte-for-byte, so ``tools/postmortem.py
    --assert-complete`` gates old and new bundles identically.
    """
    os.makedirs(dirpath, exist_ok=True)
    recorder.dump_jsonl(os.path.join(dirpath, FLIGHT_NAME))
    snapshot = registry.snapshot() if registry is not None else {}
    with open(os.path.join(dirpath, METRICS_NAME), "w") as f:
        json.dump(snapshot, f, sort_keys=True)
        f.write("\n")
    trace_path = os.path.join(dirpath, TRACE_NAME)
    if tracer is not None and tracer.enabled:
        tracer.export_chrome(trace_path)
    else:
        with open(trace_path, "w") as f:
            json.dump({"traceEvents": [], "displayTimeUnit": "ms"}, f)
            f.write("\n")
    files = {"flight": FLIGHT_NAME, "metrics": METRICS_NAME,
             "trace": TRACE_NAME}
    if journeys is not None:
        with open(os.path.join(dirpath, JOURNEYS_NAME), "w") as f:
            json.dump(journeys, f, sort_keys=True)
            f.write("\n")
        files["journeys"] = JOURNEYS_NAME
    manifest = {
        "reason": reason,
        "steps_recorded": recorder.steps_recorded,
        "steps_in_bundle": len(recorder.records()),
        "steps_dropped": recorder.dropped,
        "files": files,
    }
    if extra:
        manifest["extra"] = extra
    with open(os.path.join(dirpath, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, sort_keys=True, indent=2)
        f.write("\n")
    return manifest
