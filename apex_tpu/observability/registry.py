"""Process-wide metrics: named counters, gauges, log-bucketed histograms.

The reference has no metrics story at all (nvtx ranges and
``cudaProfilerStart/Stop`` are its whole observability surface); what
this repo had grown — serving throughput/failure counters, checkpoint
accounting, loss-scale state — lived in per-subsystem ad-hoc meters
with no shared registry and no latency distributions.  This module is
the shared substrate:

- :class:`Counter` — monotonic (negative increments raise), optionally
  labeled.
- :class:`Gauge` — sampled level with current/peak/running-mean, the
  semantics ``utils.GaugeMeter`` always had.
- :class:`HistogramMeter` — log-bucketed latency distribution.  Bucket
  boundaries are a geometric ladder (``low * growth**i`` capped at
  ``high``); assignment is a ``bisect`` over the precomputed boundary
  list, so the math is numpy-free, deterministic, and trivially
  oracle-checkable.  Quantiles (:meth:`~HistogramMeter.quantile`,
  ``p50``/``p90``/``p99``) interpolate rank position within the
  bucket and clamp to the exact observed min/max.  The clock used by
  :meth:`~HistogramMeter.time` is injectable for deterministic tests.
- :class:`MetricsRegistry` — get-or-create by ``(name, labels)`` with
  kind checking, :meth:`~MetricsRegistry.snapshot` /
  :func:`snapshot_diff` semantics, JSON-lines emission
  (:meth:`~MetricsRegistry.emit_jsonl`) and Prometheus text-format
  exposition (:meth:`~MetricsRegistry.prometheus_text`).

The existing ``apex_tpu.utils`` meters (``CounterMeter`` /
``GaugeMeter``) become views onto these metrics when constructed with
a ``registry=`` — their public behavior is unchanged
(``docs/observability.md``).
"""

from __future__ import annotations

import bisect
import contextlib
import json
import math
import threading
import time
from typing import Any, Dict, Iterable, Optional, Tuple

LabelItems = Tuple[Tuple[str, str], ...]

# the exposition-format content type an HTTP scrape must be served
# under (Prometheus content negotiation keys on the version token;
# bare "text/plain" is parsed by some scrapers and rejected by
# others).  One definition, shared by the ops plane's /metrics
# endpoint and the conformance tests.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _label_items(labels: Dict[str, Any]) -> LabelItems:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping (v0.0.4): backslash,
    double quote, and newline must be escaped or the exposition line is
    unparseable — a label value carrying a path or an error message
    would otherwise corrupt the whole scrape."""
    return (value.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def series_key(name: str, labels: LabelItems = ()) -> str:
    """Prometheus-style series identity: ``name{k="v",...}`` with
    labels sorted and values escaped (``name`` alone when unlabeled) —
    the snapshot / diff / exposition key."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter.  ``incr`` only counts up — a snapshot taken
    later always dominates one taken earlier, which is what log
    scrapers and :func:`snapshot_diff` rely on."""

    __slots__ = ("name", "labels", "_value")
    kind = "counter"

    def __init__(self, name: str, labels: LabelItems = ()):
        self.name = name
        self.labels = labels
        self._value = 0

    def incr(self, n: int = 1) -> int:
        if n < 0:
            raise ValueError(
                f"counter {series_key(self.name, self.labels)} is "
                f"monotonic; incr({n}) would decrease it")
        self._value += n
        return self._value

    @property
    def value(self) -> int:
        return self._value

    def describe(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self._value}


class Gauge:
    """Current / peak / running-mean of a sampled level (the serving
    queue-depth and batch-occupancy semantics)."""

    __slots__ = ("name", "labels", "val", "peak", "sum", "count")
    kind = "gauge"

    def __init__(self, name: str, labels: LabelItems = ()):
        self.name = name
        self.labels = labels
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.peak = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val) -> None:
        val = float(val)
        self.val = val
        self.peak = max(self.peak, val)
        self.sum += val
        self.count += 1

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)

    def describe(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self.val, "peak": self.peak,
                "avg": self.avg, "count": self.count}


class HistogramMeter:
    """Log-bucketed value distribution with interpolated quantiles.

    ``bounds[i]`` is bucket ``i``'s inclusive upper edge; bucket 0
    holds everything ``<= low`` and the last bucket everything above
    ``high`` (clamped, never dropped).  Boundaries grow geometrically
    by ``growth`` per bucket, so relative resolution is constant
    across five-plus decades of latency for a few dozen integer
    counts — no samples retained, O(1) record, numpy-free.

    Defaults suit second-denominated latencies: 1us .. 60s at 2x
    resolution (~26 buckets).
    """

    __slots__ = ("name", "labels", "bounds", "_counts", "count", "sum",
                 "min", "max", "_clock")
    kind = "histogram"

    def __init__(self, name: str = "histogram", labels: LabelItems = (),
                 *, low: float = 1e-6, high: float = 60.0,
                 growth: float = 2.0, clock=time.perf_counter):
        if low <= 0 or high <= low:
            raise ValueError(
                f"need 0 < low < high, got low={low} high={high}")
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        self.name = name
        self.labels = labels
        bounds = [float(low)]
        while bounds[-1] < high:
            bounds.append(bounds[-1] * growth)
        self.bounds: Tuple[float, ...] = tuple(bounds)
        self._clock = clock
        self.reset()

    def reset(self) -> None:
        self._counts = [0] * len(self.bounds)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def bucket_index(self, value) -> int:
        """The bucket ``value`` lands in: smallest ``i`` with
        ``value <= bounds[i]``, clamped into the ladder."""
        return min(bisect.bisect_left(self.bounds, float(value)),
                   len(self.bounds) - 1)

    def record(self, value) -> None:
        value = float(value)
        self._counts[self.bucket_index(value)] += 1
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)

    @contextlib.contextmanager
    def time(self):
        """``with hist.time(): ...`` records the block's wall time on
        the injected clock."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.record(self._clock() - t0)

    @property
    def bucket_counts(self) -> Tuple[int, ...]:
        return tuple(self._counts)

    def quantile(self, q: float) -> float:
        """Estimated ``q``-quantile: find the bucket holding the target
        rank, interpolate the rank's position linearly between the
        bucket's edges, clamp into the exact observed [min, max].  By
        construction the estimate lands in the same bucket as the true
        quantile."""
        if self.count == 0:
            return 0.0
        q = min(max(float(q), 0.0), 1.0)
        target = q * self.count
        cum = 0
        for i, c in enumerate(self._counts):
            if c == 0:
                continue
            cum += c
            if cum >= target:
                lo = self.bounds[i - 1] if i else 0.0
                hi = self.bounds[i]
                frac = (target - (cum - c)) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
        return self.max

    @property
    def p50(self) -> float:
        return self.quantile(0.50)

    @property
    def p90(self) -> float:
        return self.quantile(0.90)

    @property
    def p99(self) -> float:
        return self.quantile(0.99)

    @property
    def mean(self) -> float:
        return self.sum / max(self.count, 1)

    def describe(self) -> Dict[str, Any]:
        out = {"type": "histogram", "count": self.count,
               "sum": self.sum}
        if self.count:
            out.update(min=self.min, max=self.max, mean=self.mean,
                       p50=self.p50, p90=self.p90, p99=self.p99)
        return out


class MetricsRegistry:
    """Get-or-create metric store keyed on ``(name, labels)``.

    One ``name`` is one kind for the registry's lifetime (reusing a
    counter name as a gauge raises).  ``snapshot()`` returns a plain
    JSON-able dict — series key to :meth:`describe` dict — and
    :func:`snapshot_diff` turns two snapshots into per-series deltas.
    ``clock`` stamps JSON-lines records (injectable for deterministic
    emission tests); metric-internal clocks are per-histogram.
    """

    def __init__(self, clock=time.time):
        self._clock = clock
        self._metrics: Dict[Tuple[str, LabelItems], Any] = {}
        self._kinds: Dict[str, str] = {}
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()

    def set_help(self, name: str, text: str) -> None:
        """Help text for metric family ``name``, emitted as the
        family's ``# HELP`` line by :meth:`prometheus_text` (a default
        is synthesized when unset)."""
        self._help[name] = str(text)

    # -- get-or-create ----------------------------------------------------

    def _get(self, kind: str, name: str, labels: Dict[str, Any],
             factory):
        key = (name, _label_items(labels))
        with self._lock:
            have = self._kinds.setdefault(name, kind)
            if have != kind:
                raise ValueError(
                    f"metric {name!r} is already registered as a "
                    f"{have}, not a {kind}")
            m = self._metrics.get(key)
            if m is None:
                m = factory(name, key[1])
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str, *, low: float = 1e-6,
                  high: float = 60.0, growth: float = 2.0,
                  clock=time.perf_counter, **labels) -> HistogramMeter:
        return self._get(
            "histogram", name, labels,
            lambda n, li: HistogramMeter(n, li, low=low, high=high,
                                         growth=growth, clock=clock))

    def metrics(self) -> Iterable:
        with self._lock:
            return list(self._metrics.values())

    # -- snapshot / diff --------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """``{series_key: describe-dict}`` over every registered
        metric — plain data, safe to json.dump or diff later."""
        return {series_key(m.name, m.labels): m.describe()
                for m in self.metrics()}

    def emit_jsonl(self, path_or_file, *,
                   extra: Optional[Dict[str, Any]] = None) -> None:
        """Append one ``{"ts": ..., "metrics": snapshot}`` JSON line —
        the scrape format ``tools/obs_dump.py`` pretty-prints."""
        record = {"ts": self._clock(), "metrics": self.snapshot()}
        if extra:
            record.update(extra)
        line = json.dumps(record, sort_keys=True)
        if hasattr(path_or_file, "write"):
            path_or_file.write(line + "\n")
        else:
            with open(path_or_file, "a") as f:
                f.write(line + "\n")

    # -- exposition -------------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text exposition (v0.0.4): counters and gauges as
        single series, histograms as cumulative ``_bucket{le=...}`` +
        ``_sum`` / ``_count`` families.  Each family gets exactly one
        ``# HELP`` and one ``# TYPE`` line (set text via
        :meth:`set_help`; a default is synthesized), and label values
        are escaped per the format spec — conformance is pinned by a
        line-parsing test in ``tests/L0/test_observability.py``.
        Serve this over HTTP under :data:`PROMETHEUS_CONTENT_TYPE`
        (the ops plane's ``/metrics`` endpoint does)."""
        by_name: Dict[str, list] = {}
        for m in self.metrics():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_name):
            kind = self._kinds[name]
            help_text = self._help.get(name, f"apex_tpu {kind} {name}")
            # HELP escaping differs from label values: only backslash
            # and newline (quotes are legal in help text)
            help_text = (help_text.replace("\\", r"\\")
                         .replace("\n", r"\n"))
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for m in sorted(by_name[name], key=lambda m: m.labels):
                if kind == "counter":
                    lines.append(
                        f"{series_key(name, m.labels)} {m.value}")
                elif kind == "gauge":
                    lines.append(
                        f"{series_key(name, m.labels)} {m.val}")
                else:
                    cum = 0
                    for bound, c in zip(m.bounds, m.bucket_counts):
                        cum += c
                        le = m.labels + (("le", repr(bound)),)
                        lines.append(
                            f"{series_key(name + '_bucket', le)} {cum}")
                    inf = m.labels + (("le", "+Inf"),)
                    lines.append(
                        f"{series_key(name + '_bucket', inf)} {m.count}")
                    lines.append(
                        f"{series_key(name + '_sum', m.labels)} {m.sum}")
                    lines.append(
                        f"{series_key(name + '_count', m.labels)} "
                        f"{m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def fleet_prometheus_text(sources) -> str:
    """Merged Prometheus exposition over several registries —
    ``sources`` is an iterable of ``(extra_labels, registry)`` pairs,
    each registry's series re-emitted with ``extra_labels`` prepended
    (the fleet passes ``{"replica": name}`` per replica and ``{}`` for
    the router's own registry).

    One exposition must carry exactly one ``# HELP``/``# TYPE`` pair
    per family, so naive concatenation of per-replica
    :meth:`MetricsRegistry.prometheus_text` outputs is malformed the
    moment two replicas share a metric name (they all do — each
    replica has a private registry with the same families).  This
    merges by family instead: the first registry to define a name
    wins the kind/help line, and every series gets its source's extra
    labels so identically-named per-replica series never collide.
    Served by the fleet ops plane's ``GET /metrics/fleet``."""
    fams: Dict[str, list] = {}
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for extra, reg in sources:
        items = _label_items(extra or {})
        for m in reg.metrics():
            if m.name not in kinds:
                kinds[m.name] = reg._kinds[m.name]
                helps[m.name] = reg._help.get(
                    m.name, f"apex_tpu {kinds[m.name]} {m.name}")
            fams.setdefault(m.name, []).append((items, m))
    lines = []
    for name in sorted(fams):
        kind = kinds[name]
        help_text = (helps[name].replace("\\", r"\\")
                     .replace("\n", r"\n"))
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for extra, m in sorted(fams[name],
                               key=lambda em: (em[0], em[1].labels)):
            labels = extra + m.labels
            if kind == "counter":
                lines.append(f"{series_key(name, labels)} {m.value}")
            elif kind == "gauge":
                lines.append(f"{series_key(name, labels)} {m.val}")
            else:
                cum = 0
                for bound, c in zip(m.bounds, m.bucket_counts):
                    cum += c
                    le = labels + (("le", repr(bound)),)
                    lines.append(
                        f"{series_key(name + '_bucket', le)} {cum}")
                inf = labels + (("le", "+Inf"),)
                lines.append(
                    f"{series_key(name + '_bucket', inf)} {m.count}")
                lines.append(
                    f"{series_key(name + '_sum', labels)} {m.sum}")
                lines.append(
                    f"{series_key(name + '_count', labels)} "
                    f"{m.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_diff(old: Dict[str, Dict[str, Any]],
                  new: Dict[str, Dict[str, Any]],
                  ) -> Dict[str, Dict[str, Any]]:
    """Per-series delta between two :meth:`MetricsRegistry.snapshot`
    readings taken new-after-old: counters and histogram count/sum
    report the increment, gauges report the newer value.  Series
    absent from ``old`` diff against zero.

    A monotonic value that went *backwards* between the readings means
    the metric was reset in between (``reset_meters()`` after a warmup
    window, a histogram ``reset()``) — the pre-reset history is gone,
    so the increment since the reset is at most the new value.  The
    diff CLAMPS to that (``delta = new value``, counting from zero)
    and flags the series with ``"reset": True`` instead of raising, so
    a windowed measurement across a reset degrades to an explicit
    partial answer rather than an exception.  (Passing the snapshots
    in the wrong order produces the same signature — every monotonic
    series flagged — which the flag makes visible rather than
    silently negative.)"""
    out: Dict[str, Dict[str, Any]] = {}
    for key, desc in new.items():
        prev = old.get(key, {})
        kind = desc["type"]
        if kind == "counter":
            delta = desc["value"] - prev.get("value", 0)
            if delta < 0:
                out[key] = {"type": "counter", "delta": desc["value"],
                            "reset": True}
            else:
                out[key] = {"type": "counter", "delta": delta}
        elif kind == "histogram":
            dc = desc["count"] - prev.get("count", 0)
            if dc < 0:
                out[key] = {"type": "histogram",
                            "count_delta": desc["count"],
                            "sum_delta": desc["sum"], "reset": True}
            else:
                out[key] = {"type": "histogram", "count_delta": dc,
                            "sum_delta": desc["sum"]
                            - prev.get("sum", 0.0)}
        else:
            d = {"type": "gauge", "value": desc["value"]}
            # a gauge's cumulative sample count only moves backwards
            # on reset — flag it so avg/peak readers know the window
            # restarted
            if desc.get("count", 0) < prev.get("count", 0):
                d["reset"] = True
            out[key] = d
    return out
