"""Hang/stall watchdog for the serving step loop.

Everything observability had until now — metrics, traces, the flight
recorder — only works while the loop keeps *running*.  A wedged
engine (a device hang, a deadlocked host thread, an engine call that
never returns) produces the one failure mode none of it can report:
silence.  This module is the dead-man's switch:

- :class:`HangWatchdog` — a daemon thread fed step-loop heartbeats by
  ``InferenceServer.step()`` (``step_started`` / ``step_finished``,
  plain attribute stores on the hot path).  It declares a stall when
  either (a) a step has been *in flight* longer than ``deadline_s``
  (hung inside an engine call), or (b) the last completed step left
  work pending and no new step started within ``deadline_s`` (the
  loop itself died).  An idle server — no step in flight, no work
  pending — is never a stall: a front door with no traffic is healthy
  silence, not a hang.  Detection is one-shot per stall (latched
  until the next completed step clears it), so a single hang fires
  exactly once no matter how long it lasts.
- On a stall the server-installed handler dumps every thread's stack
  (:mod:`faulthandler`) plus a postmortem bundle through the PR-7
  machinery, flips ``/healthz`` to 503, and increments the
  ``serving_watchdog_stalls`` counter — the black box is preserved
  *by the watchdog thread* while the serve thread is still stuck in
  whatever wedged it.
- :data:`NULL_WATCHDOG` — the disabled default, the
  ``NULL_FLIGHT_RECORDER`` pattern: the step loop guards heartbeats
  on ``watchdog.enabled``, so the disabled path adds zero work and
  zero allocations per step (tracemalloc-pinned).

``poll_interval_s=None`` runs no thread at all — tests drive
:meth:`check` directly on an injected clock, so stall detection is
provable without sleeping.  The chaos build-matrix soak runs with the
watchdog armed on the real clock; a healthy soak must never fire it
(asserted by :func:`resilience.chaos.run_soak`).  See
``docs/observability.md``, "Ops plane & watchdog".
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional


class NullWatchdog:
    """The disabled watchdog: heartbeats are guarded out by
    ``enabled`` and every hook is a no-op."""

    enabled = False
    stalled = False
    stalls = 0
    deadline_s = None

    def step_started(self) -> None:
        pass

    def step_finished(self, has_work: bool = False) -> None:
        pass

    def check(self, now: Optional[float] = None) -> bool:
        return False

    def start(self) -> "NullWatchdog":
        return self

    def stop(self) -> None:
        pass


NULL_WATCHDOG = NullWatchdog()


class HangWatchdog:
    """Step-loop heartbeat monitor with one-shot stall detection.

    Args:
      deadline_s: no-progress budget — a step in flight (or pending
        work with no step starting) for longer than this is a stall.
        Size it to worst-case legitimate step time with margin: a
        first-call compile is the slowest healthy "step" a server
        ever runs.
      poll_interval_s: the watchdog thread's check cadence (default
        ``min(1, deadline_s / 4)``).  ``None`` = no thread; the owner
        calls :meth:`check` itself (deterministic tests).
      clock: injectable monotonic-seconds source.
      on_stall: ``callable(info_dict)`` run on the watchdog thread at
        detection (``InferenceServer`` installs its own handler:
        thread-stack dump + postmortem bundle + stall counter).  A
        raising handler is reported to stderr, never propagated — the
        watchdog must not take the process down.
    """

    enabled = True

    def __init__(self, deadline_s: float = 30.0, *,
                 poll_interval_s: Optional[float] = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_stall: Optional[Callable[[dict], None]] = None):
        if deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.poll_interval_s = (
            None if poll_interval_s is None
            else min(float(poll_interval_s), self.deadline_s / 4))
        self._clock = clock
        self.on_stall = on_stall
        self.stalls = 0
        self.stalled = False
        self._in_step = False
        self._step_started_at: Optional[float] = None
        self._last_progress: Optional[float] = None
        self._pending = False
        self._fired = False          # latched: one detection per stall
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # -- hot-path heartbeats (serve thread; attribute stores only) --------

    def step_started(self) -> None:
        self._step_started_at = self._clock()
        self._in_step = True

    def step_finished(self, has_work: bool = False) -> None:
        """One step completed: record progress, note whether the loop
        is obligated to step again (``has_work``), and clear any
        latched stall — the loop is demonstrably moving again."""
        now = self._clock()
        self._in_step = False
        self._last_progress = now
        self._pending = bool(has_work)
        if self._fired:
            self._fired = False
            self.stalled = False

    # -- detection (watchdog thread, or tests directly) --------------------

    def check(self, now: Optional[float] = None) -> bool:
        """One watchdog evaluation; True exactly when a NEW stall is
        declared (the handler has already run by then)."""
        if self._fired:
            return False             # latched until progress resumes
        if now is None:
            now = self._clock()
        if self._in_step:
            mark, where = self._step_started_at, "in_step"
        elif self._pending:
            mark, where = self._last_progress, "between_steps"
        else:
            return False             # idle: silence is healthy
        if mark is None or now - mark < self.deadline_s:
            return False
        self._fired = True
        self.stalled = True
        self.stalls += 1
        info = {"where": where,
                "age_s": round(now - mark, 3),
                "deadline_s": self.deadline_s,
                "stalls": self.stalls}
        if self.on_stall is not None:
            try:
                self.on_stall(info)
            except Exception as e:   # noqa: BLE001 — never kill the dog
                print(f"apex_tpu watchdog: on_stall handler failed: "
                      f"{e!r}", file=sys.stderr)
        return True

    # -- thread lifecycle ---------------------------------------------------

    def start(self) -> "HangWatchdog":
        """Spawn the daemon check thread (no-op in manual mode or if
        already running); returns self for chaining."""
        if self.poll_interval_s is None or self._thread is not None:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="apex-tpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop_evt.wait(self.poll_interval_s):
            self.check()

    def stop(self) -> None:
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
