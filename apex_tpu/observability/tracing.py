"""In-process span tracer with Chrome trace-event export.

Answers "where did this request / this step spend its time" — the
question xprof annotations (``utils/profiling.py``) can't, because
they only label ops *inside* compiled programs.  This tracer lives on
the host side of the step loop: scheduler phases (admit / prefix-match
/ chunk-prefill / decode / evict / preempt), engine compile events,
checkpoint save/restore/publish, and the amp step all record spans
here, and the export is Chrome trace-event JSON that loads directly in
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Design points:

- **Zero overhead when disabled.**  The process default is
  :data:`NULL_TRACER`, whose ``span()`` returns one shared no-op
  context-manager singleton and whose ``instant()`` does nothing —
  nothing is allocated or recorded per event, and hot paths can
  additionally guard on ``tracer.enabled``.  Tracing turns on via
  ``APEX_TPU_TRACE=/path/trace.json`` (exported at process exit) or
  :func:`enable_tracing` / :func:`set_tracer`.
- **Bounded memory.**  Events land in a ring buffer
  (``deque(maxlen=capacity)``); a long-running server keeps the most
  recent window and reports how many events rolled off
  (:attr:`SpanTracer.dropped`).
- **Monotonic, injectable clock.**  Timestamps come from
  ``time.perf_counter`` relative to tracer construction (exported in
  microseconds, the Chrome ``ts`` unit); tests inject a fake clock
  for deterministic output.
- **Span / parent ids.**  Spans nest per thread (a thread-local
  stack); every B/instant event carries ``span_id`` and, when nested,
  ``parent_id`` in its ``args``, so request flows reconstruct even
  outside the viewer.

See ``docs/observability.md`` for the instrumented span names and a
Perfetto walkthrough.
"""

from __future__ import annotations

import atexit
import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Optional

TRACE_ENV = "APEX_TPU_TRACE"


class _NullSpan:
    """The shared do-nothing context manager ``NullTracer.span``
    returns — one instance per process, never one per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The disabled tracer: every operation is a no-op and allocates
    nothing per event (``span()`` hands back the one module-level
    :class:`_NullSpan`)."""

    enabled = False
    events = ()
    dropped = 0

    def span(self, name, **args):
        return _NULL_SPAN

    def begin(self, name, **args):
        return 0

    def end(self):
        pass

    def instant(self, name, **args):
        pass

    def clear(self):
        pass

    def chrome_events(self):
        return []

    def export_chrome(self, path):
        return None


NULL_TRACER = NullTracer()


class SpanTracer:
    """Recording tracer: bounded ring buffer of span/instant events.

    Args:
      capacity: ring-buffer bound (events past it evict the oldest;
        :attr:`dropped` counts them).
      clock: monotonic seconds source (injectable for determinism).
      pid: the ``pid`` stamped on exported events (defaults to the
        real process id).
    """

    enabled = True

    def __init__(self, capacity: int = 1 << 16,
                 clock=time.perf_counter, pid: Optional[int] = None):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.capacity = int(capacity)
        self._clock = clock
        self._t0 = clock()
        self._events = deque(maxlen=self.capacity)
        self._appended = 0
        self._ids = itertools.count(1)
        self._local = threading.local()
        self.pid = os.getpid() if pid is None else int(pid)

    # -- recording --------------------------------------------------------

    def _ts_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, ev) -> None:
        self._appended += 1
        self._events.append(ev)

    def begin(self, name: str, **args) -> int:
        """Open a span; returns its id.  Prefer :meth:`span` — begin/
        end must pair up per thread or the B/E nesting breaks."""
        sid = next(self._ids)
        st = self._stack()
        parent = st[-1][0] if st else 0
        st.append((sid, name))
        self._push(("B", name, self._ts_us(), threading.get_ident(),
                    sid, parent, args or None))
        return sid

    def end(self) -> None:
        """Close the current thread's innermost open span."""
        st = self._stack()
        sid, name = st.pop() if st else (0, None)
        self._push(("E", name, self._ts_us(), threading.get_ident(),
                    sid, 0, None))

    def span(self, name: str, **args):
        """``with tracer.span("decode", batch=4): ...``"""
        return _span_ctx(self, name, args)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (Chrome ``ph="i"``) — compile
        events, preemptions, request lifecycle edges."""
        st = self._stack()
        parent = st[-1][0] if st else 0
        self._push(("i", name, self._ts_us(), threading.get_ident(),
                    next(self._ids), parent, args or None))

    def clear(self) -> None:
        self._events.clear()
        self._appended = 0

    # -- introspection / export -------------------------------------------

    @property
    def events(self):
        return tuple(self._events)

    @property
    def dropped(self) -> int:
        """Events evicted by the ring buffer since construction or
        :meth:`clear`."""
        return self._appended - len(self._events)

    def chrome_events(self):
        """The buffer as Chrome trace-event dicts: ``ph`` B/E/i,
        ``ts`` in microseconds, ``pid``/``tid``, span/parent ids in
        ``args``."""
        out = []
        for ph, name, ts, tid, sid, parent, args in self._events:
            ev = {"ph": ph, "ts": round(ts, 3), "pid": self.pid,
                  "tid": tid}
            if name is not None:
                ev["name"] = name
            if ph != "E":
                a = {"span_id": sid}
                if parent:
                    a["parent_id"] = parent
                if args:
                    a.update(args)
                ev["args"] = a
            if ph == "i":
                ev["s"] = "t"       # thread-scoped instant
            out.append(ev)
        return out

    def export_chrome(self, path: str) -> str:
        """Write the buffer as a Chrome/Perfetto-loadable JSON trace;
        returns ``path``."""
        data = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "apex_tpu.observability",
                          "dropped_events": self.dropped},
        }
        with open(path, "w") as f:
            json.dump(data, f)
            f.write("\n")
        return path


class _span_ctx:
    """Reentrant-per-call span context manager (one tiny object per
    *enabled* span; the disabled path never reaches here)."""

    __slots__ = ("_tracer", "_name", "_args")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._tracer.begin(self._name, **(self._args or {}))
        return self

    def __exit__(self, *exc):
        self._tracer.end()
        return False


# -- process default -------------------------------------------------------

_tracer = None


def _export_at_exit(tracer: SpanTracer, path: str) -> None:
    try:
        tracer.export_chrome(path)
    except OSError:
        pass                        # never fail interpreter shutdown


def get_tracer():
    """The process tracer.  First call resolves it: a recording
    :class:`SpanTracer` exporting to ``$APEX_TPU_TRACE`` at exit when
    that env var names a path, else :data:`NULL_TRACER`."""
    global _tracer
    if _tracer is None:
        path = os.environ.get(TRACE_ENV)
        if path:
            _tracer = SpanTracer()
            atexit.register(_export_at_exit, _tracer, path)
        else:
            _tracer = NULL_TRACER
    return _tracer


def set_tracer(tracer):
    """Install ``tracer`` as the process tracer; returns the previous
    one (which may be None if never resolved) so tests can restore
    it."""
    global _tracer
    prev, _tracer = _tracer, tracer
    return prev


def enable_tracing(path: Optional[str] = None, *,
                   capacity: int = 1 << 16,
                   clock=time.perf_counter) -> SpanTracer:
    """Install and return a recording process tracer; with ``path``,
    also export there at interpreter exit (the programmatic twin of
    ``APEX_TPU_TRACE``)."""
    tracer = SpanTracer(capacity=capacity, clock=clock)
    set_tracer(tracer)
    if path:
        atexit.register(_export_at_exit, tracer, path)
    return tracer
